"""dynlint visitor engine.

One pass per file: a pre-pass (`ModuleIndex`) collects import aliases,
threading/asyncio lock bindings, jitted-callable bindings, and Pallas
kernel names anywhere in the module, so rules can resolve
`t.sleep` → `time.sleep` or `self._lock` → threading.Lock without
executing anything. The main traversal (`_Engine`) maintains the
function/loop/lock/timeout stacks and dispatches structured events to
the active rules. Rules never walk the tree themselves except within
the node they were handed.

Suppression: a trailing `# dynlint: disable=RULE[,RULE...]` comment
silences those rules on that line (bare `disable` silences all);
`# dynlint: disable-file=RULE` anywhere silences a rule for the whole
file. Suppressions are deliberate, reviewable markers — prefer them to
baseline entries for true-but-accepted findings.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "Violation",
    "Rule",
    "LintContext",
    "FunctionScope",
    "lint_file",
    "lint_paths",
    "default_rules",
    "format_human",
    "format_json",
    "load_baseline",
    "baseline_counts",
    "diff_against_baseline",
]

_SUPPRESS_RE = re.compile(
    r"#\s*dynlint:\s*(disable-file|disable)\s*(?:=\s*([A-Za-z0-9_\-,\s]+))?"
)

# names whose assignment marks a threading-plane lock (held across await
# = whole-loop stall) vs an asyncio lock (fine to hold across await)
_THREAD_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
_ASYNC_LOCK_CTORS = {
    "asyncio.Lock", "asyncio.Condition", "asyncio.Semaphore",
    "asyncio.BoundedSemaphore",
}


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def key(self) -> str:
        """Baseline key: rule + path, no line numbers — unrelated edits
        above a legacy finding must not turn it into a 'new' one."""
        return f"{self.rule}:{self.path}"

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclass
class JitBinding:
    """A name bound to a jitted callable: `x = jax.jit(f, ...)` or
    `self._jit_x = _family("x", jax.jit(f, ...))`."""

    name: str  # bare name or attribute name (for self.<attr> bindings)
    static_names: Set[str] = field(default_factory=set)
    static_pos: Set[int] = field(default_factory=set)
    inner_params: List[str] = field(default_factory=list)  # empty if unknown


@dataclass
class FunctionScope:
    node: ast.AST
    name: str
    is_async: bool
    params: List[str] = field(default_factory=list)
    jit_static: Optional[Set[str]] = None  # set => function is traced
    is_kernel: bool = False

    @property
    def is_traced(self) -> bool:
        return self.jit_static is not None or self.is_kernel


class ModuleIndex(ast.NodeVisitor):
    """Whole-module pre-pass: aliases, lock bindings, jit bindings,
    kernel functions, top-level defs, module-level mutables."""

    def __init__(self) -> None:
        self.aliases: Dict[str, str] = {}
        self.lock_names: Set[str] = set()
        self.lock_attrs: Set[str] = set()
        self.async_lock_names: Set[str] = set()
        self.async_lock_attrs: Set[str] = set()
        self.jit_bindings: Dict[str, JitBinding] = {}
        self.kernel_fns: Set[str] = set()
        self.top_defs: Dict[str, ast.AST] = {}
        self.module_mutables: Dict[str, int] = {}

    # -- alias helpers ----------------------------------------------------
    def resolve(self, node: ast.AST) -> Optional[str]:
        """Dotted canonical name for a Name/Attribute chain, through
        import aliases; `self.x` resolves to "self.x"."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            if base is None:
                return None
            return f"{base}.{node.attr}"
        return None

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module is None or node.level:
            return  # relative imports keep their local names
        for a in node.names:
            if a.name == "*":
                continue
            self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    # -- binding collection -----------------------------------------------
    def _record_lock(self, target: ast.AST, value: ast.AST) -> None:
        if not isinstance(value, ast.Call):
            return
        ctor = self.resolve(value.func)
        if ctor in _THREAD_LOCK_CTORS:
            names, attrs = self.lock_names, self.lock_attrs
        elif ctor in _ASYNC_LOCK_CTORS:
            names, attrs = self.async_lock_names, self.async_lock_attrs
        else:
            return
        if isinstance(target, ast.Name):
            names.add(target.id)
        elif isinstance(target, ast.Attribute):
            attrs.add(target.attr)

    def _unwrap_jit_call(self, value: ast.AST) -> Optional[ast.Call]:
        """Return the inner `jax.jit(...)` Call for `jax.jit(...)` or a
        single-level wrapper like `_family("name", jax.jit(...))`."""
        if not isinstance(value, ast.Call):
            return None
        if self.resolve(value.func) in ("jax.jit", "jit"):
            return value
        for arg in value.args:
            if isinstance(arg, ast.Call) and self.resolve(arg.func) in (
                "jax.jit", "jit",
            ):
                return arg
        return None

    def _record_jit(self, target: ast.AST, value: ast.AST) -> None:
        call = self._unwrap_jit_call(value)
        if call is None:
            return
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute):
            name = target.attr
        else:
            return
        b = JitBinding(name)
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                b.static_names |= set(_string_elts(kw.value))
            elif kw.arg == "static_argnums":
                b.static_pos |= set(_int_elts(kw.value))
        if call.args:
            inner = call.args[0]
            fn_name = inner.id if isinstance(inner, ast.Name) else None
            fn = self.top_defs.get(fn_name) if fn_name else None
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                b.inner_params = [a.arg for a in fn.args.args]
        self.jit_bindings[name] = b

    def _record_jit_def(self, node) -> None:
        """@jax.jit / @partial(jax.jit, static_argnames=...) decorated
        defs are jit bindings too — their call sites look identical to
        assignment-form `f = jax.jit(...)` wrappers."""
        for dec in node.decorator_list:
            kws = []
            if self.resolve(dec) in ("jax.jit", "jit"):
                pass
            elif isinstance(dec, ast.Call):
                fn = self.resolve(dec.func)
                if fn in ("jax.jit", "jit"):
                    kws = dec.keywords
                elif (fn in ("functools.partial", "partial") and dec.args
                      and self.resolve(dec.args[0]) in ("jax.jit", "jit")):
                    kws = dec.keywords
                else:
                    continue
            else:
                continue
            b = JitBinding(node.name)
            for kw in kws:
                if kw.arg == "static_argnames":
                    b.static_names |= set(_string_elts(kw.value))
                elif kw.arg == "static_argnums":
                    b.static_pos |= set(_int_elts(kw.value))
            b.inner_params = [a.arg for a in node.args.args]
            self.jit_bindings[node.name] = b
            return

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._record_jit_def(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._record_jit_def(node)
        self.generic_visit(node)

    def _record_kernel(self, node: ast.Call) -> None:
        fn = self.resolve(node.func)
        if fn is None or not fn.endswith("pallas_call"):
            return
        args = list(node.args)
        for kw in node.keywords:
            if kw.arg in ("kernel", "f"):
                args.insert(0, kw.value)
        if args and isinstance(args[0], ast.Name):
            self.kernel_fns.add(args[0].id)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_lock(t, node.value)
            self._record_jit(t, node.value)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        self._record_kernel(node)
        self.generic_visit(node)

    def index_module(self, tree: ast.Module) -> None:
        # defs first so jit bindings can see inner-fn signatures
        for st in tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.top_defs[st.name] = st
            elif isinstance(st, ast.Assign) and len(st.targets) == 1:
                t = st.targets[0]
                if isinstance(t, ast.Name) and _is_mutable_literal(st.value):
                    self.module_mutables[t.id] = st.lineno
        self.visit(tree)


def _string_elts(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out: List[str] = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return out
    return []


def _int_elts(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        ]
    return []


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        fn = node.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        return name in ("dict", "list", "set", "defaultdict", "deque",
                        "OrderedDict", "Counter")
    return False


class LintContext:
    """Per-file state handed to every rule callback."""

    def __init__(self, path: str, tree: ast.Module, index: ModuleIndex,
                 suppress_lines: Dict[int, Set[str]],
                 suppress_file: Set[str]) -> None:
        self.path = path
        self.tree = tree
        self.index = index
        self._suppress_lines = suppress_lines
        self._suppress_file = suppress_file
        self.violations: List[Violation] = []
        # traversal stacks, maintained by the engine
        self.func_stack: List[FunctionScope] = []
        self.loop_depth = 0
        self.thread_lock_depth = 0
        self.async_lock_depth = 0
        self.timeout_depth = 0

    @property
    def any_lock_depth(self) -> int:
        return self.thread_lock_depth + self.async_lock_depth

    # -- state queries ----------------------------------------------------
    @property
    def func(self) -> Optional[FunctionScope]:
        return self.func_stack[-1] if self.func_stack else None

    @property
    def in_async(self) -> bool:
        f = self.func
        return bool(f and f.is_async)

    @property
    def at_module_level(self) -> bool:
        return not self.func_stack

    def resolve(self, node: ast.AST) -> Optional[str]:
        return self.index.resolve(node)

    def is_thread_lock(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Name):
            if expr.id in self.index.async_lock_names:
                return False
            return (expr.id in self.index.lock_names
                    or bool(re.search(r"(^|_)r?lock$", expr.id)))
        if isinstance(expr, ast.Attribute):
            if expr.attr in self.index.async_lock_attrs:
                return False
            return (expr.attr in self.index.lock_attrs
                    or bool(re.search(r"(^|_)r?lock$", expr.attr)))
        return False

    def is_async_lock(self, expr: ast.AST) -> bool:
        """Only meaningful under `async with` — asyncio locks are fine to
        hold across await, but still count as 'a lock in scope'."""
        if isinstance(expr, ast.Name):
            return (expr.id in self.index.async_lock_names
                    or bool(re.search(r"(^|_)r?lock$", expr.id)))
        if isinstance(expr, ast.Attribute):
            return (expr.attr in self.index.async_lock_attrs
                    or bool(re.search(r"(^|_)r?lock$", expr.attr)))
        return False

    # -- reporting --------------------------------------------------------
    def report(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 0)
        if rule in self._suppress_file or "*" in self._suppress_file:
            return
        sup = self._suppress_lines.get(line, ())
        if rule in sup or "*" in sup:
            return
        self.violations.append(
            Violation(rule, self.path, line,
                      getattr(node, "col_offset", 0), message)
        )


class Rule:
    """Base rule: override the hooks you need. `id` must be stable — it
    is the suppression token and the baseline key prefix."""

    id = "DYN-X000"
    description = ""

    def check_call(self, ctx: LintContext, node: ast.Call) -> None: ...
    def check_await(self, ctx: LintContext, node: ast.Await) -> None: ...
    def check_branch(self, ctx: LintContext, node: ast.AST) -> None: ...
    def check_expr_stmt(self, ctx: LintContext, node: ast.Expr) -> None: ...
    def check_assign(self, ctx: LintContext, node: ast.AST) -> None: ...
    def check_except(self, ctx: LintContext,
                     node: ast.ExceptHandler) -> None: ...
    def check_function(self, ctx: LintContext, scope: FunctionScope) -> None:
        ...
    def finish_module(self, ctx: LintContext) -> None: ...


class _Engine(ast.NodeVisitor):
    def __init__(self, ctx: LintContext, rules: Sequence[Rule]) -> None:
        self.ctx = ctx
        self.rules = rules

    def _each(self, hook: str, node: ast.AST) -> None:
        for r in self.rules:
            getattr(r, hook)(self.ctx, node)

    # -- functions --------------------------------------------------------
    def _function_scope(self, node) -> FunctionScope:
        idx = self.ctx.index
        params = [a.arg for a in node.args.args] + [
            a.arg for a in node.args.kwonlyargs
        ]
        static: Optional[Set[str]] = None
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = idx.resolve(target)
            if name in ("jax.jit", "jit"):
                static = set()
                if isinstance(dec, ast.Call):
                    for kw in dec.keywords:
                        if kw.arg == "static_argnames":
                            static |= set(_string_elts(kw.value))
                        elif kw.arg == "static_argnums":
                            for i in _int_elts(kw.value):
                                if i < len(params):
                                    static.add(params[i])
            elif name in ("functools.partial", "partial") and isinstance(
                dec, ast.Call
            ) and dec.args and idx.resolve(dec.args[0]) in ("jax.jit", "jit"):
                static = set()
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        static |= set(_string_elts(kw.value))
                    elif kw.arg == "static_argnums":
                        for i in _int_elts(kw.value):
                            if i < len(params):
                                static.add(params[i])
        return FunctionScope(
            node=node, name=node.name,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            params=params, jit_static=static,
            is_kernel=node.name in idx.kernel_fns,
        )

    def _visit_function(self, node) -> None:
        scope = self._function_scope(node)
        self.ctx.func_stack.append(scope)
        for r in self.rules:
            r.check_function(self.ctx, scope)
        saved_loop, self.ctx.loop_depth = self.ctx.loop_depth, 0
        self.generic_visit(node)
        self.ctx.loop_depth = saved_loop
        self.ctx.func_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.ctx.func_stack.append(
            FunctionScope(node=node, name="<lambda>", is_async=False,
                          params=[a.arg for a in node.args.args])
        )
        self.generic_visit(node)
        self.ctx.func_stack.pop()

    # -- loops ------------------------------------------------------------
    def _visit_loop(self, node) -> None:
        if isinstance(node, ast.While):
            self._each("check_branch", node)
        self.ctx.loop_depth += 1
        self.generic_visit(node)
        self.ctx.loop_depth -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop
    visit_While = _visit_loop

    # -- with blocks (lock / timeout tracking) ----------------------------
    def _with_kinds(self, node) -> Tuple[int, int, int]:
        locks = alocks = timeouts = 0
        is_async = isinstance(node, ast.AsyncWith)
        for item in node.items:
            expr = item.context_expr
            if isinstance(expr, ast.Call):
                name = self.ctx.resolve(expr.func)
                if name in ("asyncio.timeout", "asyncio.timeout_at",
                            "async_timeout.timeout"):
                    timeouts += 1
                continue  # `with Lock():` — fresh lock, not shared state
            if not is_async and self.ctx.is_thread_lock(expr):
                locks += 1
            elif is_async and self.ctx.is_async_lock(expr):
                alocks += 1
        return locks, alocks, timeouts

    def _visit_with(self, node) -> None:
        locks, alocks, timeouts = self._with_kinds(node)
        self.ctx.thread_lock_depth += locks
        self.ctx.async_lock_depth += alocks
        self.ctx.timeout_depth += timeouts
        self.generic_visit(node)
        self.ctx.thread_lock_depth -= locks
        self.ctx.async_lock_depth -= alocks
        self.ctx.timeout_depth -= timeouts

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # -- leaf events ------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        self._each("check_call", node)
        self.generic_visit(node)

    def visit_Await(self, node: ast.Await) -> None:
        self._each("check_await", node)
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        self._each("check_branch", node)
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        self._each("check_expr_stmt", node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self._each("check_assign", node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._each("check_assign", node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        self._each("check_except", node)
        self.generic_visit(node)


def _collect_suppressions(
    source: str,
) -> Tuple[Dict[int, Set[str]], Set[str]]:
    lines: Dict[int, Set[str]] = {}
    file_wide: Set[str] = set()
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = (
                {r.strip() for r in m.group(2).split(",") if r.strip()}
                if m.group(2) else {"*"}
            )
            if m.group(1) == "disable-file":
                file_wide |= rules
            else:
                lines.setdefault(tok.start[0], set()).update(rules)
    except tokenize.TokenError:
        pass
    return lines, file_wide


def default_rules() -> List[Rule]:
    from dynamo_tpu.lint.rules_async import ASYNC_RULES
    from dynamo_tpu.lint.rules_jax import JAX_RULES
    from dynamo_tpu.lint.rules_runtime import RUNTIME_RULES

    return [cls() for cls in (*ASYNC_RULES, *JAX_RULES, *RUNTIME_RULES)]


def lint_file(path: str, rules: Optional[Sequence[Rule]] = None,
              source: Optional[str] = None,
              rel_path: Optional[str] = None) -> List[Violation]:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Violation("DYN-E000", rel_path or path, e.lineno or 0,
                          e.offset or 0, f"syntax error: {e.msg}")]
    index = ModuleIndex()
    index.index_module(tree)
    sup_lines, sup_file = _collect_suppressions(source)
    ctx = LintContext(rel_path or path, tree, index, sup_lines, sup_file)
    active = list(rules) if rules is not None else default_rules()
    _Engine(ctx, active).visit(tree)
    for r in active:
        r.finish_module(ctx)
    ctx.violations.sort(key=lambda v: (v.line, v.col, v.rule))
    return ctx.violations


_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules", "build"}
_SKIP_FILE_RE = re.compile(r"_pb2(_grpc)?\.py$")

# bump when per-file rule semantics change: stale cached violations from
# an older rule set must not satisfy the gate
_CACHE_VERSION = 1


def _load_cache(cache_path: Optional[str]) -> Dict[str, Any]:
    if not cache_path or not os.path.exists(cache_path):
        return {}
    try:
        with open(cache_path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    from dynamo_tpu.lint.project import FACTS_VERSION

    if (data.get("version") != _CACHE_VERSION
            or data.get("facts_version") != FACTS_VERSION):
        return {}
    files = data.get("files", {})
    return files if isinstance(files, dict) else {}


def _save_cache(cache_path: str, files: Dict[str, Any]) -> None:
    from dynamo_tpu.lint.project import FACTS_VERSION

    tmp = f"{cache_path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": _CACHE_VERSION,
                       "facts_version": FACTS_VERSION,
                       "files": files}, f)
        os.replace(tmp, cache_path)
    except OSError:
        # the cache is an optimization; a read-only tree still lints
        try:
            os.unlink(tmp)
        except OSError:
            pass


def lint_paths(paths: Iterable[str],
               rules: Optional[Sequence[Rule]] = None,
               root: Optional[str] = None,
               project: bool = True,
               cache_path: Optional[str] = None,
               stats: Optional[Dict[str, int]] = None) -> List[Violation]:
    """Lint files/trees: the per-file rule pass plus (by default) the
    interprocedural project pass over everything collected
    (`dynamo_tpu/lint/project.py`).

    `cache_path` names an mtime+size-keyed JSON result cache: unchanged
    files reuse their per-file violations AND their extracted call-graph
    facts, so the project-wide pass stays cheap enough for tier-1 (only
    edited files re-parse; linking is pure dict work). The cache is only
    consulted for the default rule set — custom `rules` bypass it.

    `stats`, when given, is filled in place with `cache_hits` /
    `cache_misses` counts (misses include uncacheable runs), so the CLI
    can surface whether the gate actually ran warm.
    """
    from dynamo_tpu.lint.project import (
        extract_module_facts,
        project_violations,
    )

    cacheable = rules is None and cache_path is not None
    cache = _load_cache(cache_path) if cacheable else {}
    if stats is not None:
        stats.setdefault("cache_hits", 0)
        stats.setdefault("cache_misses", 0)
    out: List[Violation] = []
    facts: List[Dict[str, Any]] = []
    new_cache: Dict[str, Any] = {}
    for path in paths:
        if os.path.isfile(path):
            files = [path]
        else:
            files = []
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in _SKIP_DIRS
                )
                files.extend(
                    os.path.join(dirpath, f) for f in sorted(filenames)
                    if f.endswith(".py") and not _SKIP_FILE_RE.search(f)
                )
        for f in files:
            rel = os.path.relpath(f, root) if root else f
            try:
                st = os.stat(f)
                stamp = [st.st_mtime_ns, st.st_size]
            except OSError:
                stamp = None
            hit = cache.get(rel) if cacheable and stamp else None
            if hit is not None and hit.get("stamp") == stamp:
                vs = [Violation(**d) for d in hit["violations"]]
                mf = hit["facts"]
                if stats is not None:
                    stats["cache_hits"] += 1
            else:
                with open(f, encoding="utf-8") as fh:
                    source = fh.read()
                vs = lint_file(f, rules=rules, source=source, rel_path=rel)
                mf = extract_module_facts(rel, source) if project else None
                if stats is not None:
                    stats["cache_misses"] += 1
            out.extend(vs)
            if mf is not None:
                facts.append(mf)
            if cacheable and stamp:
                new_cache[rel] = {
                    "stamp": stamp,
                    "violations": [v.as_dict() for v in vs],
                    "facts": mf,
                }
    if project and facts:
        out.extend(project_violations(facts))
    if cacheable:
        _save_cache(cache_path, new_cache)
    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out


# -- output + baseline ----------------------------------------------------
def format_human(violations: Sequence[Violation]) -> str:
    return "\n".join(
        f"{v.path}:{v.line}:{v.col}: {v.rule} {v.message}"
        for v in violations
    )


def format_json(violations: Sequence[Violation]) -> str:
    return json.dumps([v.as_dict() for v in violations], indent=2)


def baseline_counts(violations: Sequence[Violation]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for v in violations:
        counts[v.key()] = counts.get(v.key(), 0) + 1
    return counts


def load_baseline(path: str) -> Dict[str, int]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts = data.get("counts", {})
    return {str(k): int(n) for k, n in counts.items()}


def diff_against_baseline(
    violations: Sequence[Violation], baseline: Dict[str, int],
) -> Tuple[List[Violation], Dict[str, int], Dict[str, int]]:
    """Split current violations into (new, regressed_keys, fixed_keys).

    A key regresses when its count exceeds the baseline; the *newest*
    (highest-line) findings for that key are reported as new, which is
    the best line-level attribution a count ratchet can give. Keys whose
    count dropped are 'fixed' — `--update-baseline` ratchets them down.
    """
    current = baseline_counts(violations)
    regressed: Dict[str, int] = {}
    fixed: Dict[str, int] = {}
    for key, n in current.items():
        base = baseline.get(key, 0)
        if n > base:
            regressed[key] = n - base
    for key, base in baseline.items():
        n = current.get(key, 0)
        if n < base:
            fixed[key] = base - n
    new: List[Violation] = []
    by_key: Dict[str, List[Violation]] = {}
    for v in violations:
        by_key.setdefault(v.key(), []).append(v)
    for key, extra in regressed.items():
        vs = sorted(by_key.get(key, []), key=lambda v: (v.line, v.col))
        new.extend(vs[-extra:])
    new.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return new, regressed, fixed
