"""dynlint interprocedural pass: a project-wide call graph with taint.

The per-file rules (rules_async / rules_jax / rules_runtime) only see
direct calls — `time.sleep` *inside* the `async def`, `.item()` *inside*
`_loop_once`. One helper hop hides the violation: the step loop calls
`self._readback()`, `_readback` calls `np.asarray`, and DYN-J005 is
blind. This module closes that hole with a second pass over the whole
lint scope:

1. **Facts extraction** (`extract_module_facts`) — one extra AST walk
   per file collecting, for every function: resolved call edges (with
   in-loop / awaited / bare-statement / locks-held context), direct
   blocking calls (the DYN-A001 catalog), direct sync file I/O, direct
   device→host sync forcers (the DYN-J005 catalog), ordered lock
   acquisitions, and whether the function is async or returns a spawned
   task. Facts are plain dicts so `lint_paths` can cache them per file,
   keyed by mtime.
2. **Linking** (`ProjectIndex`) — module names come from relative
   paths; call targets resolve through import aliases (including
   relative imports and one-hop re-exports like a package `__init__`
   forwarding `from pkg.impl import helper`), plain local names, and
   single-level `self.method` references.
3. **Taint + emission** (`project_violations`) — BFS taint from the
   blocking / host-sync seeds over reverse call edges, a transitive
   lock-acquisition relation, and the findings:

   - DYN-A001 / DYN-A002 at a call edge leaving an `async def` into a
     helper chain that (transitively) blocks / does file I/O,
   - DYN-J005 at an *in-loop* call edge leaving the engine step scope
     into a chain that forces a device sync (the interprocedural twin
     of the per-file rule),
   - DYN-J006 at any other call edge leaving the step scope into such
     a chain — the transfer still happens once per iteration, it is
     just hidden in a helper instead of being an explicit, auditable
     bulk `device_get` at the top level,
   - DYN-R007 for a cycle in the static lock-acquisition-order graph,
     including order established across modules through call edges made
     while a lock is held,
   - DYN-A006 for a coroutine (or spawned-task handle) created by
     calling a project `async def` as a bare statement — the coroutine
     is never awaited, so the body never runs; cross-module creation is
     the case per-file DYN-A004 cannot see,
   - DYN-A007 for a check-then-act span that crosses an `await`: an
     `if`/`while` test reads `self.x`, the guarded body suspends, and
     the same attribute is written after the suspension — any other
     coroutine scheduled during the await can invalidate the check
     (double-init, double-apply, lost update),
   - DYN-R008 for instance state written under a threading lock in one
     function but written lock-free from an `async def` elsewhere — the
     lock documents cross-thread sharing, so the unlocked async write
     races the locked writers.

Both atomicity rules double as *dynamic seeds*: `atomicity_hazards()`
exports the flagged sites (including suppressed ones — a suppression is
a claim of safety, which is exactly what a model checker should try to
refute) and `dynamo_tpu/mc` prioritizes those functions' yield points
when exploring interleavings (docs/concurrency.md).

Findings are ordinary `Violation`s and respect the same inline
suppression comments as the per-file rules, evaluated in the file where
the finding is reported (the call site, not the taint root).
"""

from __future__ import annotations

import ast
import re
from typing import Any, Dict, Iterable, List, Optional, Set, Tuple

from dynamo_tpu.lint.core import (
    ModuleIndex,
    Violation,
    _collect_suppressions,
)
from dynamo_tpu.lint.rules_async import _BLOCKING_CALLS
from dynamo_tpu.lint.shard_facts import extract_shard_facts

__all__ = [
    "extract_module_facts",
    "ProjectIndex",
    "project_violations",
    "atomicity_hazards",
    "module_name_for",
]

# bump to invalidate cached facts when the extraction schema changes
FACTS_VERSION = 3  # v3: sharding/layout facts ("shard", lint/shard_facts.py)

_LOCK_NAME_RE = re.compile(r"(^|_)r?lock$")

# direct device→host sync forcers (the DYN-J005 catalog): attribute
# calls by name, canonical dotted calls by resolved name
_SYNC_ATTRS = ("item", "tolist")
_SYNC_CALLS = ("numpy.asarray", "jax.device_get", "jax.block_until_ready")

_SPAWN_CALLS = ("asyncio.create_task", "asyncio.ensure_future")
_SPAWN_TAILS = (".create_task", ".ensure_future")

# J005/J006 step scope: the engine's per-iteration hot path
_HOT_PREFIXES = ("_run_decode", "_run_mixed", "_run_spec", "_run_prefill")

_MAX_CHAIN = 12  # taint-chain hop bound (also the re-export hop bound)

# collection mutators that count as a *write* to the receiving attribute
# for the atomicity facts (DYN-A007 / DYN-R008)
_MUTATORS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "update", "extend", "insert", "setdefault",
})


def module_name_for(rel_path: str) -> str:
    """Dotted module name for a lint-scope-relative path:
    `dynamo_tpu/lint/core.py` → `dynamo_tpu.lint.core`,
    `pkg/__init__.py` → `pkg`."""
    p = rel_path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [seg for seg in p.split("/") if seg]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class _ProjectModuleIndex(ModuleIndex):
    """ModuleIndex whose aliases also resolve relative imports, which
    the per-file index deliberately ignores (it has no module name)."""

    def __init__(self, module: str, is_pkg: bool) -> None:
        super().__init__()
        self._module = module
        self._is_pkg = is_pkg

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if not node.level:
            super().visit_ImportFrom(node)
            return
        # package the import is relative to: the module itself for
        # __init__.py, its parent otherwise; each extra level drops one
        parts = self._module.split(".") if self._module else []
        if not self._is_pkg:
            parts = parts[:-1]
        drop = node.level - 1
        if drop:
            parts = parts[:-drop] if drop < len(parts) else []
        base = ".".join(parts)
        if node.module:
            base = f"{base}.{node.module}" if base else node.module
        if not base:
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.aliases[a.asname or a.name] = f"{base}.{a.name}"


class _FactsVisitor(ast.NodeVisitor):
    """Single walk collecting per-function facts (see module docstring).
    Nested defs attribute their bodies to the innermost function."""

    def __init__(self, module: str, index: _ProjectModuleIndex) -> None:
        self.module = module
        self.index = index
        self.functions: Dict[str, Dict[str, Any]] = {}
        self._cls_stack: List[str] = []
        self._fn_stack: List[Dict[str, Any]] = []
        self._loop_depth: List[int] = []
        self._held: List[str] = []  # lock ids currently held (lexical)
        self._async_held = 0  # depth of `async with <asyncio lock>` scopes
        self._awaited: Set[int] = set()
        self._bare: Set[int] = set()

    # -- scope bookkeeping -------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._cls_stack.append(node.name)
        self.generic_visit(node)
        self._cls_stack.pop()

    def _visit_function(self, node) -> None:
        cls = self._cls_stack[-1] if self._cls_stack else None
        local = f"{cls}.{node.name}" if cls else node.name
        facts = {
            "name": node.name,
            "cls": cls,
            "line": node.lineno,
            "is_async": isinstance(node, ast.AsyncFunctionDef),
            "calls": [],
            "blocking": [],
            "file_io": [],
            "transfers": [],
            "acquires": [],
            "returns_spawn": False,
            "guards": [],   # check-then-act spans crossing an await (A007)
            "writes": [],   # self.attr writes w/ lock + async context (R008)
        }
        # nested defs (closures) keep attributing to the OUTER function:
        # their body runs, at the latest, when the outer scope calls them
        if not self._fn_stack:
            self.functions[local] = facts
            self._fn_stack.append(facts)
            self._loop_depth.append(0)
            self.generic_visit(node)
            self._loop_depth.pop()
            self._fn_stack.pop()
        else:
            self.generic_visit(node)

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def _visit_loop(self, node) -> None:
        if self._loop_depth:
            self._loop_depth[-1] += 1
        self.generic_visit(node)
        if self._loop_depth:
            self._loop_depth[-1] -= 1

    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    def visit_While(self, node: ast.While) -> None:
        self._check_guard(node)
        self._visit_loop(node)

    def visit_If(self, node: ast.If) -> None:
        self._check_guard(node)
        self.generic_visit(node)

    # -- atomicity facts (DYN-A007 / DYN-R008) ------------------------------
    @staticmethod
    def _self_attr(expr: ast.AST) -> Optional[str]:
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return expr.attr
        return None

    def _stmt_writes(self, sub: ast.AST):
        """Yield (attr, pos) for every write a single AST node performs on
        `self.<attr>`: assignment, augmented assignment, item assignment or
        deletion, and in-place collection mutators."""
        pos = (getattr(sub, "lineno", 0), getattr(sub, "col_offset", 0))
        if isinstance(sub, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            if isinstance(sub, ast.AnnAssign) and sub.value is None:
                return  # bare annotation, no store
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            for t in targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for e in elts:
                    if isinstance(e, ast.Starred):
                        e = e.value
                    if isinstance(e, ast.Subscript):
                        e = e.value
                    attr = self._self_attr(e)
                    if attr is not None:
                        yield attr, pos
        elif isinstance(sub, ast.Delete):
            for t in sub.targets:
                if isinstance(t, ast.Subscript):
                    t = t.value
                attr = self._self_attr(t)
                if attr is not None:
                    yield attr, pos
        elif (isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in _MUTATORS):
            attr = self._self_attr(sub.func.value)
            if attr is not None:
                yield attr, pos

    def _check_guard(self, node) -> None:
        """DYN-A007 fact: the test reads `self.x`, the guarded body
        suspends at an `await`, and `self.x` is written after the
        suspension point. A write *before* the first await (the
        cache-then-fill idiom) is atomic with the check and stays clean,
        as does a span serialized by an `async with` lock."""
        facts = self._fn_stack[-1] if self._fn_stack else None
        if facts is None or not facts["is_async"] or self._async_held:
            return
        guard_attrs = {
            n.attr for n in ast.walk(node.test)
            if self._self_attr(n) is not None
            and isinstance(n.ctx, ast.Load)
        }
        if not guard_attrs:
            return
        awaits: List[Tuple[int, int]] = []
        writes: List[Tuple[Tuple[int, int], str]] = []

        def scan(sub: ast.AST) -> None:
            if isinstance(sub, ast.ExceptHandler):
                # a write in an except handler compensates a FAILED await
                # (the rollback idiom) — it is not the "act" half
                return
            if isinstance(sub, ast.Await):
                awaits.append((sub.lineno, sub.col_offset))
            for attr, pos in self._stmt_writes(sub):
                if attr in guard_attrs:
                    writes.append((pos, attr))
            for child in ast.iter_child_nodes(sub):
                scan(child)

        for stmt in node.body:
            scan(stmt)
        if not awaits:
            return
        first_await = min(awaits)
        late = [(pos, attr) for pos, attr in writes if pos > first_await]
        if not late:
            return
        pos, attr = min(late)
        facts["guards"].append({
            "attr": attr,
            "line": node.lineno,
            "col": node.col_offset,
            "await_line": first_await[0],
            "write_line": pos[0],
        })

    def _record_writes(self, node: ast.AST) -> None:
        facts = self._fn_stack[-1] if self._fn_stack else None
        if facts is None:
            return
        for attr, pos in self._stmt_writes(node):
            facts["writes"].append({
                "attr": attr,
                "line": pos[0],
                "col": pos[1],
                "locks": list(self._held),
                "async_locked": self._async_held > 0,
            })

    def visit_Assign(self, node: ast.Assign) -> None:
        self._record_writes(node)
        self.generic_visit(node)

    visit_AugAssign = visit_Assign
    visit_AnnAssign = visit_Assign
    visit_Delete = visit_Assign

    # -- locks -------------------------------------------------------------
    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        """Canonical id for a lock-typed `with` target, or None."""
        if isinstance(expr, ast.Name):
            if expr.id in self.index.async_lock_names:
                return None
            if expr.id in self.index.lock_names or _LOCK_NAME_RE.search(
                expr.id
            ):
                return f"{self.module}.{expr.id}"
            return None
        if isinstance(expr, ast.Attribute):
            if expr.attr in self.index.async_lock_attrs:
                return None
            if isinstance(expr.value, ast.Name) and expr.value.id == "self":
                if (expr.attr in self.index.lock_attrs
                        or _LOCK_NAME_RE.search(expr.attr)):
                    cls = self._cls_stack[-1] if self._cls_stack else "?"
                    return f"{self.module}.{cls}.{expr.attr}"
                return None
            resolved = self.index.resolve(expr)
            if resolved and _LOCK_NAME_RE.search(resolved.rsplit(".", 1)[-1]):
                return resolved
        return None

    def _is_async_lock(self, expr: ast.AST) -> bool:
        """`async with <this>` serializes coroutines: known asyncio-lock
        bindings, or (since a threading lock cannot appear in an `async
        with` anyway) anything lock-named."""
        if isinstance(expr, ast.Name):
            return (expr.id in self.index.async_lock_names
                    or bool(_LOCK_NAME_RE.search(expr.id)))
        if isinstance(expr, ast.Attribute):
            return (expr.attr in self.index.async_lock_attrs
                    or bool(_LOCK_NAME_RE.search(expr.attr)))
        return False

    def _visit_with(self, node) -> None:
        acquired: List[str] = []
        async_acquired = 0
        if not isinstance(node, ast.AsyncWith):
            for item in node.items:
                lock = self._lock_id(item.context_expr)
                if lock is not None and self._fn_stack:
                    self._fn_stack[-1]["acquires"].append({
                        "lock": lock,
                        "line": node.lineno,
                        "held": list(self._held),
                    })
                    self._held.append(lock)
                    acquired.append(lock)
        else:
            for item in node.items:
                if self._is_async_lock(item.context_expr):
                    async_acquired += 1
        self._async_held += async_acquired
        self.generic_visit(node)
        self._async_held -= async_acquired
        for _ in acquired:
            self._held.pop()

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # -- call context markers ---------------------------------------------
    def visit_Await(self, node: ast.Await) -> None:
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    def visit_Expr(self, node: ast.Expr) -> None:
        if isinstance(node.value, ast.Call):
            self._bare.add(id(node.value))
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        if (self._fn_stack and isinstance(node.value, ast.Call)):
            name = self.index.resolve(node.value.func) or ""
            if name in _SPAWN_CALLS or name.endswith(_SPAWN_TAILS):
                self._fn_stack[-1]["returns_spawn"] = True
        self.generic_visit(node)

    # -- the leaf event ----------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        self._record_writes(node)  # in-place collection mutators
        facts = self._fn_stack[-1] if self._fn_stack else None
        if facts is not None:
            name = self.index.resolve(node.func)
            fix = _BLOCKING_CALLS.get(name or "")
            if fix is not None:
                facts["blocking"].append(
                    {"line": node.lineno, "name": name, "fix": fix}
                )
            elif name == "open":
                facts["file_io"].append({"line": node.lineno})
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_ATTRS):
                facts["transfers"].append(
                    {"line": node.lineno, "what": f".{node.func.attr}()"}
                )
            elif name in _SYNC_CALLS:
                facts["transfers"].append(
                    {"line": node.lineno, "what": name}
                )
            if name and name not in _BLOCKING_CALLS:
                facts["calls"].append({
                    "callee": name,
                    "line": node.lineno,
                    "col": node.col_offset,
                    "in_loop": bool(self._loop_depth
                                    and self._loop_depth[-1] > 0),
                    "awaited": id(node) in self._awaited,
                    "bare": id(node) in self._bare,
                    "held": list(self._held),
                })
        self.generic_visit(node)


def extract_module_facts(
    rel_path: str, source: str, tree: Optional[ast.Module] = None,
) -> Dict[str, Any]:
    """Per-module fact dict for the project pass (JSON-serializable, so
    `lint_paths` caches it alongside the per-file violations)."""
    module = module_name_for(rel_path)
    is_pkg = rel_path.replace("\\", "/").endswith("__init__.py")
    if tree is None:
        try:
            tree = ast.parse(source, filename=rel_path)
        except SyntaxError:
            # DYN-E000 is already reported by the per-file pass
            return {"module": module, "path": rel_path, "is_pkg": is_pkg,
                    "aliases": {}, "functions": {}, "shard": {},
                    "suppress_lines": {}, "suppress_file": []}
    index = _ProjectModuleIndex(module, is_pkg)
    index.index_module(tree)
    visitor = _FactsVisitor(module, index)
    visitor.visit(tree)
    sup_lines, sup_file = _collect_suppressions(source)
    return {
        "module": module,
        "path": rel_path,
        "is_pkg": is_pkg,
        "aliases": dict(index.aliases),
        "functions": visitor.functions,
        "shard": extract_shard_facts(module, tree, index),
        "suppress_lines": {str(k): sorted(v) for k, v in sup_lines.items()},
        "suppress_file": sorted(sup_file),
    }


class ProjectIndex:
    """Link a set of module facts into a call graph + taint relations."""

    def __init__(self, modules: Iterable[Dict[str, Any]]) -> None:
        self.modules: Dict[str, Dict[str, Any]] = {}
        self.functions: Dict[str, Dict[str, Any]] = {}
        self.fn_module: Dict[str, Dict[str, Any]] = {}
        for m in modules:
            self.modules[m["module"]] = m
            for local, facts in m["functions"].items():
                q = f"{m['module']}.{local}"
                self.functions[q] = facts
                self.fn_module[q] = m
        # resolved edges: caller qname -> [(callee qname, call dict)]
        self.edges: Dict[str, List[Tuple[str, Dict[str, Any]]]] = {}
        for q, facts in self.functions.items():
            m = self.fn_module[q]
            out: List[Tuple[str, Dict[str, Any]]] = []
            for call in facts["calls"]:
                callee = self._resolve_callee(
                    m["module"], facts["cls"], call["callee"]
                )
                if callee is not None:
                    out.append((callee, call))
            self.edges[q] = out
        self.rev: Dict[str, List[str]] = {}
        for q, outs in self.edges.items():
            for callee, _ in outs:
                self.rev.setdefault(callee, []).append(q)

    # -- name resolution ---------------------------------------------------
    def _resolve_callee(
        self, module: str, cls: Optional[str], raw: str,
    ) -> Optional[str]:
        if raw.startswith("self."):
            parts = raw.split(".")
            if len(parts) == 2 and cls is not None:
                q = f"{module}.{cls}.{parts[1]}"
                if q in self.functions:
                    return q
            return None
        if "." not in raw:
            for q in (f"{module}.{raw}",
                      f"{module}.{cls}.{raw}" if cls else None):
                if q and q in self.functions:
                    return q
            return None
        return self._canon(raw, 0)

    def _canon(self, name: str, depth: int) -> Optional[str]:
        """Fully-qualified project function for a dotted name, following
        re-export aliases (`pkg/__init__.py: from pkg.impl import f`) up
        to a bounded number of hops."""
        if name in self.functions:
            return name
        if depth >= _MAX_CHAIN:
            return None
        parts = name.split(".")
        for i in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:i])
            m = self.modules.get(prefix)
            if m is None:
                continue
            rest = parts[i:]
            target = m["aliases"].get(rest[0])
            if target is not None:
                return self._canon(".".join([target] + rest[1:]), depth + 1)
            return None  # known module, unknown member: external enough
        return None

    # -- taint -------------------------------------------------------------
    def _taint(self, seed_key: str) -> Dict[str, Tuple[Any, Optional[str]]]:
        """BFS from direct seeds over reverse call edges. Returns
        `fn -> (root_entry, via)` where `via` is the next function on the
        chain toward the root (None when fn holds the root directly).
        Propagation follows edges that actually execute: any call to a
        sync callee, awaited calls to an async callee."""
        taint: Dict[str, Tuple[Any, Optional[str]]] = {}
        frontier: List[str] = []
        for q, facts in self.functions.items():
            entries = facts[seed_key]
            if entries:
                taint[q] = (entries[0], None)
                frontier.append(q)
        hops = 0
        while frontier and hops < _MAX_CHAIN:
            hops += 1
            nxt: List[str] = []
            for tainted in frontier:
                root, _ = taint[tainted]
                callee_async = self.functions[tainted]["is_async"]
                for caller in self.rev.get(tainted, ()):
                    if caller in taint:
                        continue
                    if callee_async and not any(
                        c["awaited"] for q2, c in self.edges[caller]
                        if q2 == tainted
                    ):
                        continue  # coroutine never awaited: body never runs
                    taint[caller] = (root, tainted)
                    nxt.append(caller)
            frontier = nxt
        return taint

    def chain(self, start: str,
              taint: Dict[str, Tuple[Any, Optional[str]]]) -> List[str]:
        """Human-readable helper chain from `start` to the taint root."""
        out, cur, seen = [start], start, {start}
        while True:
            _, via = taint[cur]
            if via is None or via in seen:
                return out
            out.append(via)
            seen.add(via)
            cur = via

    def acquires_transitive(self) -> Dict[str, Set[str]]:
        """fn -> set of lock ids it may acquire, directly or via calls
        (fixpoint over the call graph, hop-bounded)."""
        acq: Dict[str, Set[str]] = {
            q: {a["lock"] for a in f["acquires"]}
            for q, f in self.functions.items()
        }
        for _ in range(_MAX_CHAIN):
            changed = False
            for q, outs in self.edges.items():
                mine = acq[q]
                before = len(mine)
                for callee, _c in outs:
                    mine |= acq.get(callee, set())
                changed = changed or len(mine) != before
            if not changed:
                break
        return acq

    def _short(self, q: str) -> str:
        """Compact display name: module tail + function."""
        m = self.fn_module.get(q)
        if m is None:
            return q
        local = q[len(m["module"]) + 1:] if q.startswith(m["module"]) else q
        tail = m["module"].rsplit(".", 1)[-1]
        return f"{tail}.{local}"


def _in_step_scope(m: Dict[str, Any], facts: Dict[str, Any]) -> bool:
    """The DYN-J005 hot-path predicate, lifted to facts."""
    if "engine" not in m["path"]:
        return False
    n = facts["name"]
    return (n == "_loop_once" or n.startswith("accept")
            or n.startswith(_HOT_PREFIXES))


def _a007_sites(idx: "ProjectIndex"):
    """(module, facts, guard) per check-then-act-across-await span."""
    for q, facts in idx.functions.items():
        m = idx.fn_module[q]
        for g in facts.get("guards", ()):
            yield m, facts, g


def _r008_sites(idx: "ProjectIndex"):
    """(module, facts, write, locked_example) per lock-free async write to
    an attribute that some function writes under a threading lock. The
    state key is (module, class, attr) — attribute names don't collide
    across modules/classes the way bare names would."""
    by_state: Dict[Tuple[str, Optional[str], str], List[Any]] = {}
    for q, facts in idx.functions.items():
        m = idx.fn_module[q]
        for w in facts.get("writes", ()):
            key = (m["module"], facts["cls"], w["attr"])
            by_state.setdefault(key, []).append((q, facts, m, w))
    for key in sorted(by_state, key=lambda k: (k[0], k[1] or "", k[2])):
        ws = by_state[key]
        locked = [x for x in ws if x[3]["locks"]]
        if not locked:
            continue
        for q, facts, m, w in ws:
            if w["locks"] or w["async_locked"]:
                continue
            if not facts["is_async"] or facts["name"] == "__init__":
                continue
            yield m, facts, w, locked[0]


def atomicity_hazards(
    modules: Iterable[Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """DYN-A007/R008 sites as plain dicts — the dynamic-exploration seeds
    for `dynamo_tpu/mc`. Suppressions are deliberately NOT applied here:
    an inline suppression is a human claim that the span is safe, and a
    claimed-safe interleaving is precisely what the model checker should
    spend its budget trying to refute."""
    idx = ProjectIndex(modules)
    out: List[Dict[str, Any]] = []
    for m, facts, g in _a007_sites(idx):
        out.append({
            "rule": "DYN-A007", "path": m["path"], "module": m["module"],
            "cls": facts["cls"], "fn": facts["name"], "attr": g["attr"],
            "line": g["line"],
        })
    for m, facts, w, _locked in _r008_sites(idx):
        out.append({
            "rule": "DYN-R008", "path": m["path"], "module": m["module"],
            "cls": facts["cls"], "fn": facts["name"], "attr": w["attr"],
            "line": w["line"],
        })
    out.sort(key=lambda h: (h["path"], h["line"], h["rule"]))
    return out


def _suppressed(m: Dict[str, Any], rule: str, line: int) -> bool:
    sup_file = set(m.get("suppress_file", ()))
    if rule in sup_file or "*" in sup_file:
        return True
    sup = set(m.get("suppress_lines", {}).get(str(line), ()))
    return rule in sup or "*" in sup


def project_violations(
    modules: Iterable[Dict[str, Any]],
) -> List[Violation]:
    """All interprocedural findings for a set of module facts."""
    idx = ProjectIndex(modules)
    out: List[Violation] = []

    def report(m: Dict[str, Any], rule: str, line: int, col: int,
               message: str) -> None:
        if not _suppressed(m, rule, line):
            out.append(Violation(rule, m["path"], line, col, message))

    block_taint = idx._taint("blocking")
    io_taint = idx._taint("file_io")
    sync_taint = idx._taint("transfers")

    for q, facts in idx.functions.items():
        m = idx.fn_module[q]
        step_scope = _in_step_scope(m, facts)
        for callee, call in idx.edges[q]:
            cfacts = idx.functions[callee]
            executes = call["awaited"] or not cfacts["is_async"]

            # DYN-A006: project coroutine / spawned task dropped on the
            # floor — the cross-module case per-file A004 cannot see
            if (call["bare"] and not call["awaited"]
                    and (cfacts["is_async"] or cfacts["returns_spawn"])):
                kind = ("coroutine" if cfacts["is_async"]
                        else "spawned task handle")
                where = ("another module"
                         if idx.fn_module[callee] is not m else "this module")
                report(
                    m, "DYN-A006", call["line"], call["col"],
                    f"{kind} from `{idx._short(callee)}` (defined in "
                    f"{where}, {idx.fn_module[callee]['path']}:"
                    f"{cfacts['line']}) is created and dropped: it is "
                    "never awaited, so its body never runs"
                    + (" and its exception is never retrieved"
                       if not cfacts["is_async"] else "")
                    + "; await it, retain the handle, or use "
                      "`dynamo_tpu.runtime.spawn_tracked`")
                continue  # a dropped coroutine never runs: no other taint

            if not executes:
                continue

            if facts["is_async"]:
                if callee in block_taint:
                    root, _ = block_taint[callee]
                    links = " -> ".join(
                        idx._short(x)
                        for x in [q] + idx.chain(callee, block_taint)
                    )
                    report(
                        m, "DYN-A001", call["line"], call["col"],
                        f"indirect blocking call: {links} -> "
                        f"`{root['name']}` "
                        f"({idx.fn_module[idx.chain(callee, block_taint)[-1]]['path']}"
                        f":{root['line']}) runs on the event loop; "
                        f"{root['fix']}, or offload the helper with "
                        "`asyncio.to_thread`")
                if callee in io_taint and call["in_loop"]:
                    root, _ = io_taint[callee]
                    links = " -> ".join(
                        idx._short(x)
                        for x in [q] + idx.chain(callee, io_taint)
                    )
                    report(
                        m, "DYN-A002", call["line"], call["col"],
                        f"indirect sync file I/O per loop iteration: "
                        f"{links} -> `open()` "
                        f"({idx.fn_module[idx.chain(callee, io_taint)[-1]]['path']}"
                        f":{root['line']}); move the I/O off the loop or "
                        "use `asyncio.to_thread`")

            if step_scope and callee in sync_taint:
                root, _ = sync_taint[callee]
                tail = idx.chain(callee, sync_taint)[-1]
                links = " -> ".join(
                    idx._short(x) for x in [q] + idx.chain(callee, sync_taint)
                )
                if call["in_loop"]:
                    report(
                        m, "DYN-J005", call["line"], call["col"],
                        f"host-sync forcer reached through a helper chain "
                        f"inside the step/accept loop: {links} -> "
                        f"`{root['what']}` ({idx.fn_module[tail]['path']}:"
                        f"{root['line']}) forces one device sync PER "
                        "ITERATION of this loop; `jax.device_get` the "
                        "whole batch once before the loop")
                else:
                    report(
                        m, "DYN-J006", call["line"], call["col"],
                        f"implicit device→host transfer hidden in a "
                        f"helper reachable from the step loop: {links} -> "
                        f"`{root['what']}` ({idx.fn_module[tail]['path']}:"
                        f"{root['line']}); make the transfer an explicit "
                        "bulk `device_get` at the step-loop level (the "
                        "runtime sanitizer's transfer guard allowlists "
                        "exactly those)")

    # DYN-A007: check-then-act across an await — the guard's truth can
    # change while the body is suspended
    for m, facts, g in _a007_sites(idx):
        report(
            m, "DYN-A007", g["line"], g["col"],
            f"check-then-act on `self.{g['attr']}` spans an `await` "
            f"(line {g['await_line']}): the test result can be "
            f"invalidated by any coroutine scheduled during the "
            f"suspension, and the write at line {g['write_line']} then "
            "applies a stale decision (double-init / double-apply / "
            "lost update); re-check after the await, write BEFORE the "
            "first await, or serialize the span with an asyncio.Lock — "
            "this site is a prioritized dynmc yield point "
            "(docs/concurrency.md)")

    # DYN-R008: lock-protected state also written lock-free from async
    # context — the lock proves cross-thread sharing, so the unlocked
    # write races the locked writers
    for m, facts, w, (lq, _lf, lm, lw) in _r008_sites(idx):
        lock_tail = lw["locks"][0].rsplit(".", 1)[-1]
        report(
            m, "DYN-R008", w["line"], w["col"],
            f"`self.{w['attr']}` is written under `{lock_tail}` in "
            f"`{idx._short(lq)}` ({lm['path']}:{lw['line']}) but written "
            "lock-free here from async context; the lock exists because "
            "another thread touches this state, so this write races it — "
            "take the same lock, or move the mutation onto the owning "
            "thread (this site seeds dynmc exploration, "
            "docs/concurrency.md)")

    # DYN-R007: static lock-acquisition-order cycles. Direct edges come
    # from nested `with` blocks; cross-module edges from calls made while
    # a lock is held into functions that (transitively) acquire more.
    acq = idx.acquires_transitive()
    lock_edges: Dict[Tuple[str, str], Tuple[Dict[str, Any], int]] = {}
    for q, facts in idx.functions.items():
        m = idx.fn_module[q]
        for a in facts["acquires"]:
            for held in a["held"]:
                if held != a["lock"]:
                    lock_edges.setdefault(
                        (held, a["lock"]), (m, a["line"])
                    )
        for callee, call in idx.edges[q]:
            if not call["held"]:
                continue
            for lock in acq.get(callee, ()):
                for held in call["held"]:
                    if held != lock:
                        lock_edges.setdefault(
                            (held, lock), (m, call["line"])
                        )
    graph: Dict[str, Set[str]] = {}
    for (a, b) in lock_edges:
        graph.setdefault(a, set()).add(b)
    seen_cycles: Set[Tuple[str, ...]] = set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cyc = path + [start]
                    lo = min(range(len(cyc) - 1), key=lambda i: cyc[i])
                    canon = tuple(cyc[lo:-1] + cyc[:lo])
                    if canon in seen_cycles:
                        continue
                    seen_cycles.add(canon)
                    m, line = lock_edges[(cyc[0], cyc[1])]
                    sites = "; ".join(
                        f"{x} -> {y} ({lock_edges[(x, y)][0]['path']}:"
                        f"{lock_edges[(x, y)][1]})"
                        for x, y in zip(cyc, cyc[1:])
                    )
                    report(
                        m, "DYN-R007", line, 0,
                        f"lock-acquisition-order cycle: {sites} — two "
                        "threads taking these locks in opposite orders "
                        "deadlock; pick one global order (see "
                        "docs/static_analysis.md)")
                elif nxt not in path and len(path) < _MAX_CHAIN:
                    stack.append((nxt, path + [nxt]))

    # DYN-S001..S005: sharding/layout contract rules over the shard
    # facts (lint/rules_shard.py), same suppression semantics
    from dynamo_tpu.lint.rules_shard import shard_project_violations

    shard_project_violations(idx, report)

    out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return out
