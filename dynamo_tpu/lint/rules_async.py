"""DYN-A rule pack: async-safety.

Every worker/router/frontend process runs ONE event loop; a single
blocking call in any of the ~180 coroutines stalls every request that
process is serving (heartbeats miss, leases lapse, routers see a dead
instance). These rules catch the failure classes that have actually
bitten this stack: blocking syscalls inside `async def`, awaits while a
*threading* lock is held (the engine step thread then deadlocks against
the loop), and fire-and-forget `create_task` whose only reference is
dropped — the task can be garbage-collected mid-flight and its
exception is never observed (use `dynamo_tpu.runtime.spawn_tracked`).
"""

from __future__ import annotations

import ast

from dynamo_tpu.lint.core import LintContext, Rule

# canonical (post-alias) dotted names that block the calling thread
_BLOCKING_CALLS = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "subprocess.run": "use `asyncio.create_subprocess_exec` or "
                      "`asyncio.to_thread`",
    "subprocess.call": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_call": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec`",
    "os.system": "use `asyncio.create_subprocess_shell`",
    "os.popen": "use `asyncio.create_subprocess_shell`",
    "socket.create_connection": "use `asyncio.open_connection`",
    "socket.getaddrinfo": "use `loop.getaddrinfo`",
    "socket.gethostbyname": "use `loop.getaddrinfo`",
    "urllib.request.urlopen": "use an async HTTP client or "
                              "`asyncio.to_thread`",
}
for _verb in ("get", "post", "put", "patch", "delete", "head", "request"):
    _BLOCKING_CALLS[f"requests.{_verb}"] = (
        "use an async HTTP client (aiohttp) or `asyncio.to_thread`"
    )

_SPAWN_TAILS = (".create_task", ".ensure_future")
_FILE_READ_ATTRS = {"read", "readline", "readlines", "write", "writelines"}


def _is_spawn_call(ctx: LintContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = ctx.resolve(node.func)
    if name is None:
        return False
    return (name in ("asyncio.create_task", "asyncio.ensure_future")
            or name.endswith(_SPAWN_TAILS))


class BlockingCallInAsync(Rule):
    id = "DYN-A001"
    description = "blocking call inside `async def` stalls the event loop"

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_async:
            return
        name = ctx.resolve(node.func)
        fix = _BLOCKING_CALLS.get(name or "")
        if fix is not None:
            ctx.report(self.id, node,
                       f"blocking `{name}` inside a coroutine stalls the "
                       f"whole event loop; {fix}")


class SyncFileIOInAsync(Rule):
    id = "DYN-A002"
    description = "sync file I/O inside `async def`"

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.in_async:
            return
        # open(...) inside a loop: repeated sync disk I/O on the loop
        if (isinstance(node.func, ast.Name)
                and ctx.resolve(node.func) == "open"
                and ctx.loop_depth > 0):
            ctx.report(self.id, node,
                       "sync `open()` in a loop inside a coroutine; use "
                       "`asyncio.to_thread` (or move I/O off the loop)")
            return
        # open(...).read() / .write() chained — blocking however brief
        fn = node.func
        if (isinstance(fn, ast.Attribute)
                and fn.attr in _FILE_READ_ATTRS
                and isinstance(fn.value, ast.Call)
                and ctx.resolve(fn.value.func) == "open"):
            ctx.report(self.id, node,
                       f"sync `open().{fn.attr}()` inside a coroutine "
                       "blocks the event loop; use `asyncio.to_thread`")


class AwaitHoldingThreadLock(Rule):
    id = "DYN-A003"
    description = "`await` while holding a threading.Lock"

    def check_await(self, ctx: LintContext, node: ast.Await) -> None:
        if ctx.thread_lock_depth > 0:
            ctx.report(self.id, node,
                       "`await` while holding a threading lock: the loop "
                       "may suspend here with the lock held, deadlocking "
                       "every thread (e.g. the engine step thread) that "
                       "wants it; shrink the critical section or use "
                       "`asyncio.Lock`")


class DroppedTaskRef(Rule):
    id = "DYN-A004"
    description = "fire-and-forget create_task/ensure_future ref dropped"
    _MSG = ("task reference dropped: asyncio keeps only a weak ref, so the "
            "task can be garbage-collected mid-flight and its exception is "
            "never logged; use `dynamo_tpu.runtime.spawn_tracked(...)`")

    def check_expr_stmt(self, ctx: LintContext, node: ast.Expr) -> None:
        if _is_spawn_call(ctx, node.value):
            ctx.report(self.id, node, self._MSG)

    def check_assign(self, ctx: LintContext, node: ast.AST) -> None:
        if not isinstance(node, ast.Assign):
            return
        if (len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "_"
                and _is_spawn_call(ctx, node.value)):
            ctx.report(self.id, node, self._MSG)


class WaitForShield(Rule):
    id = "DYN-A005"
    description = "asyncio.wait_for wrapping asyncio.shield"

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        if ctx.resolve(node.func) != "asyncio.wait_for":
            return
        inner = node.args[0] if node.args else None
        if (isinstance(inner, ast.Call)
                and ctx.resolve(inner.func) == "asyncio.shield"):
            ctx.report(self.id, node,
                       "`wait_for(shield(...))`: on timeout the inner task "
                       "keeps running detached with no owner to observe its "
                       "result — if that is intended, retain the inner "
                       "task explicitly and handle its completion")


ASYNC_RULES = (
    BlockingCallInAsync,
    SyncFileIOInAsync,
    AwaitHoldingThreadLock,
    DroppedTaskRef,
    WaitForShield,
)
