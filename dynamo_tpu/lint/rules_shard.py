"""dynshard project rules: DYN-S001..S005 over the extracted shard facts.

Evaluated inside `project_violations` (lint/project.py) with the same
reporting-site suppression semantics as the concurrency rules. Each rule
protects one layout contract (docs/static_analysis.md):

- **DYN-S001** — spec mismatch at a call boundary: a tensor pinned to
  one `PartitionSpec` (via `with_sharding_constraint` / `device_put`)
  reaches a callee whose declared `in_specs`/`in_shardings` disagree.
  XLA will silently insert the reshard — on a pod that is an all-gather
  over DCN. Propagation follows bare-parameter forwarding through the
  PR-13 call graph, so the declaration may be any number of helper hops
  away; the finding carries the full chain with `file:line` per hop.
- **DYN-S002** — a spec references a mesh-axis name that no reachable
  mesh constructor defines: a typo'd axis silently means "replicate".
- **DYN-S003** — a large parameter / KV tensor enters an explicitly
  specced scope fully replicated via an *inline* literal. Deliberate
  replication must come from the canonical spec tables
  (`parallel/mesh.py`) so the decision is a reviewable declaration.
- **DYN-S004** — buffer-donation conflict: an argument donated via
  `donate_argnums` is aliased with another argument of the same call or
  read again after the call. Donated buffers are invalidated; the read
  returns garbage (or XLA errors) only on hardware, never under tests
  that skip donation.
- **DYN-S005** — role divergence: the same logical tensor (argument
  name + rank) is declared with different specs in prefill- vs
  decode-role functions without a declared `*reshard*` helper carrying
  it across — the disaggregated-serving seam (ROADMAP item 5) where an
  implicit layout change becomes KV-sized wire traffic.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = ["shard_project_violations", "SHARD_RULE_IDS"]

SHARD_RULE_IDS = ("DYN-S001", "DYN-S002", "DYN-S003", "DYN-S004",
                  "DYN-S005")

# tensors big enough that silent full replication is a real cost: model
# params / weights, embeddings, KV pools and page tensors (but NOT the
# tiny per-sequence metadata that happens to carry a kv_ prefix, like
# kv_lens)
_LARGE_RE = re.compile(
    r"(^|_)(params|weights?|embed|embedding|lm_head|pages)(_|$)|pool")

# state that persists across the prefill→decode handoff — the only
# tensors whose cross-role layout agreement matters for disaggregated
# serving (activations like `q` are recomputed per role, and identical
# names across different attention ops are not the same logical tensor)
_SEAM_RE = re.compile(r"pool|pages|cache|(^|_)kv(_|$)")

_MAX_HOPS = 12

_UNRESOLVED = object()


def _fold_entry(e: Any, const_env: Dict[str, Any],
                defaults: Dict[str, str]) -> Any:
    """Concrete value for one spec entry: None, an axis string, or a
    list of axis strings; _UNRESOLVED when it cannot be folded."""
    if e is None or isinstance(e, str) and e != "?":
        return e
    if isinstance(e, list):
        return e if all(isinstance(x, str) for x in e) else _UNRESOLVED
    if isinstance(e, dict):
        if "param" in e:
            v = defaults.get(e["param"])
            return v if v is not None else _UNRESOLVED
        if "ref" in e:
            v = const_env.get(e["ref"])
            if isinstance(v, (str, list)):
                return v
            return _UNRESOLVED
    return _UNRESOLVED


class _Linker:
    """Cross-module resolution state shared by all S rules."""

    def __init__(self, idx) -> None:
        self.idx = idx
        self.shards: Dict[str, Dict[str, Any]] = {}
        for mname, m in idx.modules.items():
            sh = m.get("shard")
            if sh:
                self.shards[mname] = sh
        # dotted constant env: "pkg.mod.AXIS_MODEL" -> "model", tuple
        # constants -> ["data", ...]
        self.const_env: Dict[str, Any] = {}
        for mname, sh in self.shards.items():
            for name, v in sh.get("consts", {}).items():
                self.const_env[f"{mname}.{name}"] = v
        # canonical spec table: "pkg.mod.SPEC_X" -> folded entries
        self.spec_table: Dict[str, List[Any]] = {}
        for mname, sh in self.shards.items():
            for name, sc in sh.get("spec_consts", {}).items():
                folded = [_fold_entry(e, self.const_env, {})
                          for e in sc.get("entries", [])]
                if not any(f is _UNRESOLVED for f in folded):
                    self.spec_table[f"{mname}.{name}"] = folded
        # mesh axes defined anywhere in scope
        self.defined_axes: set = set()
        self.has_mesh = False
        for sh in self.shards.values():
            for decl in sh.get("axes", []):
                self.has_mesh = True
                for e in decl.get("axes", []):
                    f = _fold_entry(e, self.const_env, {})
                    if isinstance(f, str):
                        self.defined_axes.add(f)
                    elif isinstance(f, list):
                        self.defined_axes.update(f)
        # qname -> (module dict, shard fn facts)
        self.fns: Dict[str, Tuple[Dict[str, Any], Dict[str, Any]]] = {}
        for mname, sh in self.shards.items():
            m = idx.modules[mname]
            for local, f in sh.get("functions", {}).items():
                self.fns[f"{mname}.{local}"] = (m, f)

    # -- spec resolution ---------------------------------------------------
    def resolve_spec(self, spec: Optional[Dict[str, Any]],
                     defaults: Optional[Dict[str, str]] = None,
                     ) -> Optional[List[Any]]:
        """Concrete entry list for a spec descriptor, or None."""
        if not isinstance(spec, dict):
            return None
        if "ref" in spec and "entries" not in spec:
            return self.spec_table.get(spec["ref"])
        folded = [_fold_entry(e, self.const_env, defaults or {})
                  for e in spec.get("entries", [])]
        if any(f is _UNRESOLVED for f in folded):
            return None
        return folded

    def partial_axes(self, spec: Optional[Dict[str, Any]],
                     defaults: Dict[str, str]) -> List[Tuple[str, int]]:
        """(axis, line) for every axis string a spec mentions, even when
        other entries stay symbolic — S002 checks names, not shapes."""
        if not isinstance(spec, dict):
            return []
        line = spec.get("line", 0)
        entries = spec.get("entries")
        if entries is None and "ref" in spec:
            return []  # checked where the table entry is defined
        out: List[Tuple[str, int]] = []
        for e in entries or []:
            f = _fold_entry(e, self.const_env, defaults)
            if isinstance(f, str):
                out.append((f, line))
            elif isinstance(f, list):
                out.extend((x, line) for x in f)
        return out

    def resolve_callee(self, mname: str, cls: Optional[str],
                       raw: str) -> Optional[str]:
        q = self.idx._resolve_callee(mname, cls, raw)
        if q is not None and q in self.fns:
            return q
        return None

    def short(self, q: str) -> str:
        return self.idx._short(q)


def _norm(entries: List[Any]) -> Tuple[Any, ...]:
    """Comparison form: trailing Nones stripped (P("x") == P("x", None)
    for any array the spec can apply to), tuple entries hashable."""
    out = [tuple(e) if isinstance(e, list) else e for e in entries]
    while out and out[-1] is None:
        out.pop()
    return tuple(out)


def _fmt(entries: List[Any]) -> str:
    def one(e: Any) -> str:
        if e is None:
            return "None"
        if isinstance(e, (list, tuple)):
            return "(" + ", ".join(repr(x) for x in e) + ")"
        return repr(e)
    return "P(" + ", ".join(one(e) for e in entries) + ")"


def _declared_specs(lk: _Linker) -> Dict[str, Dict[int, Dict[str, Any]]]:
    """fn qname -> {param position -> declared spec + declaration site}.

    Seeds: a function that forwards its own parameter straight into a
    `shard_map` boundary, and `jax.jit(fn, in_shardings=...)`
    declarations. Propagation: a function that forwards its parameter
    bare into a callee with a declared spec inherits that requirement
    (fixpoint, hop-bounded) — this is what makes the 2-hop S001 fire."""
    declared: Dict[str, Dict[int, Dict[str, Any]]] = {}
    for q, (m, f) in lk.fns.items():
        for b in f.get("boundaries", []):
            for j, a in enumerate(b.get("args", [])):
                if a.get("param") is None:
                    continue
                entries = lk.resolve_spec(a.get("spec"),
                                          f.get("param_defaults"))
                if entries is None:
                    continue
                declared.setdefault(q, {}).setdefault(a["param"], {
                    "entries": entries,
                    "site": (m["path"], b.get("decl_line", b["line"])),
                    "hops": [],
                })
    for mname, sh in lk.shards.items():
        m = lk.idx.modules[mname]
        for jd in sh.get("jit_decls", []):
            q = lk.resolve_callee(mname, None, jd["fn"])
            if q is None:
                continue
            _, f = lk.fns[q]
            for pos, spec in enumerate(jd.get("in", [])):
                entries = lk.resolve_spec(spec, f.get("param_defaults"))
                if entries is None:
                    continue
                declared.setdefault(q, {}).setdefault(pos, {
                    "entries": entries,
                    "site": (m["path"], jd["line"]),
                    "hops": [],
                })
    for _ in range(_MAX_HOPS):
        changed = False
        for q, (m, f) in lk.fns.items():
            for fl in f.get("flows", []):
                callee = lk.resolve_callee(m["module"], f.get("cls"),
                                           fl["callee"])
                if callee is None:
                    continue
                cdecl = declared.get(callee, {})
                for j, a in enumerate(fl.get("args", [])):
                    if not (isinstance(a, dict) and "param" in a):
                        continue
                    d = cdecl.get(j)
                    if d is None:
                        continue
                    slot = declared.setdefault(q, {})
                    if a["param"] in slot:
                        continue
                    slot[a["param"]] = {
                        "entries": d["entries"],
                        "site": d["site"],
                        "hops": [(lk.short(callee), m["path"],
                                  fl["line"])] + d["hops"],
                    }
                    changed = True
        if not changed:
            break
    return declared


def _s001(lk: _Linker, declared, report: Callable) -> None:
    for q, (m, f) in lk.fns.items():
        defaults = f.get("param_defaults", {})
        # direct: constrained local straight into a shard_map boundary
        for b in f.get("boundaries", []):
            for a in b.get("args", []):
                actual = a.get("actual")
                if not actual:
                    continue
                have = lk.resolve_spec(actual.get("spec"), defaults)
                want = lk.resolve_spec(a.get("spec"), defaults)
                if have is None or want is None:
                    continue
                if _norm(have) != _norm(want):
                    report(
                        m, "DYN-S001", b["line"], b.get("col", 0),
                        f"spec mismatch at shard_map boundary: "
                        f"`{a.get('name') or '<arg>'}` is constrained to "
                        f"{_fmt(have)} ({m['path']}:{actual['line']}) but "
                        f"the boundary declares {_fmt(want)} "
                        f"({m['path']}:{b.get('decl_line', b['line'])}); "
                        "XLA inserts an implicit reshard (an all-gather "
                        "on a pod mesh) — align the specs via the "
                        "canonical tables in parallel/mesh.py or reshard "
                        "explicitly")
        # interprocedural: constrained local forwarded into a callee
        # whose (possibly inherited) declared spec disagrees
        for fl in f.get("flows", []):
            callee = lk.resolve_callee(m["module"], f.get("cls"),
                                       fl["callee"])
            if callee is None:
                continue
            cdecl = declared.get(callee)
            if not cdecl:
                continue
            for j, a in enumerate(fl.get("args", [])):
                if not (isinstance(a, dict) and "spec" in a):
                    continue
                d = cdecl.get(j)
                if d is None:
                    continue
                have = lk.resolve_spec(a["spec"], defaults)
                if have is None:
                    continue
                if _norm(have) == _norm(d["entries"]):
                    continue
                site_path, site_line = d["site"]
                chain = [f"`{a.get('var', '<arg>')}` constrained to "
                         f"{_fmt(have)} ({m['path']}:{a['line']})",
                         f"{lk.short(callee)} ({m['path']}:{fl['line']})"]
                chain += [f"{label} ({path}:{line})"
                          for label, path, line in d["hops"]]
                chain.append(f"declared {_fmt(d['entries'])} "
                             f"({site_path}:{site_line})")
                report(
                    m, "DYN-S001", fl["line"], fl.get("col", 0),
                    "spec mismatch at call boundary: "
                    + " -> ".join(chain)
                    + "; the callee's contract disagrees with the "
                      "caller's layout, so XLA reshards implicitly — "
                      "align the specs or route through a declared "
                      "reshard helper")


def _s002(lk: _Linker, report: Callable) -> None:
    if not lk.has_mesh or not lk.defined_axes:
        return  # no mesh constructor in scope: nothing to check against
    shown = ", ".join(sorted(lk.defined_axes))
    for mname, sh in lk.shards.items():
        m = lk.idx.modules[mname]
        fn_defaults: Dict[str, Dict[str, str]] = {
            f["name"]: f.get("param_defaults", {})
            for f in sh.get("functions", {}).values()
        }
        for spec in sh.get("specs", []):
            defaults = fn_defaults.get(spec.get("fn") or "", {})
            for axis, line in lk.partial_axes(spec, defaults):
                if axis not in lk.defined_axes:
                    report(
                        m, "DYN-S002", spec.get("line", line),
                        spec.get("col", 0),
                        f"spec references mesh axis '{axis}' which no "
                        f"reachable mesh constructor defines (defined: "
                        f"{shown}); an unknown axis name silently means "
                        "'replicate' — fix the name or add the axis to "
                        "the mesh")


def _s003(lk: _Linker, report: Callable) -> None:
    def fully_replicated(entries: List[Any]) -> bool:
        return all(e is None for e in entries)

    for q, (m, f) in lk.fns.items():
        defaults = f.get("param_defaults", {})
        for b in f.get("boundaries", []):
            for a in b.get("args", []):
                name = a.get("name")
                spec = a.get("spec")
                if (not name or not _LARGE_RE.search(name)
                        or not isinstance(spec, dict)
                        or "entries" not in spec):
                    continue  # table refs are declared decisions
                entries = lk.resolve_spec(spec, defaults)
                if entries is None or not fully_replicated(entries):
                    continue
                report(
                    m, "DYN-S003", b["line"], b.get("col", 0),
                    f"large tensor `{name}` enters the shard_map scope "
                    f"fully replicated by the inline literal "
                    f"{_fmt(entries)} "
                    f"({m['path']}:{spec.get('line', b['line'])}); if "
                    "replication is deliberate, import the canonical "
                    "declaration from parallel/mesh.py (e.g. "
                    "SPEC_REPLICATED) so the memory cost is a reviewed "
                    "decision, otherwise give it a sharded spec")
    for mname, sh in lk.shards.items():
        m = lk.idx.modules[mname]
        for jd in sh.get("jit_decls", []):
            q = lk.resolve_callee(mname, None, jd["fn"])
            params = lk.fns[q][1]["params"] if q else []
            for pos, spec in enumerate(jd.get("in", [])):
                if not isinstance(spec, dict) or "entries" not in spec:
                    continue
                name = params[pos] if pos < len(params) else None
                if not name or not _LARGE_RE.search(name):
                    continue
                entries = lk.resolve_spec(spec)
                if entries is None or not all(e is None for e in entries):
                    continue
                report(
                    m, "DYN-S003", jd["line"], 0,
                    f"large tensor `{name}` enters the pjitted scope "
                    f"fully replicated by the inline in_shardings "
                    f"literal {_fmt(entries)}; import the canonical "
                    "declaration from parallel/mesh.py or shard it")


def _s004(lk: _Linker, report: Callable) -> None:
    for q, (m, f) in lk.fns.items():
        for dc in f.get("donate_calls", []):
            for d in dc.get("donated", []):
                if "conflict_line" not in d:
                    continue
                if d["why"] == "aliased":
                    msg = (f"donated buffer `{d['name']}` is passed "
                           f"twice to `{dc['binding']}` (donate binding "
                           f"at {m['path']}:{dc['decl_line']}): the "
                           "donated operand aliases another argument, "
                           "so the kernel reads a buffer XLA already "
                           "reused — pass a copy or stop donating it")
                else:
                    msg = (f"donated buffer `{d['name']}` is read at "
                           f"{m['path']}:{d['conflict_line']} after "
                           f"being donated to `{dc['binding']}` "
                           f"({m['path']}:{dc['line']}, donate binding "
                           f"at line {dc['decl_line']}): donation "
                           "invalidates the buffer, so the later read "
                           "returns garbage on device — rebind the name "
                           "to the call's result or drop the donation")
                report(m, "DYN-S004", dc["line"], dc.get("col", 0), msg)


def _s005(lk: _Linker, report: Callable) -> None:
    # params of declared reshard helpers: tensors they carry are exempt
    # (the helper IS the declared layout change)
    reshard_params: set = set()
    for q, (_m, f) in lk.fns.items():
        if f.get("is_reshard"):
            reshard_params.update(f.get("params", []))
    sites: Dict[Tuple[str, int], List[Dict[str, Any]]] = {}
    for q, (m, f) in lk.fns.items():
        role = f.get("role")
        if role is None or f.get("is_reshard"):
            continue
        defaults = f.get("param_defaults", {})
        for b in f.get("boundaries", []):
            for a in b.get("args", []):
                name = a.get("name")
                entries = lk.resolve_spec(a.get("spec"), defaults)
                if not name or not _SEAM_RE.search(name) or entries is None:
                    continue
                sites.setdefault((name, len(entries)), []).append({
                    "role": role, "spec": entries, "m": m,
                    "line": b["line"], "col": b.get("col", 0),
                    "fn": f["name"],
                })
        for c in f.get("constraints", []):
            entries = lk.resolve_spec(c.get("spec"), defaults)
            if entries is None or not _SEAM_RE.search(c["var"]):
                continue
            sites.setdefault((c["var"], len(entries)), []).append({
                "role": role, "spec": entries, "m": m,
                "line": c["line"], "col": 0, "fn": f["name"],
            })
    for (name, _rank), ss in sorted(sites.items()):
        if name in reshard_params:
            continue
        pre = [s for s in ss if s["role"] == "prefill"]
        dec = [s for s in ss if s["role"] == "decode"]
        done = False
        for p in pre:
            for d in dec:
                if _norm(p["spec"]) == _norm(d["spec"]):
                    continue
                report(
                    d["m"], "DYN-S005", d["line"], d["col"],
                    f"role divergence for `{name}`: prefill "
                    f"`{p['fn']}` declares {_fmt(p['spec'])} "
                    f"({p['m']['path']}:{p['line']}) but decode "
                    f"`{d['fn']}` declares {_fmt(d['spec'])} — the "
                    "layouts disagree across the prefill/decode seam "
                    "with no declared reshard helper in between, so a "
                    "disaggregated deployment reshards KV-sized state "
                    "on the wire implicitly; share one canonical spec "
                    "from parallel/mesh.py or route through a "
                    "`*reshard*` helper that takes this tensor")
                done = True
                break
            if done:
                break


def shard_project_violations(idx, report: Callable) -> None:
    """Run all S rules. `report(module, rule, line, col, message)` is
    the suppression-aware emitter owned by project_violations."""
    lk = _Linker(idx)
    if not lk.shards:
        return
    declared = _declared_specs(lk)
    _s001(lk, declared, report)
    _s002(lk, report)
    _s003(lk, report)
    _s004(lk, report)
    _s005(lk, report)
