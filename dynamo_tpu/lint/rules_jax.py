"""DYN-J rule pack: JAX trace hygiene and compile-key cardinality.

A jitted function is *traced*: Python control flow runs once per compile
key, so branching on a tracer raises at best (ConcretizationTypeError)
and silently bakes in one branch at worst. Worse for a serving system is
cardinality: every distinct static-arg value is a fresh XLA compile
(seconds of host stall each — the exact cache growth `_CompiledFamily`
counts and the ragged kernel collapsed to ~|T buckets|, see
docs/ragged_attention.md). DYN-J004 enforces that discipline at call
sites: a static arg must be a constant or routed through a bucketing
helper (`ensure_ragged_bucket`, `pack_buckets`, any `*bucket*` name),
never a raw `len(...)`/`.shape` of request-sized data.
"""

from __future__ import annotations

import ast
from typing import Optional, Set

from dynamo_tpu.lint.core import JitBinding, LintContext, Rule

# attributes of a tracer that are static (safe to branch on at trace time)
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "at"}


def _static_exempt_names(test: ast.AST) -> Set[str]:
    """Names that only feed trace-time-static expressions: `x.shape[0]`,
    `x.ndim`, `len(x)` are Python ints during tracing."""
    exempt: Set[str] = set()
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and sub.attr in _STATIC_ATTRS:
            exempt |= {n.id for n in ast.walk(sub)
                       if isinstance(n, ast.Name)}
        elif (isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name)
              and sub.func.id in ("len", "isinstance", "type", "getattr",
                                  "hasattr")):
            exempt |= {n.id for n in ast.walk(sub)
                       if isinstance(n, ast.Name)}
    return exempt


def _tracer_params(ctx: LintContext) -> Set[str]:
    scope = ctx.func
    if scope is None or not scope.is_traced:
        return set()
    static = scope.jit_static or set()
    return set(scope.params) - static - {"self", "cls"}


class TracerBranch(Rule):
    id = "DYN-J001"
    description = "Python if/while on a tracer inside a jitted function"

    def check_branch(self, ctx: LintContext, node: ast.AST) -> None:
        tracers = _tracer_params(ctx)
        if not tracers:
            return
        test = getattr(node, "test", None)
        if test is None:
            return
        names = {n.id for n in ast.walk(test) if isinstance(n, ast.Name)}
        hot = (names - _static_exempt_names(test)) & tracers
        if hot:
            kind = "while" if isinstance(node, ast.While) else "if"
            ctx.report(self.id, node,
                       f"Python `{kind}` on tracer value(s) "
                       f"{sorted(hot)} inside a traced function; use "
                       "`jax.lax.cond`/`select`/`jnp.where` (or mark the "
                       "arg static and bucket it)")


class TracerMaterialize(Rule):
    id = "DYN-J002"
    description = ".item()/int()/float() on a tracer inside jit"

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        tracers = _tracer_params(ctx)
        if not tracers:
            return
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in ("item", "tolist"):
            ctx.report(self.id, node,
                       f"`.{fn.attr}()` inside a traced function forces a "
                       "host sync / fails on tracers; keep the value on "
                       "device or compute it outside jit")
            return
        if (isinstance(fn, ast.Name) and fn.id in ("int", "float", "bool")
                and node.args):
            sub = node.args[0]
            names = {n.id for n in ast.walk(sub) if isinstance(n, ast.Name)}
            hot = (names - _static_exempt_names(sub)) & tracers
            if hot:
                ctx.report(self.id, node,
                           f"`{fn.id}()` on tracer value(s) {sorted(hot)} "
                           "inside a traced function raises "
                           "ConcretizationTypeError at runtime")


class ImportTimeJnp(Rule):
    id = "DYN-J003"
    description = "jnp.* executed at module import time"

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not ctx.at_module_level:
            return
        name = ctx.resolve(node.func)
        if name and name.startswith("jax.numpy."):
            ctx.report(self.id, node,
                       f"`{name}` runs at import time: it initializes the "
                       "JAX backend before the process can configure "
                       "platforms/mesh (breaks JAX_PLATFORMS=cpu test "
                       "runs); build the array lazily or use numpy")


class CompileKeyCardinality(Rule):
    id = "DYN-J004"
    description = "jit static arg not provably drawn from a bucket set"

    def _binding_for(self, ctx: LintContext,
                     func: ast.AST) -> Optional[JitBinding]:
        name = ctx.resolve(func)
        if name is None:
            return None
        return ctx.index.jit_bindings.get(name.split(".")[-1])

    def _unbucketed(self, ctx: LintContext, expr: ast.AST) -> bool:
        """True when the static-arg expression derives from runtime data
        (len()/.shape/arithmetic) with no bucketing step in the chain."""
        if isinstance(expr, (ast.Constant, ast.Name, ast.Attribute)):
            return False  # constants and pre-bound names are accepted
        if isinstance(expr, ast.IfExp):
            # a conditional between two bounded values is itself bounded
            return (self._unbucketed(ctx, expr.body)
                    or self._unbucketed(ctx, expr.orelse))
        derived = False
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                name = ctx.resolve(sub.func) or ""
                if "bucket" in name.lower():
                    return False  # provably routed through a bucket helper
                if name.split(".")[-1] == "len":
                    derived = True
            elif isinstance(sub, ast.Name) and "bucket" in sub.id.lower():
                return False
            elif isinstance(sub, ast.Attribute):
                if "bucket" in sub.attr.lower():
                    return False
                if sub.attr == "shape":
                    derived = True
            elif isinstance(sub, ast.BinOp):
                derived = True
        return derived

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        b = self._binding_for(ctx, node.func)
        if b is None:
            return
        static_pos = set(b.static_pos)
        if b.inner_params:
            static_pos |= {
                i for i, p in enumerate(b.inner_params)
                if p in b.static_names
            }
        for i, arg in enumerate(node.args):
            if i in static_pos and self._unbucketed(ctx, arg):
                ctx.report(self.id, node,
                           f"static arg {i} of jitted `{b.name}` is "
                           "computed from runtime values without a "
                           "bucketing step: every distinct value is a "
                           "fresh XLA compile; round through "
                           "`ensure_ragged_bucket`/`pack_buckets` (see "
                           "docs/ragged_attention.md)")
        for kw in node.keywords:
            if kw.arg in b.static_names and self._unbucketed(ctx, kw.value):
                ctx.report(self.id, node,
                           f"static arg `{kw.arg}` of jitted `{b.name}` "
                           "is computed from runtime values without a "
                           "bucketing step: every distinct value is a "
                           "fresh XLA compile; round through "
                           "`ensure_ragged_bucket`/`pack_buckets`")


class HostSyncInStepLoop(Rule):
    id = "DYN-J005"
    description = "host-sync forcer inside an engine step/accept loop"

    # functions on the engine's per-iteration hot path: the step loop
    # itself, the dispatch wrappers, and the speculative accept path
    _HOT = ("_run_decode", "_run_mixed", "_run_spec", "_run_prefill")

    def _in_step_scope(self, ctx: LintContext) -> bool:
        if "engine" not in ctx.path:
            return False
        scope = ctx.func
        if scope is None:
            return False
        n = scope.name
        return (n == "_loop_once" or n.startswith("accept")
                or n.startswith(self._HOT))

    def _is_sync_call(self, ctx: LintContext, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in ("item", "tolist"):
            return True
        name = ctx.resolve(fn) or ""
        return name in ("numpy.asarray", "jax.device_get")

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        if ctx.loop_depth <= 0 or not self._in_step_scope(ctx):
            return
        if self._is_sync_call(ctx, node):
            what = (node.func.attr + "()"
                    if isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("item", "tolist")
                    else (ctx.resolve(node.func) or "host sync"))
            ctx.report(self.id, node,
                       f"`{what}` inside the engine step/accept loop "
                       "forces one device sync PER TOKEN, serializing the "
                       "accept path against the device; `jax.device_get` "
                       "the whole batch ONCE before the loop and index "
                       "host-side")
            return
        fn = node.func
        if (isinstance(fn, ast.Name) and fn.id in ("int", "float")
                and node.args):
            # int(x[i]) on an already-host array is fine; int(x.item())
            # or float(np.asarray(x)[0]) smuggles the sync inside the cast
            for sub in ast.walk(node.args[0]):
                if self._is_sync_call(ctx, sub):
                    ctx.report(self.id, node,
                               f"`{fn.id}(...)` wraps a host-sync forcer "
                               "inside the engine step/accept loop; pull "
                               "the device transfer out of the loop")
                    return


JAX_RULES = (
    TracerBranch,
    TracerMaterialize,
    ImportTimeJnp,
    CompileKeyCardinality,
    HostSyncInStepLoop,
)
