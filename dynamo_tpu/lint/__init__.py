"""dynlint — AST-based invariant checker for the dynamo_tpu stack.

Three planes of latent bugs are invisible to pytest: blocking calls that
stall the single event loop shared by ~180 coroutines (DYN-A), Python
control flow on JAX tracers / unbounded compile keys that silently
multiply the jit cache the ragged kernel collapsed (DYN-J), and
cross-coroutine races or swallowed failures in the runtime planes
(DYN-R). dynlint machine-checks those invariants as a tier-1 gate; see
docs/static_analysis.md for the rule catalog and suppression policy.
"""

from dynamo_tpu.lint.core import (
    Violation,
    Rule,
    lint_file,
    lint_paths,
    default_rules,
    format_human,
    format_json,
    load_baseline,
    baseline_counts,
    diff_against_baseline,
)
from dynamo_tpu.lint.project import (
    ProjectIndex,
    atomicity_hazards,
    extract_module_facts,
    project_violations,
)

__all__ = [
    "Violation",
    "Rule",
    "lint_file",
    "lint_paths",
    "default_rules",
    "format_human",
    "format_json",
    "load_baseline",
    "baseline_counts",
    "diff_against_baseline",
    "ProjectIndex",
    "atomicity_hazards",
    "extract_module_facts",
    "project_violations",
]
