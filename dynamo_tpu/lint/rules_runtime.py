"""DYN-R rule pack: runtime races and robustness.

The runtime planes (request/event/discovery) are long-lived: a swallowed
exception or a hung await doesn't crash the process, it degrades it —
the worker keeps its lease while silently serving nothing. These rules
flag the three shapes that produce that state: module-level mutable
state mutated from multiple coroutines with no lock (loop interleaving
at any await corrupts it), `except Exception: pass` that erases the
evidence, and cross-plane socket reads with no timeout (a half-dead
peer then parks the coroutine forever — the request-plane connection is
the only thing that notices).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from dynamo_tpu.lint.core import LintContext, Rule

_MUTATORS = {
    "append", "add", "update", "pop", "setdefault", "clear", "extend",
    "discard", "remove", "insert", "popitem",
}

# awaited cross-plane reads that hang forever when the peer half-dies;
# each needs asyncio.wait_for / asyncio.timeout (or a documented reason)
_RPC_ATTRS = {"readexactly", "next_msg", "round_trip", "request_once"}


class SharedMutableState(Rule):
    id = "DYN-R001"
    description = "module-level mutable written from >=2 coroutines unlocked"

    def __init__(self) -> None:
        # name -> list of (coroutine name, write node, lock held)
        self._writes: Dict[str, List[Tuple[str, ast.AST, bool]]] = {}

    def _record(self, ctx: LintContext, name: str, node: ast.AST) -> None:
        if name not in ctx.index.module_mutables or not ctx.in_async:
            return
        self._writes.setdefault(name, []).append(
            (ctx.func.name, node, ctx.any_lock_depth > 0)
        )

    def check_assign(self, ctx: LintContext, node: ast.AST) -> None:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name):
                self._record(ctx, t.value.id, node)

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS
                and isinstance(fn.value, ast.Name)):
            self._record(ctx, fn.value.id, node)

    def finish_module(self, ctx: LintContext) -> None:
        for name, writes in self._writes.items():
            writers = {fn for fn, _, _ in writes}
            unlocked = [(fn, node) for fn, node, locked in writes
                        if not locked]
            if len(writers) >= 2 and unlocked:
                for fn, node in unlocked:
                    ctx.report(self.id, node,
                               f"module-level mutable `{name}` written "
                               f"from {len(writers)} coroutines "
                               f"({sorted(writers)}) with no lock in "
                               "scope: loop interleaving at any await "
                               "corrupts it; guard with one asyncio.Lock")
        self._writes.clear()


class ExceptPassSwallow(Rule):
    id = "DYN-R002"
    description = "`except Exception: pass` swallows failures silently"

    def _too_broad(self, ctx: LintContext, node: ast.ExceptHandler) -> bool:
        t = node.type
        if t is None:
            return True
        if isinstance(t, ast.Tuple):
            return any(ctx.resolve(e) in ("Exception", "BaseException")
                       for e in t.elts)
        return ctx.resolve(t) in ("Exception", "BaseException")

    def check_except(self, ctx: LintContext,
                     node: ast.ExceptHandler) -> None:
        if (len(node.body) == 1 and isinstance(node.body[0], ast.Pass)
                and self._too_broad(ctx, node)):
            ctx.report(self.id, node,
                       "broad `except` with bare `pass` erases the only "
                       "evidence of a failure; narrow the exception type "
                       "and/or log at debug level")


class MissingRpcTimeout(Rule):
    id = "DYN-R003"
    description = "cross-plane await with no timeout"

    def check_await(self, ctx: LintContext, node: ast.Await) -> None:
        if ctx.timeout_depth > 0:
            return
        val = node.value
        if (isinstance(val, ast.Call)
                and isinstance(val.func, ast.Attribute)
                and val.func.attr in _RPC_ATTRS):
            ctx.report(self.id, node,
                       f"`await ...{val.func.attr}()` with no timeout: a "
                       "half-dead peer parks this coroutine forever; wrap "
                       "in `asyncio.wait_for` (or an `asyncio.timeout` "
                       "scope)")


# the flight recorder's append path runs inline in the engine step loop:
# ONE blocking syscall there shows up in every iteration's wall time and
# poisons the very EWMA the recorder uses to spot anomalies. Dump/profile
# work must stay on the hand-off thread (_dump_loop / _write_dump).
_BLOCKING_NAMES = {"open", "print"}
_BLOCKING_ATTRS = {
    "sleep", "write", "flush", "fsync", "fdatasync", "dump", "urlopen",
    "sendall", "send", "recv", "put",  # queue.put blocks when full;
}                                      # put_nowait is the allowed spelling
_HOT_PREFIXES = ("append", "record", "observe", "on_")


class RecorderBlockingIo(Rule):
    id = "DYN-R004"
    description = "blocking I/O in a flight-recorder append path"

    def _in_hot_path(self, ctx: LintContext) -> bool:
        if "flight_recorder" not in ctx.path:
            return False
        for scope in ctx.func_stack:
            if scope.name.lstrip("_").startswith(_HOT_PREFIXES):
                return True
        return False

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        if not self._in_hot_path(ctx):
            return
        fn = node.func
        name = None
        if isinstance(fn, ast.Name) and fn.id in _BLOCKING_NAMES:
            name = fn.id
        elif isinstance(fn, ast.Attribute) and fn.attr in _BLOCKING_ATTRS:
            name = fn.attr
        if name is not None:
            ctx.report(self.id, node,
                       f"`{name}(...)` in a flight-recorder append path "
                       "runs inline in the engine step loop and skews "
                       "every iteration it touches; hand the work to the "
                       "dump thread (queue.put_nowait) instead")


# Prometheus label sets are bounded or they are a slow memory leak: every
# distinct label value materializes a time series that lives for the rest
# of the process (and the scraper's retention window). A request id, block
# hash, or per-boot UUID in a label turns /metrics into an unbounded
# allocation — per-request detail belongs in the routing audit ring and
# the /debug/fleet JSON, not in metric labels.
_METRIC_FACTORIES = {"counter", "gauge", "histogram", "child"}
# label NAMES that are per-request / per-object by construction
_UNBOUNDED_LABEL_RE = re.compile(
    r"(^|_)(rid|request_id|req_id|block_hash|hash|hashes|uuid|"
    r"session_id|trace_id|span_id)($|_)"
)
# label VALUE expressions that resolve to request ids / generated UUIDs
_UNBOUNDED_VALUE_RE = re.compile(
    r"(^|\.)(rid|request_id|req_id|block_hash|uuid4|uuid1|hex)($|\.)"
)
_CTX_ID_RE = re.compile(r"^(ctx|context|request|req)\.(id|rid)$")


class MetricLabelCardinality(Rule):
    id = "DYN-R005"
    description = "unbounded-cardinality metric label (rid/hash/uuid)"

    def _value_unbounded(self, ctx: LintContext, node: ast.AST):
        """Reason string when the label-value expression is per-request /
        per-object; None when it looks bounded."""
        if isinstance(node, (ast.Name, ast.Attribute)):
            resolved = ctx.resolve(node)
            if resolved is None:
                # `uuid.uuid4().hex`: attribute on a call result
                if isinstance(node, ast.Attribute):
                    return self._value_unbounded(ctx, node.value)
                return None
            if _CTX_ID_RE.match(resolved):
                return f"`{resolved}` is a per-request id"
            if _UNBOUNDED_VALUE_RE.search(resolved):
                return f"`{resolved}` is per-request / per-object"
            return None
        if isinstance(node, ast.Call):
            resolved = ctx.resolve(node.func)
            if resolved and _UNBOUNDED_VALUE_RE.search(resolved):
                return f"`{resolved}(...)` generates a fresh value per call"
            return None
        if isinstance(node, ast.JoinedStr):
            for part in node.values:
                if isinstance(part, ast.FormattedValue):
                    reason = self._value_unbounded(ctx, part.value)
                    if reason:
                        return reason
        return None

    def check_call(self, ctx: LintContext, node: ast.Call) -> None:
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in _METRIC_FACTORIES):
            return
        for kw in node.keywords:
            if kw.arg is None:
                continue  # **labels expansion: unverifiable statically
            if _UNBOUNDED_LABEL_RE.search(kw.arg):
                ctx.report(self.id, node,
                           f"metric label `{kw.arg}` is per-request / "
                           "per-object: every distinct value materializes "
                           "a Prometheus series forever — keep labels "
                           "bounded (model, phase, slo, window) and put "
                           "per-request detail in /debug/routing or the "
                           "flight recorder")
                continue
            reason = self._value_unbounded(ctx, kw.value)
            if reason:
                ctx.report(self.id, node,
                           f"metric label `{kw.arg}` takes {reason}: "
                           "unbounded label values leak a series per "
                           "value — use a bounded label set and put "
                           "per-request detail in /debug/routing or the "
                           "flight recorder")


# Migration and indexer-resync paths talk to workers that are, by
# definition, suspected dead — these are the only call sites where the
# peer being gone is the EXPECTED case, so an unbounded await there is a
# guaranteed wedge, and conflating CancelledError (our own shutdown)
# with transport errors (their death) retries a request the caller
# already abandoned or logs a worker fault on a clean drain.
_XWORKER_ATTRS = {"_dump_fn", "dump_fn", "direct", "round_trip",
                  "request_once"}
_XWORKER_PATH_RE = re.compile(r"(migration|indexer)")


class MigrationAwaitHygiene(Rule):
    id = "DYN-R006"
    description = ("cross-worker await in migration/resync path without "
                   "timeout, or CancelledError conflated with transport "
                   "errors")

    def _in_scope(self, ctx: LintContext) -> bool:
        return _XWORKER_PATH_RE.search(ctx.path) is not None

    def check_await(self, ctx: LintContext, node: ast.Await) -> None:
        if not self._in_scope(ctx) or ctx.timeout_depth > 0:
            return
        val = node.value
        if (isinstance(val, ast.Call)
                and isinstance(val.func, ast.Attribute)
                and val.func.attr in _XWORKER_ATTRS):
            ctx.report(self.id, node,
                       f"`await ...{val.func.attr}()` targets a worker "
                       "this path already suspects is dead: without "
                       "`asyncio.wait_for` the resync/migration slot "
                       "wedges on the corpse forever")

    def check_except(self, ctx: LintContext,
                     node: ast.ExceptHandler) -> None:
        if not self._in_scope(ctx):
            return
        t = node.type
        if t is None:
            ctx.report(self.id, node,
                       "bare `except:` in a migration/resync path catches "
                       "CancelledError along with transport errors — a "
                       "clean shutdown gets handled as a worker fault; "
                       "catch the transport types and re-raise "
                       "CancelledError")
            return
        if isinstance(t, ast.Tuple):
            names = [ctx.resolve(e) or "" for e in t.elts]
            cancelled = [n for n in names if n.endswith("CancelledError")]
            if cancelled and len(names) > len(cancelled):
                ctx.report(self.id, node,
                           "`except` mixes CancelledError with other "
                           "exception types: shutdown (ours) and worker "
                           "death (theirs) need opposite handling — "
                           "split the handlers")
            return
        if (ctx.resolve(t) or "").endswith("BaseException"):
            ctx.report(self.id, node,
                       "`except BaseException` in a migration/resync path "
                       "swallows CancelledError with the transport "
                       "errors; catch Exception (which excludes it) and "
                       "handle cancellation separately")


# A tracing span is a scope, not a value: `tracing.span(...)` returns a
# context manager, and only `with` (or an ExitStack.enter_context, which
# is `with` with the scope hoisted) guarantees the span is closed —
# exported to the ring, error recorded, duration stamped — on EVERY exit
# edge, including the exception and cancellation ones. A span call that
# is never entered silently records nothing; one entered by hand
# (`__enter__` without try/finally) leaks open on the error path, which
# is exactly the path forensics needs the span for.
_SPAN_CALL_RE = re.compile(r"(^|\.)tracing\.span$")


class SpanScopeLeak(Rule):
    id = "DYN-R009"
    description = ("tracing span not scoped by `with`/enter_context "
                   "(never closes on exception exit edges)")

    def _is_span_call(self, ctx: LintContext, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        resolved = ctx.resolve(node.func)
        return bool(resolved and _SPAN_CALL_RE.search(resolved))

    def check_function(self, ctx: LintContext, scope) -> None:
        span_calls: List[ast.Call] = []
        safe: set = set()          # id() of span calls with a safe scope
        assigned: Dict[str, List[ast.Call]] = {}  # name -> its span calls
        safe_names: set = set()    # names entered/propagated somewhere

        def visit(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # nested defs get their own check_function
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    for item in child.items:
                        ce = item.context_expr
                        if self._is_span_call(ctx, ce):
                            safe.add(id(ce))
                        elif isinstance(ce, ast.Name):
                            safe_names.add(ce.id)
                elif isinstance(child, ast.Call):
                    if self._is_span_call(ctx, child):
                        span_calls.append(child)
                    fn = child.func
                    if (isinstance(fn, ast.Attribute)
                            and fn.attr == "enter_context" and child.args):
                        arg = child.args[0]
                        if self._is_span_call(ctx, arg):
                            safe.add(id(arg))
                        elif isinstance(arg, ast.Name):
                            safe_names.add(arg.id)
                elif isinstance(child, ast.Assign):
                    if (self._is_span_call(ctx, child.value)
                            and len(child.targets) == 1
                            and isinstance(child.targets[0], ast.Name)):
                        assigned.setdefault(
                            child.targets[0].id, []).append(child.value)
                elif isinstance(child, ast.Return):
                    # returning the unopened cm propagates the scoping
                    # duty to the caller — their `with` closes it
                    if isinstance(child.value, ast.Name):
                        safe_names.add(child.value.id)
                    elif self._is_span_call(ctx, child.value):
                        safe.add(id(child.value))
                visit(child)

        visit(scope.node)
        for name in safe_names:
            for call in assigned.get(name, ()):
                safe.add(id(call))
        for call in span_calls:
            if id(call) not in safe:
                ctx.report(self.id, call,
                           "`tracing.span(...)` opened without a `with` "
                           "scope: on an exception exit edge the span is "
                           "never closed or exported, so the one request "
                           "forensics needs is the one with no trace — "
                           "use `with tracing.span(...) as s:` (or "
                           "ExitStack.enter_context)")


RUNTIME_RULES = (
    SharedMutableState,
    ExceptPassSwallow,
    MissingRpcTimeout,
    RecorderBlockingIo,
    MetricLabelCardinality,
    MigrationAwaitHygiene,
    SpanScopeLeak,
)
