"""`python -m dynamo_tpu.ext_proc` — Envoy endpoint-picker process.

Deployed next to an Envoy gateway with an `ext_proc` HTTP filter
pointing here (reference deploy/inference-gateway topology): picks the
worker pod per request from live discovery and returns it as the
x-gateway-destination-endpoint header mutation."""

from __future__ import annotations

import argparse
import asyncio

from dynamo_tpu.ext_proc import EndpointPicker, ExtProcServer
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging_util import configure_logging


def parse_args(argv=None):
    p = argparse.ArgumentParser("dynamo_tpu.ext_proc")
    p.add_argument("--port", type=int, default=9002)
    p.add_argument("--endpoint", default="dyn/tpu-worker/generate",
                   help="worker endpoint path to watch")
    p.add_argument("--router-mode", default="least_loaded",
                   choices=["round_robin", "random", "p2c", "least_loaded",
                            "device_aware"])
    p.add_argument("--session-ttl", type=float, default=0.0,
                   help="sticky-session TTL for x-dynamo-session-id (0=off)")
    p.add_argument("--discovery-backend", default=None)
    p.add_argument("--discovery-root", default=None)
    return p.parse_args(argv)


async def async_main(args) -> None:
    configure_logging()
    kw = {}
    if args.discovery_root:
        kw["root"] = args.discovery_root
    runtime = DistributedRuntime(discovery_backend=args.discovery_backend, **kw)
    client = runtime.client(args.endpoint, args.router_mode)
    await client.start()
    server = ExtProcServer(
        EndpointPicker(client, session_ttl_s=args.session_ttl),
        port=args.port,
    )
    await server.start()
    print(f"ext-proc picker on :{server.port}", flush=True)
    try:
        await asyncio.Event().wait()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await server.stop()
        await client.close()
        await runtime.shutdown()


def main(argv=None) -> None:
    try:
        asyncio.run(async_main(parse_args(argv)))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
