"""Envoy ext-proc endpoint picker (inference-gateway integration).

Analog of reference deploy/inference-gateway/ext-proc (Rust): an Envoy
`ext_proc` gRPC filter that picks the destination worker for each HTTP
request and returns it as a header mutation — the Gateway API Inference
Extension (GAIE) endpoint-picker pattern (docs/design-docs/
architecture.md:131-138). Envoy routes the request to the chosen pod's
frontend (each worker pod runs `python -m dynamo_tpu.frontend
--router-mode direct` as its sidecar, same topology as the reference).

Flow per request stream:
  request_headers  → if the picker can decide from headers alone
                     (no model-specific routing), respond immediately
                     with `x-gateway-destination-endpoint`; otherwise
                     CONTINUE and wait for the body
  request_body     → parse the JSON body's "model" (and optionally
                     session id header captured earlier), pick, respond
  no live endpoint → ImmediateResponse 503 (load shed at the edge)

The picker consults the same discovery the serving stack uses: workers
publish `http_address` in instance metadata; selection reuses
PushRouter's policies (round_robin / p2c / least_loaded /
device_aware). Session stickiness honors `x-dynamo-session-id` with a
TTL map, mirroring frontend/session_affinity.py semantics at the edge.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from pathlib import Path
from typing import Dict, Optional, Tuple

import grpc

sys.path.insert(0, str(Path(__file__).parent / "protos"))
import ext_proc_min_pb2 as pb  # noqa: E402

log = logging.getLogger("dynamo_tpu.ext_proc")

SERVICE = "envoy.service.ext_proc.v3.ExternalProcessor"
DEST_HEADER = "x-gateway-destination-endpoint"
SESSION_HEADER = "x-dynamo-session-id"


class EndpointPicker:
    """Selection core: discovery-fed instance set → (address) pick."""

    def __init__(self, client, session_ttl_s: float = 0.0):
        from dynamo_tpu.frontend.session_affinity import (
            MAX_SESSION_AFFINITY_ENTRIES,
        )

        self.client = client  # runtime EndpointClient (watching workers)
        self.session_ttl_s = session_ttl_s
        self.max_sessions = MAX_SESSION_AFFINITY_ENTRIES
        self._sessions: Dict[str, Tuple[int, float]] = {}  # sid -> (iid, exp)
        self._rr = 0

    def _http_address(self, iid: int) -> Optional[str]:
        inst = self.client.instances.get(iid)
        if inst is None:
            return None
        return (inst.metadata or {}).get("http_address")

    def _serves(self, iid: int, model: Optional[str]) -> bool:
        if not model:
            return True
        md = (self.client.instances[iid].metadata or {})
        card = md.get("model_card") or {}
        return model == card.get("name") or model in (card.get("adapters") or [])

    def _eligible(self, model: Optional[str]) -> list:
        """Instances that are routable (publish http_address) AND serve
        the requested model; falls back to any routable instance when
        nothing matches the model filter (the pod's frontend answers
        model-not-found with a proper error body)."""
        routable = [
            i for i in self.client.router.instance_ids
            if self._http_address(i)
        ]
        serving = [i for i in routable if self._serves(i, model)]
        return serving or routable

    def pick(self, model: Optional[str], session_id: Optional[str]) -> Optional[str]:
        router = self.client.router
        now = time.monotonic()
        if session_id and self.session_ttl_s > 0:
            hit = self._sessions.get(session_id)
            if (hit and hit[1] > now and hit[0] in self.client.instances
                    and self._serves(hit[0], model)
                    and self._http_address(hit[0])):
                self._sessions[session_id] = (hit[0], now + self.session_ttl_s)
                return self._http_address(hit[0])
        ids = self._eligible(model)
        if not ids:
            return None
        # honor the router's policy OVER THE ELIGIBLE SET: take its pick
        # when eligible, otherwise the least-loaded eligible instance with
        # a rotating tiebreak (never a fixed ids[0] hotspot)
        iid = None
        try:
            cand, _ = router._pick()
            if cand in ids:
                iid = cand
        except Exception:
            # router not warmed yet (no KV events) — fall through to the
            # least-loaded pick below
            log.debug("router pick failed; using least-loaded fallback",
                      exc_info=True)
        if iid is None:
            self._rr += 1
            n = len(ids)
            iid = min(
                (ids[(self._rr + j) % n] for j in range(n)),
                key=router.load_of,
            )
        if session_id and self.session_ttl_s > 0:
            if len(self._sessions) >= self.max_sessions:
                # hard cap (same bound as frontend/session_affinity.py):
                # drop expired first, then the soonest-to-expire
                self._sessions = {
                    k: v for k, v in self._sessions.items() if v[1] > now
                }
                while len(self._sessions) >= self.max_sessions:
                    oldest = min(self._sessions, key=lambda k: self._sessions[k][1])
                    del self._sessions[oldest]
            self._sessions[session_id] = (iid, now + self.session_ttl_s)
        return self._http_address(iid)


def _headers_dict(http_headers: pb.HttpHeaders) -> Dict[str, str]:
    out = {}
    for h in http_headers.headers.headers:
        v = h.value or (h.raw_value.decode("utf-8", "replace") if h.raw_value else "")
        out[h.key.lower()] = v
    return out


def _route_response(kind: str, address: str) -> pb.ProcessingResponse:
    common = pb.CommonResponse(
        status=pb.CommonResponse.CONTINUE,
        header_mutation=pb.HeaderMutation(set_headers=[
            pb.HeaderValueOption(header=pb.HeaderValue(
                key=DEST_HEADER, raw_value=address.encode()))
        ]),
        clear_route_cache=True,  # the mutation must re-run route matching
    )
    if kind == "headers":
        return pb.ProcessingResponse(
            request_headers=pb.HeadersResponse(response=common))
    return pb.ProcessingResponse(request_body=pb.BodyResponse(response=common))


def _shed_response() -> pb.ProcessingResponse:
    return pb.ProcessingResponse(immediate_response=pb.ImmediateResponse(
        status=pb.HttpStatus(code=503),
        body=json.dumps({"error": {
            "message": "no live worker endpoint", "code": 503}}).encode(),
        details="dynamo_tpu ext-proc: empty endpoint set",
    ))


class ExtProcServer:
    """grpc.aio bidi ExternalProcessor (generic handlers, same
    no-codegen-plugin pattern as the KServe frontend)."""

    def __init__(self, picker: EndpointPicker, host: str = "0.0.0.0",
                 port: int = 9002):
        self.picker = picker
        self.host = host
        self.port = port
        self._server: Optional[grpc.aio.Server] = None

    async def _process(self, request_iter, context):
        session_id = None
        routed = False  # a destination was already chosen for this request
        async for req in request_iter:
            which = req.WhichOneof("request")
            if which == "request_headers":
                hdrs = _headers_dict(req.request_headers)
                session_id = hdrs.get(SESSION_HEADER)
                model = hdrs.get("x-dynamo-model")
                if model or req.request_headers.end_of_stream:
                    # decidable now (explicit model header, or no body
                    # coming): pick immediately
                    addr = self.picker.pick(model, session_id)
                    routed = addr is not None
                    yield (_route_response("headers", addr) if addr
                           else _shed_response())
                else:
                    # wait for the body to learn the model
                    yield pb.ProcessingResponse(
                        request_headers=pb.HeadersResponse(
                            response=pb.CommonResponse(
                                status=pb.CommonResponse.CONTINUE)))
            elif which == "request_body":
                if routed:
                    # already answered at the headers phase (Envoy's
                    # static processing mode may still stream the body):
                    # don't pick twice — it would advance routing state
                    # and could rebind the session
                    yield pb.ProcessingResponse(
                        request_body=pb.BodyResponse(
                            response=pb.CommonResponse(
                                status=pb.CommonResponse.CONTINUE)))
                    continue
                model = None
                try:
                    model = json.loads(
                        req.request_body.body.decode() or "{}").get("model")
                except (ValueError, UnicodeDecodeError):
                    pass
                addr = self.picker.pick(model, session_id)
                routed = addr is not None
                yield (_route_response("body", addr) if addr
                       else _shed_response())
            # response_* phases need no action from the picker

    async def start(self) -> int:
        self._server = grpc.aio.server()
        handlers = {
            "Process": grpc.stream_stream_rpc_method_handler(
                self._process,
                request_deserializer=pb.ProcessingRequest.FromString,
                response_serializer=pb.ProcessingResponse.SerializeToString,
            ),
        }
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE, handlers),)
        )
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        await self._server.start()
        log.info("ext-proc endpoint picker on %s:%d", self.host, self.port)
        return self.port

    async def stop(self) -> None:
        # claim before the await: a concurrent stop() sees None instead of
        # double-stopping the server (DYN-A007)
        server, self._server = self._server, None
        if server is not None:
            await server.stop(grace=5)
