"""`python -m dynamo_tpu.worker` — native TPU engine worker process.

Analog of reference `python -m dynamo.vllm` (components/src/dynamo/vllm/
main.py worker startup call stack, SURVEY.md §3.2), with the JAX engine in
place of vLLM: parse args → build runner/engine → register model card in
discovery → serve the generate endpoint over the request plane.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
from typing import Optional

from dynamo_tpu.engine.engine import InferenceEngine
from dynamo_tpu.engine.model_runner import ModelRunner
from dynamo_tpu.frontend.protocols import ModelCard
from dynamo_tpu.models.config import get_config
from dynamo_tpu.parallel.mesh import MeshConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging_util import configure_logging

log = logging.getLogger("dynamo_tpu.worker")

# attached shm weight stages pinned for the process lifetime (their numpy
# views back device_put and snapshot writes; unmapping would invalidate)
_SHM_STAGES: list = []


def parse_args(argv=None):
    p = argparse.ArgumentParser("dynamo_tpu.worker")
    p.add_argument("--model", default="tiny", help="model config preset name")
    p.add_argument("--checkpoint", default=None,
                   help="HF safetensors checkpoint dir (config derived from its config.json)")
    p.add_argument("--model-name", default=None, help="served model name (default: config name)")
    p.add_argument("--shm-weights", default=None, metavar="NAME",
                   help="host shared-memory weight staging (gpu_memory_"
                        "service analog): attach the staged tree if a "
                        "host peer published it, else load cold and "
                        "publish for peers/restarts")
    p.add_argument("--orbax-cache", default=None,
                   help="params snapshot dir: load if present, else save "
                        "after build (fast worker restarts — the snapshot-"
                        "restore role of the reference's fast-restart path)")
    p.add_argument("--compilation-cache", default=None,
                   help="persistent XLA compilation cache dir (also env "
                        "JAX_COMPILATION_CACHE_DIR): a restarted worker "
                        "reuses compiled step programs instead of paying "
                        "the 20-40s TPU compile again — the TPU analog of "
                        "the reference's CRIU/GMS fast-restart stack "
                        "(SURVEY.md §5.4)")
    p.add_argument("--namespace", default="dyn")
    p.add_argument("--component", default="tpu-worker")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--tokenizer", default="byte", help="'byte' or path to tokenizer.json")
    p.add_argument("--http-address", default=None, metavar="HOST:PORT",
                   help="this pod's direct-mode HTTP frontend address, "
                        "published for the Envoy ext-proc endpoint picker "
                        "(env DYN_HTTP_ADDRESS; operators set it from the "
                        "pod IP)")
    p.add_argument("--engine-sidecar", default=None, metavar="HOST:PORT",
                   help="attach an OUT-OF-PROCESS engine over gRPC "
                        "(python -m dynamo_tpu.sidecar) instead of "
                        "building one in this process")
    p.add_argument("--profiler-port", type=int, default=0,
                   help="start the XLA profiler server on this port for "
                        "TensorBoard capture (0 = off); pair with "
                        "DYN_ENABLE_JAX_TRACE=1 for engine-phase ranges")
    # parallelism (mesh axes)
    p.add_argument("--data-parallel", type=int, default=1)
    p.add_argument("--tensor-parallel", type=int, default=1)
    p.add_argument("--expert-parallel", type=int, default=1)
    p.add_argument("--seq-parallel", type=int, default=1)
    p.add_argument("--pipeline-parallel", type=int, default=1,
                   help="GPipe stages over a pipe mesh axis (dense GQA "
                        "family; composes with no other axis yet)")
    # KV cache
    p.add_argument("--num-pages", type=int, default=512)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--max-seq-len", type=int, default=4096)
    p.add_argument("--host-kv-blocks", type=int, default=0,
                   help="G2 host-DRAM KV tier capacity in blocks (0 = off)")
    p.add_argument("--disk-kv-blocks", type=int, default=0,
                   help="G3 disk KV tier capacity in blocks (needs G2 on)")
    p.add_argument("--disk-kv-root", default=None,
                   help="G3 tier directory (default: a temp dir)")
    p.add_argument("--obj-kv-root", default=None,
                   help="G4 object-store root (shared mount; enables the "
                        "terminal KV tier)")
    p.add_argument("--kv-tier-quantize", action="store_true",
                   help="store demoted G2/G3/G4 blocks as int8 + per-"
                        "(token, head) scales (~1.9x blocks per byte at "
                        "D=128); G1 device hits stay full precision")
    p.add_argument("--onboard-layer-groups", type=int, default=1,
                   help="stream tier onboarding in this many layer-group "
                        "slabs so prefill starts after the first slab "
                        "lands (1 = whole-sequence import)")
    p.add_argument("--prefetch", action="store_true",
                   help="router-hinted predictive KV promotion (needs "
                        "--host-kv-blocks > 0); advertises kv_prefetch so "
                        "routers send tier-promotion hints ahead of dispatch")
    p.add_argument("--prefetch-max-inflight", type=int, default=4,
                   help="max concurrent G3->G2 disk reads per worker")
    p.add_argument("--prefetch-bandwidth-mbps", type=float, default=0.0,
                   help="promotion bandwidth budget in MB/s (0 = unlimited)")
    p.add_argument("--prefetch-hint-ttl-s", type=float, default=10.0,
                   help="drop a hint whose request never arrives after this")
    p.add_argument("--prefetch-pin-ttl-s", type=float, default=5.0,
                   help="how long promoted blocks stay pinned against eviction")
    # batching
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--chunk-size", type=int, default=512)
    p.add_argument("--mixed-prefill-tokens", type=int, default=256,
                   help="per-iteration prefill token POOL when co-scheduled "
                        "with decode: fair-shared across up to "
                        "--mixed-prefill-seqs packed chunks from distinct "
                        "sequences (0 = strict prefill-first). Align with a "
                        "prefill bucket: the set pads to the next bucket")
    p.add_argument("--mixed-prefill-seqs", type=int, default=8,
                   help="max distinct prefills packed per iteration "
                        "(1 = legacy single-chunk MixedPlan)")
    p.add_argument("--mixed-min-chunk", type=int, default=16,
                   help="fair-share floor: each packed sequence is offered "
                        "at least this many prefill tokens per iteration")
    # speculative decoding
    p.add_argument("--draft-model", default=None,
                   help="draft model config preset (enables speculative decoding)")
    p.add_argument("--draft-checkpoint", default=None,
                   help="HF safetensors dir for draft weights")
    p.add_argument("--spec-gamma", type=int, default=4,
                   help="draft tokens proposed per target verify pass")
    p.add_argument("--spec-draft-model", default=None, metavar="PRESET",
                   help="alias for --draft-model: route speculation through "
                        "a separate draft model instead of n-gram lookup")
    p.add_argument("--spec-ngram", action="store_true",
                   help="draft-model-free speculation: propose the next K "
                        "tokens by prompt/history n-gram lookup and verify "
                        "them as ragged rows of the mixed dispatch")
    p.add_argument("--spec-k", type=int, default=4,
                   help="n-gram draft length K (verify rows are K+1 tokens)")
    p.add_argument("--spec-max-tokens", type=int, default=0,
                   help="per-iteration cap on drafted tokens admitted to "
                        "the verify dispatch (0 = the leftover mixed "
                        "prefill token budget)")
    # multi-LoRA
    p.add_argument("--lora", action="append", default=[],
                   help="serve a LoRA adapter: NAME=<peft_dir> (HF PEFT "
                        "safetensors) or bare NAME (random factors, dev). "
                        "Repeatable; each name becomes a servable model.")
    p.add_argument("--lora-rank", type=int, default=8,
                   help="rank for randomly-initialized dev adapters")
    p.add_argument("--lora-slots", type=int, default=0,
                   help="EXTRA free adapter slots beyond --lora specs, for "
                        "runtime registration via the rl load_adapter op")
    p.add_argument("--quantize", default=None, choices=[None, "int8", "fp8"],
                   help="weight-only quantization (halves decode HBM weight "
                        "traffic; fp8 = e4m3 per-channel)")
    p.add_argument("--kv-quantize", default=None, choices=[None, "int8"],
                   help="int8 KV-cache pools with per-vector scales (~48%% "
                        "less KV stream per decode step; transfers/offload "
                        "stay bf16 so mixed fleets interoperate)")
    # infra
    p.add_argument("--disagg-role", default=None, choices=[None, "prefill", "decode", "both"],
                   help="disaggregation role; prefill workers park KV for decode pulls")
    p.add_argument("--disagg-chunk-pages", type=int, default=16,
                   help="P->D KV pull chunk size in pages (0 = one message)")
    p.add_argument("--shadow", action="store_true",
                   help="active/passive failover: load+warm the engine but "
                        "only register when the active worker's discovery "
                        "record disappears (shadow-engine-failover analog)")
    p.add_argument("--vision", action="store_true",
                   help="serve a vision encoder (multimodal EPD): publishes "
                        "the encode endpoint + vision card info")
    p.add_argument("--image-token-id", type=int, default=None,
                   help="placeholder token id (default: vocab_size - 1)")
    p.add_argument("--status-port", type=int, default=0,
                   help="serve /live /health /metrics on this port (0 = off)")
    p.add_argument("--digest-period", type=float, default=2.0,
                   help="fleet digest publish period in seconds (0 = off; "
                        "docs/observability.md Fleet view)")
    # flight recorder (observability; docs/observability.md)
    p.add_argument("--recorder-size", type=int, default=4096,
                   help="flight-recorder ring capacity in iterations "
                        "(0 = recorder off)")
    p.add_argument("--anomaly-k", type=float, default=4.0,
                   help="iteration wall time > EWMA*k fires the anomaly "
                        "trigger (dump + optional profile window)")
    p.add_argument("--anomaly-dump-dir", default=None,
                   help="directory for anomaly ring dumps (unset = no dumps)")
    p.add_argument("--anomaly-dump-last-n", type=int, default=256,
                   help="ring records written per anomaly dump")
    p.add_argument("--anomaly-profile-ms", type=int, default=0,
                   help="jax.profiler capture window on anomaly, in ms "
                        "(0 = off; traces land under the dump dir)")
    p.add_argument("--sanitize", action="store_true",
                   help="arm the runtime sanitizer: transfer_guard around "
                        "steady-state dispatches, recompile tripwire, "
                        "lock-order recorder, task/pool audits (DYN_SAN=1 "
                        "is the env equivalent)")
    p.add_argument("--discovery-backend", default=None)
    p.add_argument("--discovery-root", default=None)
    p.add_argument("--request-plane", default=None, choices=[None, "tcp", "nats"],
                   help="RPC transport: tcp (default) or nats broker "
                        "subjects (env DYN_REQUEST_PLANE / DYN_NATS_URL)")
    # multi-host worker group (parallel/multihost.py): N processes form one
    # logical worker over a single jax.distributed global mesh. Process 0
    # serves; 1..N-1 replay its step stream. Mesh axis sizes above refer to
    # the GLOBAL device count.
    p.add_argument("--mh-coordinator", default=None,
                   help="host:port of the group coordinator (rank 0); "
                        "enables multi-host mode")
    p.add_argument("--mh-num-processes", type=int, default=1)
    p.add_argument("--mh-process-id", type=int, default=0)
    p.add_argument("--mh-step-port", type=int, default=0,
                   help="leader step-plane port (required when "
                        "--mh-num-processes > 1)")
    p.add_argument("--mh-local-devices", type=int, default=None,
                   help="virtual CPU devices per process (tests)")
    return p.parse_args(argv)


def _lora_kwargs(args, config) -> dict:
    """Load every --lora spec up front: duplicate names are an error (a
    repeat would silently keep the first checkpoint's weights), and the
    stacked tree's targets are the union of what the checkpoints actually
    adapt (a PEFT adapter touching MLP projections must not be silently
    half-applied)."""
    extra = int(getattr(args, "lora_slots", 0) or 0)
    if not args.lora:
        if extra > 0:
            # dynamic-only: free slots for rl load_adapter, nothing at boot
            args._lora_factors = []
            return {"lora_slots": extra, "lora_rank": args.lora_rank}
        return {}
    from dynamo_tpu.models import lora as lora_mod

    names = [s.partition("=")[0] for s in args.lora]
    dupes = {n for n in names if names.count(n) > 1}
    if dupes:
        raise SystemExit(f"duplicate --lora adapter names: {sorted(dupes)}")
    loaded = []
    targets = set()
    for i, spec in enumerate(args.lora):
        name, _, path = spec.partition("=")
        if path:
            factors = lora_mod.load_peft_adapter(path, config)
        else:
            factors = lora_mod.random_adapter(config, rank=args.lora_rank, seed=100 + i)
        targets.update(k[:-2] for k in factors)
        loaded.append((name, factors))
    # mixed-rank checkpoints share one stacked tree: zero-pad factors up to
    # the max rank (padded rows/cols contribute nothing to A @ B)
    import numpy as np

    rank = max(
        [args.lora_rank] + [f[k].shape[-1] for _, f in loaded for k in f if k.endswith("_a")]
    )
    for _, factors in loaded:
        for k, arr in list(factors.items()):
            r = arr.shape[-1] if k.endswith("_a") else arr.shape[-2]
            if r == rank:
                continue
            pad = [(0, 0)] * arr.ndim
            pad[-1 if k.endswith("_a") else -2] = (0, rank - r)
            factors[k] = np.pad(arr, pad)
    args._lora_factors = loaded
    return {
        "lora_slots": len(loaded) + extra,
        "lora_rank": rank,
        "lora_targets": tuple(sorted(targets)),
    }


def enable_compilation_cache(path: Optional[str]) -> Optional[str]:
    """Worker-facing wrapper over dynamo_tpu.enable_compilation_cache
    (kept importable from here for the CLI's callers/tests)."""
    import dynamo_tpu

    out = dynamo_tpu.enable_compilation_cache(path)
    if out:
        log.info("persistent compilation cache at %s", out)
    return out


def build_runner(args, save_snapshot_ok: bool = True) -> tuple[ModelRunner, "object"]:
    """Construct the ModelRunner (and its model config) from CLI args —
    shared by the serving leader and multi-host follower replicas, which
    must build bit-identical runners (same config/seed/checkpoint).
    save_snapshot_ok=False suppresses the cold orbax-cache write: in a
    group every process sees the same args, and N concurrent writers
    would corrupt one snapshot directory — only the leader writes."""
    import os

    # resolve the model CONFIG first (config.json only — no weights);
    # every warm tier below validates against it
    if args.checkpoint:
        from dynamo_tpu.engine.hub import fetch_model
        from dynamo_tpu.engine.weights import config_from_hf

        # --checkpoint accepts hub repo ids too (hf://org/name or
        # org/name); local dirs pass through untouched (hub.rs role)
        args.checkpoint = fetch_model(args.checkpoint, config_only=True)
        config = config_from_hf(args.checkpoint, name=args.model_name or args.model)
    else:
        config = get_config(args.model)

    params = None
    # warm tier 1 — host-shm staging (gpu_memory_service analog,
    # engine/shm_weights.py): a peer on this host (or our own previous
    # incarnation) already holds the tree in /dev/shm — attach zero-copy
    # views and skip disk entirely. The stage carries a model-config
    # fingerprint; a stale stage for a DIFFERENT model under the same
    # name is ignored (and later REPLACED by our publish — the fallback
    # is free: just load cold).
    shm_stage = None
    shm_meta = {
        "model": config.name, "vocab": config.vocab_size, "dim": config.dim,
        "n_layers": config.n_layers, "n_heads": config.n_heads,
        "n_kv_heads": config.n_kv_heads,
    }
    if getattr(args, "shm_weights", None):
        from dynamo_tpu.engine import shm_weights

        stage = shm_weights.attach(args.shm_weights)
        if stage is not None:
            if stage.meta == shm_meta:
                log.info(
                    "fast restart: attached %d staged arrays (%.1f MB shm) "
                    "as %r", stage.n_arrays, stage.nbytes / 1e6,
                    args.shm_weights,
                )
                params = stage.params
                shm_stage = stage
                # pin the mapping for the life of the process: the views
                # feed device_put now and any later snapshot write
                _SHM_STAGES.append(stage)
            else:
                log.warning(
                    "shm stage %r fingerprint %s does not match model "
                    "config %s; loading cold (our publish will replace "
                    "the stale stage)", args.shm_weights, stage.meta,
                    shm_meta,
                )
                stage.close()
    # warm tier 2 — orbax snapshot: short-circuits the expensive HF
    # checkpoint load (that is the whole point of fast restart)
    snapshot_present = bool(
        args.orbax_cache
        and os.path.isdir(args.orbax_cache)
        and os.listdir(args.orbax_cache)
    )
    if params is None and snapshot_present:
        from dynamo_tpu.engine.weights import load_orbax

        log.info("fast restart: loading params snapshot %s", args.orbax_cache)
        params = load_orbax(args.orbax_cache)
        embed = params.get("embed")
        if embed is None or tuple(embed.shape) != (config.vocab_size, config.dim):
            raise SystemExit(
                f"snapshot {args.orbax_cache} does not match model config "
                f"{config.name} (embed {getattr(embed, 'shape', None)} vs "
                f"{(config.vocab_size, config.dim)}); delete the snapshot "
                "to rebuild it"
            )
    # cold — HF checkpoint weights
    if params is None and args.checkpoint:
        from dynamo_tpu.engine.hub import fetch_model
        from dynamo_tpu.engine.weights import load_hf_checkpoint

        args.checkpoint = fetch_model(args.checkpoint)  # now the weights
        params = load_hf_checkpoint(args.checkpoint, config)
    # re-warm whichever tier is empty: the snapshot is written even when
    # params came from shm (a host reboot clears /dev/shm; disk must not
    # depend on which peer happened to boot first), and the shm stage is
    # published from any cold/snapshot load (publish replaces atomically,
    # so a stale other-model stage under our name is repaired here too)
    save_snapshot = bool(
        args.orbax_cache and params is not None and not snapshot_present
    )
    if (getattr(args, "shm_weights", None) and shm_stage is None
            and params is not None):
        from dynamo_tpu.engine import shm_weights

        shm_weights.publish(args.shm_weights, params, meta=shm_meta)
    mesh = MeshConfig(
        data=args.data_parallel,
        model=args.tensor_parallel,
        expert=args.expert_parallel,
        seq=args.seq_parallel,
        pipe=getattr(args, "pipeline_parallel", 1),
    )
    max_pages_per_seq = -(-args.max_seq_len // args.page_size)
    draft_config = draft_params = None
    if getattr(args, "spec_draft_model", None) and not args.draft_model:
        args.draft_model = args.spec_draft_model
    if args.draft_model or args.draft_checkpoint:
        if args.draft_checkpoint:
            from dynamo_tpu.engine.weights import config_from_hf, load_hf_checkpoint

            draft_config = config_from_hf(
                args.draft_checkpoint, name=args.draft_model or "draft"
            )
            draft_params = load_hf_checkpoint(args.draft_checkpoint, draft_config)
        else:
            draft_config = get_config(args.draft_model)
    runner = ModelRunner(
        config,
        mesh,
        num_pages=args.num_pages,
        page_size=args.page_size,
        max_pages_per_seq=max_pages_per_seq,
        params=params,
        draft_config=draft_config,
        draft_params=draft_params,
        spec_gamma=args.spec_gamma,
        quantize=args.quantize,
        kv_quantize=args.kv_quantize,
        **_lora_kwargs(args, config),
    )
    for name, factors in getattr(args, "_lora_factors", []):
        runner.register_adapter(name, factors)
    if save_snapshot and save_snapshot_ok:
        from dynamo_tpu.engine.weights import save_orbax

        log.info("writing params snapshot to %s", args.orbax_cache)
        save_orbax(params, args.orbax_cache)
    return runner, config


def build_engine(args, runner=None) -> tuple[InferenceEngine, ModelCard]:
    if runner is None:
        runner, config = build_runner(args)
    else:
        # multi-host leader: runner was built (and wrapped) by the caller
        config = runner.config
    mesh = runner.mesh_config
    engine = InferenceEngine(
        runner, max_batch=args.max_batch, chunk_size=args.chunk_size,
        mixed_prefill_tokens=getattr(args, "mixed_prefill_tokens", 256),
        mixed_prefill_seqs=getattr(args, "mixed_prefill_seqs", 8),
        mixed_min_chunk=getattr(args, "mixed_min_chunk", 16),
        host_kv_blocks=args.host_kv_blocks,
        disk_kv_blocks=args.disk_kv_blocks, disk_kv_root=args.disk_kv_root,
        obj_kv_root=args.obj_kv_root,
        kv_tier_quantize=getattr(args, "kv_tier_quantize", False),
        onboard_layer_groups=getattr(args, "onboard_layer_groups", 1),
        prefetch=getattr(args, "prefetch", False),
        prefetch_max_inflight=getattr(args, "prefetch_max_inflight", 4),
        prefetch_bandwidth_mbps=getattr(args, "prefetch_bandwidth_mbps", 0.0),
        prefetch_hint_ttl_s=getattr(args, "prefetch_hint_ttl_s", 10.0),
        prefetch_pin_ttl_s=getattr(args, "prefetch_pin_ttl_s", 5.0),
        tokenizer_spec=args.tokenizer,
        recorder_size=getattr(args, "recorder_size", 4096),
        anomaly_k=getattr(args, "anomaly_k", 4.0),
        anomaly_dump_dir=getattr(args, "anomaly_dump_dir", None),
        anomaly_dump_last_n=getattr(args, "anomaly_dump_last_n", 256),
        anomaly_profile_ms=getattr(args, "anomaly_profile_ms", 0),
        spec_ngram=getattr(args, "spec_ngram", False),
        spec_k=getattr(args, "spec_k", 4),
        spec_max_tokens=getattr(args, "spec_max_tokens", 0),
        sanitize=getattr(args, "sanitize", None) or None,
    )
    if getattr(args, "shm_weights", None) or args.orbax_cache:
        # RL weight hot-swap: after update_weights the WARM TIERS hold a
        # superseded policy — a crash-restart would attach the stale shm
        # stage, or (shm gone) reload the old orbax snapshot from disk
        # and republish THAT, serving the old policy next to refreshed
        # peers. On every swap: drop the shm stage and refresh the orbax
        # cache from the new snapshot (atomic dir swap), so the restart
        # invariant holds: the warm tiers always contain the weights
        # being served. (Without --orbax-cache a restart falls back to
        # the ORIGINAL checkpoint — choose warm tiers accordingly for RL
        # workers.)
        _inner_update = engine.update_weights
        _stage_name = getattr(args, "shm_weights", None)
        _cache_dir = args.orbax_cache

        def _refresh_snapshot(src: str) -> None:
            import os
            import shutil as _sh
            import tempfile as _tf

            if os.path.realpath(src) == os.path.realpath(_cache_dir):
                return
            parent = os.path.dirname(os.path.abspath(_cache_dir)) or "."
            tmp = _tf.mkdtemp(prefix=".orbax_swap_", dir=parent)
            new = os.path.join(tmp, "new")
            _sh.copytree(src, new)
            old = os.path.join(tmp, "old")
            if os.path.exists(_cache_dir):
                os.rename(_cache_dir, old)
            os.rename(new, _cache_dir)
            _sh.rmtree(tmp, ignore_errors=True)

        async def _update_and_invalidate(path: str) -> int:
            import asyncio as _aio

            version = await _inner_update(path)
            if _stage_name:
                from dynamo_tpu.engine import shm_weights as _shm

                _shm.unlink(_stage_name)
            if _cache_dir:
                try:
                    await _aio.to_thread(_refresh_snapshot, path)
                except Exception:
                    log.exception(
                        "orbax cache refresh from %s failed — a restart "
                        "would reload the superseded snapshot", path,
                    )
            log.info("warm tiers refreshed after weight update v%d", version)
            return version

        engine.update_weights = _update_and_invalidate
    vision = None
    if args.vision:
        from dynamo_tpu.models.vision import TINY_VISION, VisionConfig

        import dataclasses as _dc

        vcfg = _dc.replace(
            TINY_VISION if config.dim <= 256 else VisionConfig(),
            out_dim=config.dim,
        )
        args._vision_config = vcfg
        vision = {
            "image_token_id": (
                args.image_token_id if args.image_token_id is not None
                else config.vocab_size - 1
            ),
            "n_image_tokens": vcfg.n_patches,
            "image_size": vcfg.image_size,
        }
    card = ModelCard(
        name=args.model_name or config.name,
        tokenizer=args.tokenizer,
        context_length=args.max_seq_len,
        kv_block_size=args.page_size,
        adapters=[s.partition("=")[0] for s in args.lora],
        vision=vision,
        runtime_config={
            "mesh": list(mesh.shape),
            "num_pages": args.num_pages,
            "max_batch": args.max_batch,
        },
    )
    return engine, card


async def async_main(args) -> None:
    configure_logging()
    if args.profiler_port:
        from dynamo_tpu.runtime.annotations import start_profiler_server

        start_profiler_server(args.profiler_port)
    kw = {}
    if args.discovery_root:
        kw["root"] = args.discovery_root
    if getattr(args, "request_plane", None):
        kw["request_plane"] = args.request_plane
    runtime = DistributedRuntime(discovery_backend=args.discovery_backend, **kw)
    spec = getattr(args, "_mh_spec", None)
    plane = None
    if getattr(args, "engine_sidecar", None):
        # out-of-process engine (reference lib/sidecar role): this worker
        # owns discovery + request plane; generate calls forward over gRPC
        from dynamo_tpu.frontend.protocols import ModelCard
        from dynamo_tpu.sidecar import SidecarEngine

        if args.vision:
            raise SystemExit(
                "--vision requires an in-process engine (the encoder runs "
                "next to the model); drop it or run without --engine-sidecar"
            )
        engine = SidecarEngine(args.engine_sidecar)
        health = await engine.health(timeout=30.0)
        card = ModelCard(
            name=args.model_name or health.get("model") or args.model,
            tokenizer=args.tokenizer,
            context_length=args.max_seq_len,
            kv_block_size=args.page_size,
        )
    elif spec is not None:
        # multi-host leader: accept the follower connections first, then
        # build the runner (followers build theirs concurrently) and wrap
        # it so every device-touching call replays group-wide
        from dynamo_tpu.parallel import multihost as mh

        plane = mh.StepPlaneLeader(spec.step_port, spec.num_processes - 1)
        plane.wait_followers()
        # weight load / shm attach polls and compiles: off the loop so
        # startup never stalls heartbeats already running on it (DYN-A001)
        leader_runner, _ = await asyncio.to_thread(build_runner, args)
        engine, card = await asyncio.to_thread(
            build_engine, args, runner=mh.ReplicatingRunner(leader_runner, plane)
        )
    else:
        engine, card = await asyncio.to_thread(build_engine, args)
    group_broken_box = [False]
    stop_box = []  # filled with (loop, stop_ev) once serving starts
    if plane is not None and hasattr(engine, "on_fatal"):
        # multi-host group leader: a dead follower is unrecoverable
        # (GroupBroken) — exit nonzero so the supervisor restarts the
        # whole group. Wired BEFORE the worker serves: a request hitting
        # an already-broken group on the very first step must still
        # trigger the exit path.
        def _group_fatal():
            group_broken_box[0] = True
            if stop_box:
                lp, ev = stop_box[0]
                lp.call_soon_threadsafe(ev.set)

        engine.on_fatal(_group_fatal)
    if args.vision:
        import jax

        from dynamo_tpu.frontend.encoder import ENCODE_ENDPOINT, EncodeEngine
        from dynamo_tpu.models import vision as vision_mod

        vparams = vision_mod.init_params(args._vision_config, jax.random.PRNGKey(7))
        await runtime.serve_endpoint(
            f"{args.namespace}/{ENCODE_ENDPOINT}",
            EncodeEngine(args._vision_config, vparams),
        )
    status = None
    if args.status_port:
        from dynamo_tpu.runtime.status import StatusServer

        status = StatusServer(runtime, port=args.status_port)
        # SidecarEngine has no step thread — the remote engine's health is
        # its own; this check then only covers the local process
        status.add_check(
            "engine", lambda: getattr(engine, "_thread", True) is not None
        )
        _rec = getattr(engine, "recorder", None)
        if _rec is not None and _rec.enabled:
            from dynamo_tpu.runtime.flight_recorder import to_chrome_trace

            status.add_timeline(
                lambda last_n=None: to_chrome_trace(_rec.snapshot(last_n))
            )
        _san = getattr(engine, "sanitizer", None)
        if _san is not None:
            # GET /debug/sanitizer: violations + counters (layout_checked
            # proves the DYN-S layout guard ran at the warm transition)
            status.add_debug("sanitizer", lambda _q: _san.report())
        await status.start()
    from dynamo_tpu.worker_common import serve_worker

    path = f"{args.namespace}/{args.component}/{args.endpoint}"
    shadow = None
    worker = None
    if args.shadow:
        # active/passive failover (runtime/shadow.py): the engine above is
        # already warm (weights + jit + pools); hold it out of discovery
        # until the active worker's record disappears, then register — the
        # restart skips the model load, matching the reference's
        # shadow-engine-failover recovery path.
        from dynamo_tpu.runtime.shadow import ShadowServer

        async def _activate():
            return await serve_worker(
                runtime, engine, card,
                namespace=args.namespace, component=args.component,
                endpoint=args.endpoint, disagg_role=args.disagg_role,
                disagg_chunk_pages=args.disagg_chunk_pages,
                http_address=args.http_address,
                digest_period_s=args.digest_period,
            )

        shadow = ShadowServer(
            runtime, path, activate=_activate, metadata={"model": card.name}
        )
        await shadow.start()
        print(f"worker standing by as shadow for {path}", flush=True)
    else:
        worker = await serve_worker(
            runtime, engine, card,
            namespace=args.namespace, component=args.component, endpoint=args.endpoint,
            disagg_role=args.disagg_role,
            disagg_chunk_pages=args.disagg_chunk_pages,
            http_address=args.http_address,
            digest_period_s=args.digest_period,
        )
        print(f"worker serving {card.name} at {path}", flush=True)
    promotion_failed = False
    group_broken = False
    try:
        stop_ev = asyncio.Event()
        import signal

        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                loop.add_signal_handler(sig, stop_ev.set)
            except NotImplementedError:  # pragma: no cover
                pass
        if shadow is not None:
            # a failed promotion must kill the process (exit nonzero so
            # the supervisor restarts it) — not leave an invisible zombie
            # that neither serves nor stands by
            shadow.promoted.add_done_callback(
                lambda f: stop_ev.set() if f.exception() is not None else None
            )
        stop_box.append((loop, stop_ev))
        if group_broken_box[0]:
            stop_ev.set()  # broke before we started waiting
        await stop_ev.wait()
        group_broken = group_broken_box[0]
        if group_broken:
            print("worker group BROKEN; exiting for restart", flush=True)
        elif (shadow is not None and shadow.promoted.done()
                and shadow.promoted.exception() is not None):
            promotion_failed = True
            print("shadow promotion FAILED; exiting", flush=True)
        else:
            print("draining...", flush=True)
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        # teardown steps are individually guarded: after a group break the
        # jax.distributed coordination service is already unhealthy and a
        # raising cleanup step must not mask the intended exit code
        async def _safe(coro):
            try:
                await coro
            except Exception:
                log.exception("teardown step failed")

        if shadow is not None:
            await _safe(shadow.stop())
            if shadow.promoted.done() and shadow.promoted.exception() is None:
                worker = shadow.promoted.result()
        if worker is not None:
            await _safe(worker.stop())
        if status is not None:
            await _safe(status.stop())
        if plane is not None:
            try:
                plane.close()  # releases followers from their replay loops
            except Exception:
                # best-effort: after a group break the plane socket may
                # already be dead; the exit path below is what matters
                log.debug("step-plane close failed during teardown",
                          exc_info=True)
        await _safe(runtime.shutdown())
    if promotion_failed:
        raise SystemExit(1)
    if group_broken:
        # bypass interpreter teardown: the coordination service raises on
        # atexit with a dead rank, which would repaint the exit code
        import os as _os
        import sys as _sys

        _sys.stdout.flush()
        _os._exit(13)


def main(argv=None) -> None:
    import dynamo_tpu

    dynamo_tpu.ensure_platform()
    args = parse_args(argv)
    # before ANY jit: every process (leader, followers, single) must see
    # the cache so a restarted replica skips recompilation
    enable_compilation_cache(args.compilation_cache)
    if args.mh_coordinator and args.mh_num_processes > 1:
        from dynamo_tpu.parallel import multihost as mh

        if not args.mh_step_port:
            raise SystemExit("--mh-step-port is required for a multi-host group")
        spec = mh.MultihostSpec(
            coordinator=args.mh_coordinator,
            num_processes=args.mh_num_processes,
            process_id=args.mh_process_id,
            step_port=args.mh_step_port,
            local_devices=args.mh_local_devices,
        )
        mh.initialize(spec)
        if not spec.is_leader:
            configure_logging()
            # connect BEFORE building: runner construction device_puts over
            # the global mesh, which needs every process participating —
            # the leader only starts ITS build once all followers are
            # connected, so connecting late deadlocks the group
            sock = mh.follower_connect(
                spec.leader_host, spec.step_port, spec.process_id
            )
            runner, _ = build_runner(args, save_snapshot_ok=False)
            print(f"follower {spec.process_id} replaying for {spec.coordinator}",
                  flush=True)
            try:
                mh.follower_loop(runner, sock)
            finally:
                sock.close()
            return
        args._mh_spec = spec
    try:
        asyncio.run(async_main(args))
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
