"""Multi-tier KV block manager (analog of reference KVBM v2 crates,
lib/kvbm-{logical,physical,engine}: G1 = TPU HBM paged pool, G2 = host
DRAM, G3 = NVMe (later), G4 = object store (later))."""
