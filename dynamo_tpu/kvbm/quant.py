"""Shared int8 tier codec for demoted KV blocks (G2/G3/G4).

The ragged/decode kernels already consume int8 KV pools in the dict
convention {"q": int8 [..., D], "s": f32 [...]} with one symmetric scale
per (token, head) vector (models/quant.py kv_quantize). This module is
the same fold in plain numpy — no jax import, so mocker workers and the
disk writer thread can run it — applied per BLOCK at the demotion
boundary: a block quantizes once when it leaves the device tier and the
int8+scales pair is what G2 DRAM, G3 files, and G4 objects store.

Why it matters: a bf16/fp16 KV vector is 2*D bytes; quantized it is
D + 4 bytes (int8 payload + one f32 scale). At D=128 that is 132 vs 256
bytes — 1.94x effective capacity for every cold tier at the same byte
budget, which is the difference between holding a prefix cache for a
user population and thrashing it.

Promotion either dequantizes back to the pool dtype (dense-pool runners,
the disagg wire — KV_WIRE_LAYOUT_VERSION stays dense so heterogeneous
workers interoperate) or passes q/s through natively when the runner's
device pool is itself int8-quantized (kv_quantize="int8"): same fold,
same layout, zero requantization error on the hot path.

A quantized block-side array is the dict {"q": int8 [L, PS, Hk, D],
"s": float32 [L, PS, Hk], "dt": "<original dtype str>"} — "dt" records
the pre-quantization dtype so promotion restores exactly what the
runner exported.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np


def _np_dtype(name: str) -> np.dtype:
    if "bfloat16" in name:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def is_quantized_block(x: Any) -> bool:
    """True for a tier-codec quantized array (dict with q/s leaves)."""
    return isinstance(x, dict) and "q" in x and "s" in x


def quantize_block(x: np.ndarray) -> Dict[str, Any]:
    """Dense [..., D] → {"q": int8 [..., D], "s": f32 [...], "dt": str}.

    Bit-exact match of the device-side fold (models/quant.py
    kv_quantize): amax over the head dim in f32, s = max(amax, 1e-8)/127,
    q = clip(round(x/s), -127, 127). np.round and jnp.round both use
    round-half-to-even, so a tier-quantized block and a device-quantized
    page of the same data carry identical q/s.
    """
    xf = np.asarray(x).astype(np.float32)
    amax = np.max(np.abs(xf), axis=-1)
    s = (np.maximum(amax, 1e-8) / 127.0).astype(np.float32)
    q = np.clip(np.round(xf / s[..., None]), -127, 127).astype(np.int8)
    return {"q": q, "s": s, "dt": str(np.asarray(x).dtype)}


def dequantize_block(d: Dict[str, Any], dtype: Optional[Any] = None) -> np.ndarray:
    """Inverse of quantize_block → dense [..., D] in the recorded dtype
    (or an explicit override)."""
    dt = _np_dtype(str(dtype)) if dtype is not None else _np_dtype(d.get("dt", "float32"))
    return (d["q"].astype(np.float32) * d["s"][..., None]).astype(dt)


def maybe_quantize(x: Optional[Any]) -> Optional[Any]:
    """Quantize a dense array; pass through None (sim hash-only blocks)
    and already-quantized dicts (re-demotion down the ladder must not
    double-quantize)."""
    if x is None or is_quantized_block(x):
        return x
    return quantize_block(x)


def maybe_dequantize(x: Optional[Any], dtype: Optional[Any] = None) -> Optional[Any]:
    """Densify a tier array: quantized dicts dequantize, dense arrays and
    None pass through."""
    if is_quantized_block(x):
        return dequantize_block(x, dtype)
    return x


def block_nbytes(x: Optional[Any]) -> int:
    """Actual stored bytes of a tier array — int8 payload + f32 scales
    for quantized blocks, raw nbytes for dense, 0 for hash-only."""
    if x is None:
        return 0
    if is_quantized_block(x):
        return int(x["q"].nbytes) + int(x["s"].nbytes)
    return int(np.asarray(x).nbytes)


def quantized_ratio(head_dim: int, itemsize: int = 2) -> float:
    """Stored-bytes ratio quantized/dense for a given head dim and dense
    itemsize: (D + 4) / (D * itemsize). Used for hash-only (sim) byte
    accounting where no real array exists to measure."""
    return (head_dim + 4.0) / (head_dim * float(itemsize))


def roundtrip_error_bound(x: np.ndarray) -> float:
    """Max absolute error the symmetric int8 fold can introduce for this
    data: half a quantization step per vector. Tests use it to bound
    rehydration drift honestly rather than with a magic tolerance."""
    amax = np.max(np.abs(np.asarray(x).astype(np.float32)), axis=-1)
    s = np.maximum(amax, 1e-8) / 127.0
    return float(np.max(s) * 0.5)


def pair_nbytes(k: Optional[Any], v: Optional[Any]) -> int:
    return block_nbytes(k) + block_nbytes(v)


def stacked_to_blocks(
    k: Optional[np.ndarray], v: Optional[np.ndarray], i: int
) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
    """Slice block i out of stacked [L, n, PS, Hk, D] wire arrays (page
    axis 1), contiguously — the per-block unit every tier stores."""
    kb = np.ascontiguousarray(k[:, i]) if k is not None else None
    vb = np.ascontiguousarray(v[:, i]) if v is not None else None
    return kb, vb
