"""Predictive KV prefetch plane: router-hinted tier promotion.

The KV router scores a request against every worker's device AND
lower-tier (G2/G3/G4) residency before dispatch, so it knows what the
chosen worker will need seconds before the engine does. This module
spends that lead time: the router emits a `kv_prefetch` hint over the
request plane ahead of the request itself, and the worker's
PrefetchManager promotes the hinted blocks up the KVBM ladder while the
request is still queueing —

    G3 → G2: file reads ride the disk pool's existing writer thread
             (DiskKvPool.read_block_async), so the step thread never
             blocks on file IO; results land back on the step thread
             via the engine inbox.
    G2 → G1: `runner.import_pages` on the step thread, between
             iterations (the import primitive mutates device pool state
             and is only safe serialized with steps — same constraint
             the synchronous admission-time onboard lives under).

Promoted pages are registered into the PagePool and released into its
reusable-cache set *pinned*: eviction skips them, and the scheduler's
ordinary `match_prefix` claims them when the hinted request arrives —
no new scheduler path, the synchronous onboard candidates simply shrink
to zero. Everything is governed by:

    max_inflight     cap on concurrent G3→G2 reads in flight
    bandwidth_mbps   token-bucket budget on promoted bytes/s (0 = off)
    hint_ttl_s       a hinted block not yet promoted when the TTL fires
                     is cancelled (the request never arrived)
    pin_ttl_s        a promoted-but-unclaimed block is unpinned after
                     this long (back to plain LRU-evictable cache)

Late arrivals (request lands mid-promote) fall back to the untouched
synchronous onboard path: promotion COPIES from G2 (the tier keeps its
block), and a duplicate device import resolves through the PagePool's
register() dedup, so the result is byte-identical either way.

Accounting is request-id free: hits fire from the PagePool's claim hook
(a pinned hash claimed by match_prefix), lates from the engine's
synchronous onboard overlapping an in-flight promotion, cancels from
TTL expiry. Counters surface through runtime/metrics.py.
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from dynamo_tpu.runtime import tracing

from .quant import pair_nbytes, quantized_ratio

log = logging.getLogger("dynamo_tpu.kvbm.prefetch")

# job states
QUEUED = "queued"        # accepted, waiting for budget / in-flight slot
READING = "reading"      # G3→G2 file read in flight on the disk thread
PROMOTED = "promoted"    # registered + pinned in the device pool


class _Job:
    __slots__ = ("h", "parent", "state", "t0", "deadline", "pin_deadline",
                 "tp")

    def __init__(self, h: int, parent: Optional[int], t0: float, deadline: float):
        self.h = h
        self.parent = parent
        self.state = QUEUED
        self.t0 = t0
        self.deadline = deadline
        self.pin_deadline = 0.0
        self.tp = None  # traceparent of the hinting route span, if any


class PrefetchManager:
    """Owned by the engine; every method runs on the engine step thread
    unless noted. The only cross-thread entry is the disk-read callback,
    which posts back through the engine inbox."""

    def __init__(
        self,
        engine,
        *,
        max_inflight: int = 4,
        bandwidth_mbps: float = 0.0,  # 0 = unlimited
        hint_ttl_s: float = 10.0,
        pin_ttl_s: float = 5.0,
        metrics=None,
        clock=time.monotonic,  # injectable for deterministic TTL tests
        sim_block_bytes: int = 1 << 18,  # budget charge for hash-only blocks
    ):
        self.engine = engine
        self.pool = engine.pool
        self.tiered = engine.host_pool  # TieredKv (G2 [+G3 +G4])
        self.max_inflight = max(1, int(max_inflight))
        self.hint_ttl_s = float(hint_ttl_s)
        self.pin_ttl_s = float(pin_ttl_s)
        self.sim_block_bytes = int(sim_block_bytes)
        self._clock = clock
        self._bps = float(bandwidth_mbps) * 1e6
        self._limited = self._bps > 0
        # token bucket with one-block overdraft: dispatch is gated on a
        # non-negative balance, charges land at completion, refill in tick()
        self._budget_bytes = self._bps * 0.1 if self._limited else 0.0
        self._budget_burst = max(self._bps * 0.5, float(self.sim_block_bytes))
        self._last_refill = clock()

        self._jobs: "OrderedDict[int, _Job]" = OrderedDict()  # hash -> job
        self._queue: deque = deque()  # hashes awaiting dispatch (FIFO)
        self._reading: set = set()  # hashes with a disk read in flight

        self.stats: Dict[str, Any] = {
            "hints": 0,            # hint messages accepted
            "hinted_blocks": 0,    # blocks enqueued for promotion
            "promoted": 0,         # blocks registered + pinned in G1
            "hits": 0,             # pinned blocks claimed by a request
            "late": 0,             # sync onboard won the race mid-promote
            "cancelled": 0,        # hint/pin TTL expiries
            "dup": 0,              # import lost the register() dedup race
            "no_space": 0,         # device pool full, left to sync path
            "lost": 0,             # block evicted out from under the job
            "bytes_promoted": 0,
            # per-hop split at the ACTUAL stored width (int8+scales tiers
            # move ~0.52x the dense bytes): G3→G2 file-read bytes vs
            # G2→G1 device-import bytes (always dense — the import
            # boundary dequantizes)
            "bytes_promoted_g3": 0,
            "bytes_promoted_g2": 0,
            "bytes_promoted_g4": 0,  # G4→G2 object-store fetch bytes
            "reading_peak": 0,
            "promote_latency_sum_s": 0.0,
        }
        if metrics is None:
            from dynamo_tpu.runtime.metrics import make_metrics

            metrics = make_metrics("worker")
        self.bind_metrics(metrics)
        self.pool.claim_hook = self._on_claim

    def bind_metrics(self, metrics) -> None:
        """Re-home the counters onto a shared hierarchy. The worker calls
        this with runtime.metrics at serve time so the status-port
        /metrics renders them — the engine-built default lives in its own
        registry that no HTTP surface exports."""
        node = metrics.child(dynamo_component="kv_prefetch")
        self._m_hits = node.counter(
            "kv_prefetch_hits_total", "prefetched blocks claimed by a request")
        self._m_late = node.counter(
            "kv_prefetch_late_total",
            "blocks onboarded synchronously while their promotion was in flight")
        self._m_cancelled = node.counter(
            "kv_prefetch_cancelled_total", "hinted blocks expired by TTL unclaimed")
        self._m_bytes = node.counter(
            "kv_prefetch_bytes_total", "bytes promoted up the KV ladder")

    # -- hint ingress (engine inbox op "prefetch") ---------------------------
    def on_hint(self, hint: Dict[str, Any]) -> None:
        hashes = [int(h) for h in (hint.get("hashes") or [])]
        parents = list(hint.get("parents") or [])
        if not hashes:
            return
        self.stats["hints"] += 1
        now = self._clock()
        hint_tp = hint.get("traceparent")
        for i, h in enumerate(hashes):
            if h in self._jobs or h in self.pool.by_hash:
                continue  # already warm or already being promoted
            parent = parents[i] if i < len(parents) else None
            parent = int(parent) if parent is not None else None
            job = _Job(h, parent, now, now + self.hint_ttl_s)
            job.tp = hint_tp
            if h in self._reading:
                # a TTL-expired job's disk read is still in flight: adopt
                # it instead of queueing a second read. Double-dispatch is
                # worse than wasteful — DiskKvPool pins are a set, so the
                # first completion's unpin strips eviction protection from
                # the second read mid-flight, and the collapsed _reading
                # entry breaks the max_inflight gate (found by dynmc, spec
                # prefetch_ttl; regression schedule committed)
                job.state = READING
            else:
                self._queue.append(h)
            self._jobs[h] = job
            self.stats["hinted_blocks"] += 1
        self._pump()

    # -- periodic (every engine inbox drain) ---------------------------------
    def tick(self) -> None:
        now = self._clock()
        if self._limited:
            self._budget_bytes = min(
                self._budget_burst,
                self._budget_bytes + (now - self._last_refill) * self._bps,
            )
        self._last_refill = now
        for h, job in list(self._jobs.items()):
            if job.state == PROMOTED:
                if now >= job.pin_deadline:
                    self.pool.unpin(h)
                    del self._jobs[h]
                    self._cancelled(1)
            elif now >= job.deadline:
                # QUEUED: drop (lazy queue removal). READING: drop the job;
                # the read result finds no job and is discarded.
                del self._jobs[h]
                self._cancelled(1)
        self._pump()

    def _cancelled(self, n: int) -> None:
        self.stats["cancelled"] += n
        self._m_cancelled.inc(n)

    # -- dispatch ------------------------------------------------------------
    def _pump(self) -> None:
        disk = self.tiered.disk
        obj = getattr(self.tiered, "obj", None)
        while self._queue:
            if self._limited and self._budget_bytes <= 0:
                break
            h = self._queue[0]
            job = self._jobs.get(h)
            if job is None or job.state != QUEUED:
                self._queue.popleft()  # cancelled / already moved on
                continue
            if h in self.tiered.host:
                self._queue.popleft()
                self._promote_from_host(job)
            elif disk is not None and h in disk:
                if len(self._reading) >= self.max_inflight:
                    break  # FIFO: wait for a slot rather than skip ahead
                self._queue.popleft()
                job.state = READING
                self._reading.add(h)
                self.stats["reading_peak"] = max(
                    self.stats["reading_peak"], len(self._reading))
                disk.pin(h)
                if not disk.read_block_async(h, self._on_disk_read):
                    self._reading.discard(h)
                    disk.unpin(h)
                    self._drop(job, "lost")
            elif obj is not None and h in obj:
                # G4-only: the shared object store serves promotions too
                # (a peer's demoted block, or our own after G3 churn) —
                # the fetch rides G4's writer thread like G3's file reads
                if len(self._reading) >= self.max_inflight:
                    break
                self._queue.popleft()
                job.state = READING
                self._reading.add(h)
                self.stats["reading_peak"] = max(
                    self.stats["reading_peak"], len(self._reading))
                obj.pin(h)
                if not obj.read_block_async(h, self._on_obj_read):
                    self._reading.discard(h)
                    obj.unpin(h)
                    self._drop(job, "lost")
            else:
                # not in any tier we promote from (evicted underneath us)
                self._queue.popleft()
                self._drop(job, "lost")

    def _drop(self, job: _Job, reason: str) -> None:
        self.stats[reason] += 1
        self._jobs.pop(job.h, None)

    # -- G3/G4 → G2 ----------------------------------------------------------
    def _on_disk_read(self, h: int, parent: Optional[int], k, v,
                      found: bool) -> None:
        """Disk writer thread: hand the bytes back to the step thread."""
        self.engine._inbox.put(("prefetch_disk", (h, parent, k, v, found)))

    def _on_obj_read(self, h: int, parent: Optional[int], k, v,
                     found: bool) -> None:
        """G4 writer thread: hand the bytes back to the step thread."""
        self.engine._inbox.put(("prefetch_obj", (h, parent, k, v, found)))

    def on_disk_read(self, h: int, parent: Optional[int], k, v,
                     found: bool) -> None:
        """Step thread (inbox op "prefetch_disk")."""
        self._on_lower_read(h, k, v, found, self.tiered.disk,
                            "bytes_promoted_g3")

    def on_obj_read(self, h: int, parent: Optional[int], k, v,
                    found: bool) -> None:
        """Step thread (inbox op "prefetch_obj")."""
        self._on_lower_read(h, k, v, found,
                            getattr(self.tiered, "obj", None),
                            "bytes_promoted_g4")

    def _on_lower_read(self, h: int, k, v, found: bool, pool,
                       hop_stat: str) -> None:
        self._reading.discard(h)
        if pool is not None:
            pool.unpin(h)
        job = self._jobs.get(h)
        if job is None or job.state != READING:
            self._pump()  # job cancelled/superseded while the read ran
            return
        if not found:
            self._drop(job, "lost")
            self._pump()
            return
        if k is not None:
            # one [L, PS, Hk, D] block — dense or quantized dict, exactly
            # as the lower tier stored it; the host tier absorbs either
            self.tiered.host.put_block(h, job.parent, k, v)
            nbytes = pair_nbytes(k, v)
        elif not self._sim_runner():
            # real engine, data-less read (corrupt/truncated block was
            # quarantined underneath us): nothing to promote
            self._drop(job, "lost")
            self._pump()
            return
        else:
            self.tiered.host.put([h], [job.parent], None, None)
            nbytes = int(self.sim_block_bytes * self._tier_byte_ratio())
        if self._limited:
            self._budget_bytes -= nbytes
        self.stats["bytes_promoted"] += nbytes
        self.stats[hop_stat] += nbytes
        job.state = QUEUED  # now host-resident: next stage
        self._promote_from_host(job)
        self._pump()

    def _sim_runner(self) -> bool:
        return not hasattr(self.engine.runner, "export_pages_device")

    def _tier_byte_ratio(self) -> float:
        """Stored-bytes scale for hash-only (sim) budget charges: 1.0 for
        dense tiers, the int8+scales ratio when the tier quantizes."""
        if not getattr(self.tiered.host, "quantize", False):
            return 1.0
        shape = getattr(self.engine.runner, "kv_page_shape", None)
        if shape:
            return quantized_ratio(int(shape[-1]))
        return quantized_ratio(128)

    # -- G2 → G1 -------------------------------------------------------------
    def _promote_from_host(self, job: _Job) -> None:
        from dynamo_tpu.engine.kv_pool import NoSpace
        from dynamo_tpu.engine.model_runner import kv_arrays_to_payload

        h = job.h
        try:
            k, v = self.tiered.host.get([h])
        except KeyError:
            return self._drop(job, "lost")
        if k is None and not self._sim_runner():
            return self._drop(job, "lost")
        try:
            page = self.pool.alloc(1)[0]
        except NoSpace:
            # device pool exhausted by live sequences: the synchronous
            # onboard handles this block at admission, when pages free up
            return self._drop(job, "no_space")
        if k is not None:
            payload = kv_arrays_to_payload(k, v)
            nbytes = k.nbytes + v.nbytes
        else:
            payload = {"sim": True, "data": True, "n_pages": 1}
            nbytes = self.sim_block_bytes
        self.engine.runner.import_pages([page], 0, payload)
        canonical = self.pool.register(page, h, job.parent)
        if canonical != page:
            # the synchronous path imported this block while we worked:
            # ours is a duplicate — return the page, keep theirs
            self.pool.release([page])
            return self._drop(job, "dup")
        self.pool.release([page])  # registered, ref 0 -> reusable cache
        self.pool.pin(h)
        now = self._clock()
        job.state = PROMOTED
        job.pin_deadline = now + self.pin_ttl_s
        if self._limited:
            self._budget_bytes -= nbytes
        self.stats["promoted"] += 1
        self.stats["bytes_promoted"] += nbytes
        self.stats["bytes_promoted_g2"] += nbytes
        self.stats["promote_latency_sum_s"] += now - job.t0
        self._m_bytes.inc(nbytes)
        if job.tp is not None:
            # promotions span several engine ticks; reconstruct the
            # interval retroactively under the route span that hinted it
            end_ns = time.time_ns()
            start_ns = end_ns - max(0, int((now - job.t0) * 1e9))
            tracing.record_span(
                "kv.prefetch.promote", start_ns, end_ns, parent=job.tp,
                attributes={"kv.block_hash": h, "kv.tier": "G2->G1",
                            "kv.bytes": nbytes})

    # -- accounting hooks ----------------------------------------------------
    def _on_claim(self, h: int) -> None:
        """PagePool claim hook: a pinned hash was claimed by match_prefix
        (the pool already dropped the pin)."""
        if self._jobs.pop(h, None) is not None:
            self.stats["hits"] += 1
            self._m_hits.inc()

    def note_sync_onboard(self, hashes: List[int]) -> None:
        """Engine's synchronous onboard path: any of these blocks still
        mid-promotion arrived LATE — cancel the job (the sync import wins;
        an in-flight duplicate resolves via register() dedup)."""
        for h in hashes:
            job = self._jobs.get(h)
            if job is None:
                continue
            if job.state == PROMOTED:
                # shouldn't happen (promoted blocks are device-resident and
                # excluded from sync-onboard candidates) — just unpin
                self.pool.unpin(h)
                del self._jobs[h]
            else:
                del self._jobs[h]
                self.stats["late"] += 1
                self._m_late.inc()

    # -- shutdown ------------------------------------------------------------
    def stop(self) -> None:
        """After the step thread has joined: release every pin."""
        for h, job in list(self._jobs.items()):
            if job.state == PROMOTED:
                self.pool.unpin(h)
        self._jobs.clear()
        self._queue.clear()
        self._reading.clear()

    @property
    def mean_promote_latency_s(self) -> float:
        n = self.stats["promoted"]
        return self.stats["promote_latency_sum_s"] / n if n else 0.0
