"""G4 object-store KV tier.

Bottom rung of the KVBM ladder (reference tier model
lib/kvbm-engine/src/lib.rs:9-24: G1 device / G2 host / G3 disk / G4 object
store): blocks evicted from local disk demote into a durable,
cluster-shared object store keyed by content hash, so any worker can
onboard a prefix another worker computed — cross-node KV reuse without a
transfer plane.

Backends are pluggable: `FsBackend` (a shared/mounted directory — also the
test double) and `S3Backend` (boto3, gated on availability; zero-egress
environments use Fs). Blocks are serialized with the same header+raw
format as the G3 tier (kvbm/disk_pool.py).
"""

from __future__ import annotations

import json
import logging
import os
import struct
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dynamo_tpu.kvbm.disk_pool import decode_block, encode_block
from dynamo_tpu.kvbm.quant import is_quantized_block, maybe_quantize, pair_nbytes

log = logging.getLogger("dynamo_tpu.kvbm.object")


class FsBackend:
    """Object store over a (shared) filesystem directory."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key)

    def put(self, key: str, data: bytes) -> None:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self._path(key))

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def exists(self, key: str) -> bool:
        return os.path.exists(self._path(key))

    def list_keys(self) -> List[str]:
        return [n for n in os.listdir(self.root) if n.endswith(".kvb")]


class S3Backend:  # pragma: no cover - requires boto3 + network
    """Object store over S3-compatible storage (reference G4 via NIXL
    object plugins). Gated: raises if boto3 is unavailable."""

    def __init__(self, bucket: str, prefix: str = "kv/", **client_kw):
        try:
            import boto3
        except ImportError as e:
            raise RuntimeError(
                "S3 G4 backend requires boto3 (not present in this "
                "environment); use FsBackend over a shared mount"
            ) from e
        self._s3 = boto3.client("s3", **client_kw)
        self.bucket = bucket
        self.prefix = prefix

    def put(self, key: str, data: bytes) -> None:
        self._s3.put_object(Bucket=self.bucket, Key=self.prefix + key, Body=data)

    def get(self, key: str) -> Optional[bytes]:
        try:
            return self._s3.get_object(Bucket=self.bucket, Key=self.prefix + key)[
                "Body"
            ].read()
        except self._s3.exceptions.NoSuchKey:
            return None

    def delete(self, key: str) -> None:
        self._s3.delete_object(Bucket=self.bucket, Key=self.prefix + key)

    def exists(self, key: str) -> bool:
        try:
            self._s3.head_object(Bucket=self.bucket, Key=self.prefix + key)
            return True
        except Exception:
            return False

    def list_keys(self) -> List[str]:
        out, token = [], None
        while True:
            kw = {"Bucket": self.bucket, "Prefix": self.prefix}
            if token:
                kw["ContinuationToken"] = token
            resp = self._s3.list_objects_v2(**kw)
            out.extend(o["Key"][len(self.prefix):] for o in resp.get("Contents", []))
            if not resp.get("IsTruncated"):
                return out
            token = resp.get("NextContinuationToken")


class ObjectKvPool:
    """Content-addressed KV blocks in an object store; same pool surface
    as DiskKvPool so TieredKv chains it as the terminal tier. Writes run on
    a background thread; capacity is TTL-free LRU in block count (object
    stores are effectively unbounded — the cap only bounds the local
    index)."""

    def __init__(self, backend, capacity_blocks: int = 1 << 20,
                 quantize: bool = False, dedup: bool = True):
        self.backend = backend
        self.capacity = capacity_blocks
        # quantize dense blocks on entry (blocks demoted from quantized
        # upper tiers arrive as dicts already and pass through untouched)
        self.quantize = quantize
        # fleet-wide content-hash dedup: before writing a demoted block,
        # probe the (shared) backend — a peer already stored this content,
        # so adopt its object instead of re-uploading identical bytes
        self.dedup = dedup
        self._blocks: "OrderedDict[int, Optional[int]]" = OrderedDict()
        self.stats = {"offloaded": 0, "onboarded": 0, "evicted": 0,
                      "stored_bytes": 0, "quant_blocks": 0,
                      "dedup_hits": 0, "dedup_bytes_saved": 0}
        self._evict_listeners: List[Any] = []
        # fleet placement: called with (hash, parent) when a block becomes
        # locally indexed (write queued OR dedup-adopted) — the engine
        # forwards these as tier="obj" KV events so the router's G4 index
        # credits the shared tier. May fire from the spill/writer thread;
        # the listener must be thread-safe (the engine posts to its inbox).
        self.store_listener = None
        self._lock = threading.Lock()
        self._hash_only: set = set()  # entries with no data behind them
        self._pending: Dict[int, Tuple[np.ndarray, np.ndarray, Optional[int]]] = {}
        # prefetch pins: hashes capacity enforcement must not drop while a
        # promotion read is queued/in flight (brief, TTL-bounded)
        self._pinned: set = set()
        import queue

        self._write_q: "queue.Queue" = queue.Queue()
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._writer.start()
        # adopt existing objects (shared store: another worker's blocks)
        for key in backend.list_keys():
            try:
                self._blocks[int(key[:-4], 16)] = None
            except ValueError:
                continue
        if self._blocks:
            log.info("G4 adopted %d existing objects", len(self._blocks))

    def pin(self, block_hash: int) -> None:
        with self._lock:
            self._pinned.add(block_hash)

    def unpin(self, block_hash: int) -> None:
        with self._lock:
            self._pinned.discard(block_hash)

    def _key(self, block_hash: int) -> str:
        return f"{block_hash & 0xFFFFFFFFFFFFFFFF:016x}.kvb"

    def clear(self) -> List[int]:
        """Policy flush: drop the local index and pending writes. Stored
        objects become unreachable (content-addressed; the backend may
        garbage-collect them out of band)."""
        with self._lock:
            dropped = list(self._blocks)
            self._blocks.clear()
            self._hash_only.clear()
            self._pending.clear()
            self._pinned.clear()
        if dropped:
            for cb in self._evict_listeners:
                cb(dropped)
        return dropped

    def on_evict(self, cb) -> None:
        self._evict_listeners.append(cb)

    def __contains__(self, h: int) -> bool:
        with self._lock:
            return h in self._blocks

    def __len__(self) -> int:
        with self._lock:
            return len(self._blocks)

    def put_block(self, block_hash, parent_hash, k, v) -> None:
        if self.quantize:
            k, v = maybe_quantize(k), maybe_quantize(v)
        deduped = False
        with self._lock:
            if block_hash in self._blocks:
                self._blocks.move_to_end(block_hash)
                # upgrade a hash-only entry (sim / failed earlier spill)
                # when real data arrives; data-bearing entries are final
                if k is None or block_hash not in self._hash_only:
                    return
                self._hash_only.discard(block_hash)
            else:
                self._blocks[block_hash] = parent_hash
                self.stats["offloaded"] += 1
        # shared-store dedup probe OUTSIDE the lock (backend IO): the
        # block is content-addressed, so an existing object with this key
        # IS this block — adopt it and skip the duplicate upload
        if (k is not None and self.dedup
                and self.backend.exists(self._key(block_hash))):
            deduped = True
        with self._lock:
            if block_hash not in self._blocks:
                return  # evicted during the probe
            if deduped:
                self._hash_only.discard(block_hash)
                self.stats["dedup_hits"] += 1
                self.stats["dedup_bytes_saved"] += pair_nbytes(k, v)
            elif k is not None:
                self._pending[block_hash] = (k, v, parent_hash)
                self.stats["stored_bytes"] += pair_nbytes(k, v)
                if is_quantized_block(k):
                    self.stats["quant_blocks"] += 1
            else:
                self._hash_only.add(block_hash)
        if k is not None and not deduped:
            self._write_q.put(block_hash)
        if self.store_listener is not None:
            try:
                self.store_listener(block_hash, parent_hash)
            except Exception:
                log.exception("G4 store listener failed for %x", block_hash)
        self._enforce_capacity()

    def _write_loop(self) -> None:
        while True:
            item = self._write_q.get()
            if item is None:
                return
            if isinstance(item, tuple) and item[0] == "read":
                # async promotion read (G4→G2 prefetch): backend IO rides
                # this thread like the writes so the step thread never
                # blocks on an object fetch
                _, h, parent, cb = item
                with self._lock:
                    present = h in self._blocks
                    pending = self._pending.get(h)
                    hash_only = h in self._hash_only
                k = v = None
                if present and pending is not None:
                    k, v = pending[0], pending[1]
                elif present and not hash_only:
                    try:
                        k, v = self.get_block(h)
                    except KeyError:
                        present = False
                    except Exception:
                        log.exception("G4 async read failed for %x", h)
                        k = v = None
                try:
                    cb(h, parent, k, v, present)
                except Exception:
                    log.exception("G4 read callback failed for %x", h)
                continue
            h = item
            with self._lock:
                entry = self._pending.get(h)
            if entry is None:
                continue
            k, v, parent = entry
            try:
                self.backend.put(self._key(h), encode_block(parent, k, v))
            except Exception:
                log.exception("G4 write failed for %x", h)
                with self._lock:
                    self._blocks.pop(h, None)
            finally:
                with self._lock:
                    self._pending.pop(h, None)

    def flush(self) -> None:
        import time

        while True:
            with self._lock:
                if not self._pending:
                    return
            time.sleep(0.005)

    def _enforce_capacity(self) -> None:
        # capacity bounds the LOCAL index only: the store is shared, other
        # workers may still index these objects, so nothing is deleted from
        # the backend (lifecycle/GC is the store operator's policy)
        dropped: List[int] = []
        with self._lock:
            while len(self._blocks) > self.capacity:
                # LRU order, skipping prefetch-pinned blocks; all pinned →
                # overshoot until the pins release (pins are TTL-bounded)
                h = next(
                    (b for b in self._blocks if b not in self._pinned), None)
                if h is None:
                    break
                self._blocks.pop(h)
                self._pending.pop(h, None)
                self._hash_only.discard(h)
                dropped.append(h)
                self.stats["evicted"] += 1
        if dropped:
            for cb in self._evict_listeners:
                cb(dropped)

    def match(self, hashes: List[int]) -> int:
        n = 0
        with self._lock:
            for h in hashes:
                if h not in self._blocks:
                    break
                n += 1
        return n

    def get_block(self, block_hash: int):
        with self._lock:
            self._blocks.move_to_end(block_hash)  # KeyError if gone
            pending = self._pending.get(block_hash)
        self.stats["onboarded"] += 1
        if pending is not None:
            return pending[0], pending[1]
        data = self.backend.get(self._key(block_hash))
        if data is None:
            return None, None
        from dynamo_tpu.kvbm.disk_pool import BlockLayoutMismatch

        try:
            _, k, v = decode_block(data)
        except BlockLayoutMismatch:
            # a shared store can hold objects written by workers running
            # another pool layout — treat as a data miss (recompute), the
            # same path as an externally-deleted object
            log.warning("G4 object %x has a stale block layout; ignoring",
                        block_hash)
            return None, None
        except (KeyError, ValueError, struct.error):
            # truncated/corrupt object (short payload, missing scale
            # segment on int8+scales blocks): data miss, drop the local
            # index entry so it stops matching. The object itself stays —
            # deletion from a shared store is the operator's GC policy.
            log.warning("G4 object %x truncated/corrupt; ignoring",
                        block_hash, exc_info=True)
            with self._lock:
                self._blocks.pop(block_hash, None)
                self._pinned.discard(block_hash)
            return None, None
        return k, v

    def read_block_async(self, block_hash: int, cb) -> bool:
        """Queue a block read on the writer thread (G4→G2 prefetch
        promotion: object-store IO off the step thread, behind any queued
        writes for the same block). `cb(block_hash, parent, k, v, found)`
        fires on the writer thread — k/v None for hash-only (sim) or
        quarantined (stale-layout/corrupt) objects, found=False if the
        block left the index before the read ran. Returns False (cb never
        fires) when the block is already absent."""
        with self._lock:
            if block_hash not in self._blocks:
                return False
            parent = self._blocks[block_hash]
            self._blocks.move_to_end(block_hash)
        self._write_q.put(("read", block_hash, parent, cb))
        return True
