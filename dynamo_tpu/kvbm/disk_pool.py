"""G3 disk (NVMe/SSD) KV block tier.

Third rung of the KVBM memory ladder (reference tier model
lib/kvbm-engine/src/lib.rs:9-24: G1 device / G2 host / G3 disk / G4 object
store): content-addressed KV blocks spilled from the host tier land in
files; prefix-cache misses in G1/G2 onboard from here instead of
recomputing. The reference moves G3 data with GDS/NIXL; on TPU the path is
plain file IO into host arrays followed by the runner's host→device import
(the same primitive the disagg transfer uses).

Layout: one file per block — an 8-byte little-endian JSON-header length,
the JSON header (shape/dtype/parent), then raw k bytes followed by raw v
bytes. Capacity is bounded in blocks with LRU eviction (files unlinked).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import struct
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .quant import (
    is_quantized_block,
    maybe_dequantize,
    maybe_quantize,
    pair_nbytes,
)

log = logging.getLogger("dynamo_tpu.kvbm.disk")


def _np_dtype(name: str):
    if "bfloat16" in name:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


# On-disk/object block layout version. v2 = token-major [L, PS, Hk, D]
# pages (models/llama.py make_kv_pool); v1 (implicit, no field) was
# head-major [L, Hk, PS, D]. Readers reject other versions — adopting an
# old-layout block would import transposed KV (silently wrong activations
# when PS == Hk, a shape crash otherwise).
BLOCK_LAYOUT_VERSION = 2


class BlockLayoutMismatch(ValueError):
    pass


def encode_block(parent_hash, k, v) -> bytes:
    """Shared tier codec: 8-byte LE header length, JSON header, then the
    payload segments. Both the G3 files and G4 objects use exactly this
    format so blocks demote across tiers byte-for-byte.

    Dense blocks carry two segments (raw k, raw v). Quantized blocks
    (kvbm/quant.py dicts) carry four — k.q, k.s, v.q, v.s — with the
    header recording quant="int8_ts", the scale shape, and the original
    dense dtype so decode restores the exact demotion-time dict."""
    if is_quantized_block(k):
        header = json.dumps(
            {
                "shape": list(k["q"].shape),
                "dtype": "int8",
                "parent": parent_hash,
                "layout": BLOCK_LAYOUT_VERSION,
                "quant": "int8_ts",
                "sshape": list(k["s"].shape),
                "dt": k.get("dt", "float32"),
            }
        ).encode()
        return (
            struct.pack("<Q", len(header)) + header
            + np.ascontiguousarray(k["q"]).tobytes()
            + np.ascontiguousarray(k["s"]).tobytes()
            + np.ascontiguousarray(v["q"]).tobytes()
            + np.ascontiguousarray(v["s"]).tobytes()
        )
    header = json.dumps(
        {
            "shape": list(k.shape),
            "dtype": str(k.dtype),
            "parent": parent_hash,
            "layout": BLOCK_LAYOUT_VERSION,
        }
    ).encode()
    return (
        struct.pack("<Q", len(header)) + header
        + np.ascontiguousarray(k).tobytes() + np.ascontiguousarray(v).tobytes()
    )


def decode_block(data: bytes):
    """Inverse of encode_block → (parent_hash, k, v) — k/v are quantized
    dicts when the block was stored quantized. Raises BlockLayoutMismatch
    for blocks written under another pool layout and ValueError for
    truncated payloads (including a missing/short SCALE segment on
    quantized blocks — the quarantine path treats both as corrupt)."""
    (hlen,) = struct.unpack("<Q", data[:8])
    header = json.loads(data[8 : 8 + hlen])
    if header.get("layout") != BLOCK_LAYOUT_VERSION:
        raise BlockLayoutMismatch(
            f"block layout {header.get('layout')} != {BLOCK_LAYOUT_VERSION}"
        )
    shape = tuple(header["shape"])
    off = 8 + hlen
    if header.get("quant") == "int8_ts":
        sshape = tuple(header["sshape"])
        nq = int(np.prod(shape))  # int8: 1 byte/elem
        ns = int(np.prod(sshape)) * 4  # f32 scales
        if len(data) - off != 2 * (nq + ns):
            raise ValueError(
                f"quantized block payload {len(data) - off}B != expected "
                f"{2 * (nq + ns)}B (scale segment missing or truncated)"
            )
        dt = header.get("dt", "float32")

        def seg(o, n, dtype, shp):
            return np.frombuffer(data[o : o + n], dtype=dtype).reshape(shp)

        k = {"q": seg(off, nq, np.int8, shape),
             "s": seg(off + nq, ns, np.float32, sshape), "dt": dt}
        v = {"q": seg(off + nq + ns, nq, np.int8, shape),
             "s": seg(off + 2 * nq + ns, ns, np.float32, sshape), "dt": dt}
        return header.get("parent"), k, v
    dtype = _np_dtype(header["dtype"])
    n = int(np.prod(shape)) * dtype.itemsize
    if len(data) - off < 2 * n:
        raise ValueError(
            f"block payload {len(data) - off}B < expected {2 * n}B"
        )
    k = np.frombuffer(data[off : off + n], dtype=dtype).reshape(shape)
    v = np.frombuffer(data[off + n : off + 2 * n], dtype=dtype).reshape(shape)
    return header.get("parent"), k, v


class DiskKvPool:
    """Content-addressed KV block store on disk. Same match/get/put surface
    as HostKvPool so the tier chain composes them uniformly."""

    def __init__(self, root: str, capacity_blocks: int = 1 << 16,
                 quantize: bool = False,
                 capacity_bytes: Optional[int] = None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.capacity = capacity_blocks
        # optional byte budget (how an operator actually provisions an
        # NVMe partition): eviction under byte pressure spills data-bearing
        # blocks down to the G4 object store via spill_hook, same as the
        # block-count LRU
        self.capacity_bytes = capacity_bytes
        # quantize dense blocks on entry (blocks demoted from a quantized
        # G2 arrive as dicts already and pass through untouched)
        self.quantize = quantize
        # LRU index: hash → parent (file presence is authoritative for data)
        self._blocks: "OrderedDict[int, Optional[int]]" = OrderedDict()
        self._hash_only: set = set()  # sim entries with no file behind them
        self._bytes: Dict[int, int] = {}  # hash → stored payload bytes
        self._quant: set = set()  # hashes stored int8+scales
        self.stats = {"offloaded": 0, "onboarded": 0, "evicted": 0,
                      "stored_bytes": 0, "quant_blocks": 0}
        self._evict_listeners: List[Any] = []
        self._lock = threading.Lock()
        # demotion: called with (hash, parent, k, v) before an LRU drop so
        # a lower tier (G4 object store) can absorb the block
        self.spill_hook = None
        # spill runs on the engine step thread; do the file write on a
        # background writer so a device-eviction burst doesn't add disk
        # latency to the decode hot path. _pending holds not-yet-written
        # blocks so get_block stays consistent.
        self._pending: Dict[int, Tuple[Any, Any]] = {}
        # prefetch pins: hashes capacity enforcement must not drop while a
        # promotion read is queued/in flight (brief, TTL-bounded)
        self._pinned: set = set()
        self._outstanding = 0  # queued-but-unprocessed writer items
        self._write_q: "queue.Queue" = queue.Queue()
        self._writer = threading.Thread(target=self._write_loop, daemon=True)
        self._writer.start()
        self._rescan()

    def _rescan(self) -> None:
        """Adopt .kvb files left by a previous process with the same root:
        rebuild the LRU index (mtime order) so they stay matchable and
        capacity-managed instead of leaking forever."""
        entries = []
        for name in os.listdir(self.root):
            if not name.endswith(".kvb"):
                continue
            path = os.path.join(self.root, name)
            try:
                with open(path, "rb") as f:
                    (hlen,) = struct.unpack("<Q", f.read(8))
                    header = json.loads(f.read(hlen))
                if header.get("layout") != BLOCK_LAYOUT_VERSION:
                    # a previous process wrote this under another pool
                    # layout — unusable; drop it rather than serving
                    # transposed KV later
                    log.warning("dropping %s: stale block layout %s",
                                name, header.get("layout"))
                    os.unlink(path)
                    continue
                payload = max(0, os.path.getsize(path) - 8 - hlen)
                entries.append(
                    (os.path.getmtime(path), int(name[:-4], 16),
                     header.get("parent"), payload,
                     header.get("quant") == "int8_ts")
                )
            except (OSError, ValueError, struct.error):
                try:
                    os.unlink(path)
                except OSError:
                    pass
        for _, h, parent, payload, quant in sorted(entries):
            self._blocks[h] = parent
            self._bytes[h] = payload
            self.stats["stored_bytes"] += payload
            if quant:
                self._quant.add(h)
                self.stats["quant_blocks"] += 1
        if entries:
            log.info("G3 rescan adopted %d blocks from %s", len(entries), self.root)
        self._enforce_capacity()

    def _put_q(self, item) -> None:
        with self._lock:
            self._outstanding += 1
        self._write_q.put(item)

    def _write_loop(self) -> None:
        while True:
            item = self._write_q.get()
            if item is None:
                return
            try:
                self._process(item)
            finally:
                with self._lock:
                    self._outstanding -= 1

    def _drop_accounting(self, block_hash: int) -> None:
        """Caller holds self._lock. Byte/quant bookkeeping for a block
        leaving the index (evict, clear, quarantine)."""
        self.stats["stored_bytes"] -= self._bytes.pop(block_hash, 0)
        if block_hash in self._quant:
            self._quant.discard(block_hash)
            self.stats["quant_blocks"] -= 1

    def pin(self, block_hash: int) -> None:
        with self._lock:
            self._pinned.add(block_hash)

    def unpin(self, block_hash: int) -> None:
        with self._lock:
            self._pinned.discard(block_hash)

    def _process(self, item) -> None:
        if item[0] == "read":
            # async promotion read (G3→G2 prefetch): file IO rides this
            # thread like the spills so the step thread never blocks on it
            _, block_hash, parent, cb = item
            with self._lock:
                present = block_hash in self._blocks
                pending = self._pending.get(block_hash)
                hash_only = block_hash in self._hash_only
            k = v = None
            if present and pending is not None:
                k, v = pending
            elif present and not hash_only:
                try:
                    if os.path.exists(self._path(block_hash)):
                        k, v = self._read_file(block_hash)
                    else:
                        present = False
                except Exception:
                    log.exception("G3 async read failed for %x", block_hash)
                    k = v = None
            try:
                cb(block_hash, parent, k, v, present)
            except Exception:
                log.exception("G3 read callback failed for %x", block_hash)
            return
        if item[0] == "spill":
            # deferred demotion of an already-flushed block: read the
            # file off the hot path, hand it down, then unlink
            _, h, parent = item
            try:
                k, v = self._read_file(h)
                if self.spill_hook is not None:
                    self.spill_hook(h, parent, k, v)
            except (OSError, ValueError):
                log.warning("G3 spill read failed for %x; block lost", h)
            finally:
                try:
                    os.unlink(self._path(h))
                except FileNotFoundError:
                    pass
            return
        _, block_hash, parent_hash, k, v = item
        with self._lock:
            if block_hash not in self._pending:
                return  # evicted before the write happened
        try:
            self._write_file(block_hash, parent_hash, k, v)
        except OSError:
            log.exception("G3 write failed for %x", block_hash)
            with self._lock:
                self._blocks.pop(block_hash, None)
        finally:
            with self._lock:
                self._pending.pop(block_hash, None)

    def _write_file(self, block_hash, parent_hash, k, v) -> None:
        tmp = self._path(block_hash) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(encode_block(parent_hash, k, v))
        os.replace(tmp, self._path(block_hash))

    def clear(self) -> List[int]:
        """Policy flush: drop the index AND the backing files (a restart
        rescan must not resurrect stale-policy blocks). No spilling."""
        import os as _os

        with self._lock:
            dropped = list(self._blocks)
            self._blocks.clear()
            self._hash_only.clear()
            self._pending.clear()
            self._pinned.clear()
            self._bytes.clear()
            self._quant.clear()
            self.stats["stored_bytes"] = 0
            self.stats["quant_blocks"] = 0
        for h in dropped:
            try:
                _os.unlink(self._path(h))
            except OSError:
                pass
        if dropped:
            for cb in self._evict_listeners:
                cb(dropped)
        return dropped

    def on_evict(self, cb) -> None:
        self._evict_listeners.append(cb)

    def __contains__(self, block_hash: int) -> bool:
        with self._lock:
            return block_hash in self._blocks

    def __len__(self) -> int:
        with self._lock:
            return len(self._blocks)

    def _path(self, block_hash: int) -> str:
        return os.path.join(self.root, f"{block_hash & 0xFFFFFFFFFFFFFFFF:016x}.kvb")

    # -- offload (G2 → G3) --------------------------------------------------
    def put_block(
        self,
        block_hash: int,
        parent_hash: Optional[int],
        k: Any,  # [L, PS, Hk, D] one token-major block, a quantized
        v: Any,  # dict (kvbm/quant.py), or None (sim)
    ) -> None:
        if self.quantize:
            k, v = maybe_quantize(k), maybe_quantize(v)
        with self._lock:
            if block_hash in self._blocks:
                self._blocks.move_to_end(block_hash)
                return
            self._blocks[block_hash] = parent_hash
            if k is not None:
                self._pending[block_hash] = (k, v)
                self._bytes[block_hash] = pair_nbytes(k, v)
                self.stats["stored_bytes"] += self._bytes[block_hash]
                if is_quantized_block(k):
                    self._quant.add(block_hash)
                    self.stats["quant_blocks"] += 1
            else:
                self._hash_only.add(block_hash)
            self.stats["offloaded"] += 1
        if k is not None:
            self._put_q(("write", block_hash, parent_hash, k, v))
        self._enforce_capacity()

    def flush(self) -> None:
        """Block until queued writes AND deferred spills are processed."""
        import time

        while True:
            with self._lock:
                if not self._pending and self._outstanding == 0:
                    return
            time.sleep(0.005)

    def _over_budget(self) -> bool:
        """Caller holds self._lock."""
        if len(self._blocks) > self.capacity:
            return True
        return (self.capacity_bytes is not None
                and self.stats["stored_bytes"] > self.capacity_bytes)

    def _enforce_capacity(self) -> None:
        dropped: List[int] = []
        unlink_now: List[int] = []
        spill_mem = []
        spill_deferred = []
        with self._lock:
            while self._over_budget():
                # LRU order, skipping prefetch-pinned blocks; all pinned →
                # overshoot until the pins release (pins are TTL-bounded)
                h = next(
                    (b for b in self._blocks if b not in self._pinned), None)
                if h is None:
                    break
                parent = self._blocks.pop(h)
                pend = self._pending.pop(h, None)
                self._drop_accounting(h)
                dropped.append(h)
                self.stats["evicted"] += 1
                if self.spill_hook is None:
                    self._hash_only.discard(h)
                    unlink_now.append(h)
                elif pend is not None:
                    spill_mem.append((h, parent, pend))
                    unlink_now.append(h)
                elif h in self._hash_only:
                    # data-free (sim) entry: demote the hash itself
                    self._hash_only.discard(h)
                    spill_mem.append((h, parent, None))
                else:
                    # already on disk: read + demote on the writer thread,
                    # never on the engine step thread (it unlinks after)
                    spill_deferred.append((h, parent))
        for h, parent, pend in spill_mem:
            try:
                if pend is None:
                    self.spill_hook(h, parent, None, None)
                else:
                    self.spill_hook(h, parent, pend[0], pend[1])
            except Exception:
                log.exception("G3 spill hook failed for %x", h)
        for h, parent in spill_deferred:
            self._put_q(("spill", h, parent))
        for h in unlink_now:
            try:
                os.unlink(self._path(h))
            except FileNotFoundError:
                pass
        if dropped:
            for cb in self._evict_listeners:
                cb(dropped)

    # -- onboard (G3 → up) --------------------------------------------------
    def match(self, hashes: List[int]) -> int:
        n = 0
        with self._lock:
            for h in hashes:
                if h not in self._blocks:
                    break
                n += 1
        return n

    def get_block(self, block_hash: int) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """One block's (k, v) [L, Hk, PS, D]; (None, None) for hash-only
        (sim) entries. Raises KeyError if the block was evicted since the
        caller's match() — onboard callers treat that as a failed onboard
        and fall back to recompute (never a silent partial import)."""
        with self._lock:
            self._blocks.move_to_end(block_hash)  # KeyError if evicted
            pending = self._pending.get(block_hash)
        self.stats["onboarded"] += 1
        if pending is not None:  # spilled but not yet on disk
            return pending
        path = self._path(block_hash)
        if not os.path.exists(path):
            return None, None
        return self._read_file(block_hash)

    def read_block_async(self, block_hash: int, cb) -> bool:
        """Queue a block read on the writer thread (G3→G2 prefetch
        promotion: file IO off the step thread, behind any queued writes
        for the same block). `cb(block_hash, parent, k, v, found)` fires
        on the writer thread — k/v None for hash-only (sim) or corrupt
        blocks, found=False if the block was evicted before the read ran.
        Returns False (cb never fires) when the block is already absent."""
        with self._lock:
            if block_hash not in self._blocks:
                return False
            parent = self._blocks[block_hash]
            self._blocks.move_to_end(block_hash)
        self.stats["onboarded"] += 1
        self._put_q(("read", block_hash, parent, cb))
        return True

    def _read_file(self, block_hash: int):
        try:
            with open(self._path(block_hash), "rb") as f:
                _, k, v = decode_block(f.read())
        except BlockLayoutMismatch:
            # rescan drops stale-layout files, but a shared root can
            # gain them underneath a live process — data miss
            log.warning("block %x has a stale layout on disk; ignoring",
                        block_hash)
            return None, None
        except (OSError, KeyError, ValueError, struct.error):
            # truncated or corrupt file (short header, bad JSON, short
            # payload — including a missing/size-mismatched SCALE segment
            # on int8+scales blocks, e.g. half-written by a crashed
            # process): a data miss the onboard path recomputes through,
            # NEVER an exception into it. Unlink + drop the index entry
            # so it stops matching.
            log.warning("block %x truncated/corrupt on disk; unlinking",
                        block_hash, exc_info=True)
            try:
                os.unlink(self._path(block_hash))
            except OSError:
                pass
            with self._lock:
                self._blocks.pop(block_hash, None)
                self._hash_only.discard(block_hash)
                self._pinned.discard(block_hash)
                self._drop_accounting(block_hash)
            return None, None
        return k, v

    def get(self, hashes: List[int]) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """Stacked dense [L, n, PS, Hk, D] arrays (HostKvPool-compatible;
        quantized blocks dequantize here)."""
        pairs = [self.get_block(h) for h in hashes]
        # ANY data-less block fails the whole read (stale-layout file can
        # appear mid-chain under a shared root) — np.stack over a None
        # would raise where callers expect a data-miss result
        if not pairs or any(p[0] is None for p in pairs):
            return None, None
        # token-major wire layout: page axis 1
        k = np.stack([maybe_dequantize(p[0]) for p in pairs], axis=1)
        v = np.stack([maybe_dequantize(p[1]) for p in pairs], axis=1)
        return k, v


class TieredKv:
    """G2 (host DRAM) + optional G3 (disk) presented as one lower-tier pool
    to the scheduler/engine: match() walks the leading run across both
    tiers, get() reads each block from whichever tier holds it, and
    host-tier evictions spill block data down to disk instead of dropping
    it (the KVBM ladder's demotion path). Lower-tier removal events fire
    only from the terminal tier, so router credits persist while data
    merely demotes."""

    def __init__(self, host, disk: Optional[DiskKvPool] = None, obj=None):
        self.host = host
        self.disk = disk
        self.obj = obj  # G4 ObjectKvPool (kvbm/object_store.py)
        if disk is not None:
            host.spill_hook = self._spill
            if obj is not None:
                disk.spill_hook = obj.put_block
        elif obj is not None:
            host.spill_hook = self._spill_to_obj

    def _spill(self, block) -> None:  # HostBlock
        self.disk.put_block(block.block_hash, block.parent_hash, block.k, block.v)

    def _spill_to_obj(self, block) -> None:  # HostBlock (no G3 tier)
        self.obj.put_block(block.block_hash, block.parent_hash, block.k, block.v)

    def on_evict(self, cb) -> None:
        # only terminal drops remove lower-tier residency. NB: pools define
        # __len__, so `a or b` would treat an EMPTY tier as absent
        terminal = self.host if self.disk is None else self.disk
        terminal = terminal if self.obj is None else self.obj
        terminal.on_evict(cb)

    def _tiers(self):
        return [t for t in (self.host, self.disk, self.obj) if t is not None]

    def clear(self) -> None:
        """Flush every tier (weight-update policy invalidation): blocks
        cached under the old weights must not be onboarded under the new
        ones. Tiers fire their removal events themselves."""
        for t in self._tiers():
            clear = getattr(t, "clear", None)
            if clear is not None:
                clear()

    def match(self, hashes: List[int]) -> int:
        n = 0
        tiers = self._tiers()
        for h in hashes:
            if any(h in t for t in tiers):
                n += 1
            else:
                break
        return n

    def get(self, hashes: List[int]) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """Raises KeyError if any block was evicted (from BOTH tiers) after
        the caller's match() — concurrent spills can churn the disk LRU.
        Quantized blocks dequantize here; the stacked result is dense."""
        ks, vs = [], []
        for h in hashes:
            if h in self.host:
                k, v = self.host.get([h])
                k = k[:, 0] if k is not None else None
                v = v[:, 0] if v is not None else None
            elif self.disk is not None and h in self.disk:
                k, v = self.disk.get_block(h)
            elif self.obj is not None:
                k, v = self.obj.get_block(h)
            else:
                raise KeyError(h)
            if k is None:
                return None, None
            ks.append(maybe_dequantize(k))
            vs.append(maybe_dequantize(v))
        # token-major wire layout: page axis 1
        return np.stack(ks, axis=1), np.stack(vs, axis=1)

    def residency(self, hashes: List[int]) -> List[str]:
        """Tier label per hash — "host" / "disk" / "obj" / "miss" — the
        attribution the per-tier kv_onboard_s EWMA (topology-aware
        placement) charges transfer time against."""
        out = []
        for h in hashes:
            if h in self.host:
                out.append("host")
            elif self.disk is not None and h in self.disk:
                out.append("disk")
            elif self.obj is not None and h in self.obj:
                out.append("obj")
            else:
                out.append("miss")
        return out

    def put(self, hashes, parents, k, v) -> None:
        self.host.put(hashes, parents, k, v)

    @property
    def stats(self):
        s = dict(self.host.stats)
        if self.disk is not None:
            s.update({f"disk_{k}": val for k, val in self.disk.stats.items()})
        if self.obj is not None:
            s.update({f"obj_{k}": val for k, val in self.obj.stats.items()})
        return s

    def __contains__(self, h: int) -> bool:
        return any(h in t for t in self._tiers())
