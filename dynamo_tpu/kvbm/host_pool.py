"""G2 host-DRAM KV block pool.

Analog of the reference's G2 tier (lib/kvbm-engine/src/lib.rs:9-24 tier
model; kvbm-logical block registry + dedup + LRU): content-addressed
storage of complete KV blocks evicted from device HBM, onboarded back on
prefix-cache hits. The TPU "transfer manager" here is a host array copy —
device↔host movement happens via the runner's export/import (the same
primitives the P→D disagg path uses; the reference uses NIXL/GDS).

Capacity is bounded in blocks; eviction is LRU. Data may be None (mocker
workers track hash-level residency without bytes).
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("dynamo_tpu.kvbm.host")


@dataclass
class HostBlock:
    block_hash: int
    parent_hash: Optional[int]
    k: Any  # np.ndarray [L, PS, Hk, D] (one token-major page) or None (sim)
    v: Any
    stored_at: float = field(default_factory=time.monotonic)


class HostKvPool:
    def __init__(self, capacity_blocks: int = 4096):
        self.capacity = capacity_blocks
        self._blocks: "OrderedDict[int, HostBlock]" = OrderedDict()  # LRU
        self.stats = {"offloaded": 0, "onboarded": 0, "evicted": 0}
        self._evict_listeners: List[Any] = []
        # demotion: called with the full HostBlock before an LRU drop so a
        # lower tier (G3 disk) can absorb the data
        self.spill_hook: Optional[Any] = None
        # prefetch pins: hashes capacity enforcement must not drop (a
        # promotion is reading them); capacity may transiently overshoot
        # while pins are held — pins are brief and TTL-bounded
        self._pinned: set = set()

    def pin(self, block_hash: int) -> None:
        self._pinned.add(block_hash)

    def unpin(self, block_hash: int) -> None:
        self._pinned.discard(block_hash)

    def on_evict(self, cb) -> None:
        """cb(list[int]) — hashes dropped from the host tier."""
        self._evict_listeners.append(cb)

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._blocks

    def __len__(self) -> int:
        return len(self._blocks)

    # -- offload (G1 → G2) --------------------------------------------------
    def put(
        self,
        hashes: List[int],
        parents: List[Optional[int]],
        k: Optional[np.ndarray],  # [L, n, PS, Hk, D] or None
        v: Optional[np.ndarray],
    ) -> None:
        for i, (h, p) in enumerate(zip(hashes, parents)):
            if h in self._blocks:
                self._blocks.move_to_end(h)
                continue
            # token-major wire layout [L, n, PS, Hk, D]: page axis 1
            kb = np.ascontiguousarray(k[:, i]) if k is not None else None
            vb = np.ascontiguousarray(v[:, i]) if v is not None else None
            self._blocks[h] = HostBlock(h, p, kb, vb)
            self.stats["offloaded"] += 1
        self._enforce_capacity()

    def _enforce_capacity(self) -> None:
        dropped: List[int] = []
        while len(self._blocks) > self.capacity:
            # LRU order, skipping pinned blocks; all-pinned → overshoot
            # until the pins release (prefetch pins are TTL-bounded)
            victim = next(
                (h for h in self._blocks if h not in self._pinned), None)
            if victim is None:
                break
            block = self._blocks.pop(victim)
            if self.spill_hook is not None:
                self.spill_hook(block)
            dropped.append(victim)
            self.stats["evicted"] += 1
        if dropped:
            for cb in self._evict_listeners:
                cb(dropped)

    def clear(self) -> List[int]:
        """Drop EVERY block without spilling (policy flush: the data is
        invalid, demotion would preserve it). Fires removal events so
        router lower-tier credits drop too; returns the cleared hashes."""
        dropped = list(self._blocks)
        self._blocks.clear()
        self._pinned.clear()
        if dropped:
            for cb in self._evict_listeners:
                cb(dropped)
        return dropped

    # -- onboard (G2 → G1) --------------------------------------------------
    def match(self, hashes: List[int]) -> int:
        """Leading blocks of `hashes` resident in this tier."""
        n = 0
        for h in hashes:
            if h not in self._blocks:
                break
            n += 1
        return n

    def get(
        self, hashes: List[int]
    ) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """Stacked [L, n, PS, Hk, D] arrays (None if sim/hash-only)."""
        blocks = [self._blocks[h] for h in hashes]
        for b in blocks:
            self._blocks.move_to_end(b.block_hash)
        self.stats["onboarded"] += len(blocks)
        if not blocks or blocks[0].k is None:
            return None, None
        k = np.stack([b.k for b in blocks], axis=1)
        v = np.stack([b.v for b in blocks], axis=1)
        return k, v

    def lookup_chain(self, hashes: List[int]) -> List[int]:
        return [h for h in hashes if h in self._blocks]
