"""G2 host-DRAM KV block pool.

Analog of the reference's G2 tier (lib/kvbm-engine/src/lib.rs:9-24 tier
model; kvbm-logical block registry + dedup + LRU): content-addressed
storage of complete KV blocks evicted from device HBM, onboarded back on
prefix-cache hits. The TPU "transfer manager" here is a host array copy —
device↔host movement happens via the runner's export/import (the same
primitives the P→D disagg path uses; the reference uses NIXL/GDS).

Capacity is bounded in blocks; eviction is LRU. With quantize=True the
pool stores int8+scales (kvbm/quant.py) instead of the export dtype —
~1.94x blocks per byte at D=128 — and dequantizes on get(); an optional
byte budget (capacity_bytes) then bounds the tier the way an operator
actually provisions it. Data may be None (mocker workers track
hash-level residency without bytes).
"""

from __future__ import annotations

import logging
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .quant import (
    block_nbytes,
    is_quantized_block,
    maybe_dequantize,
    maybe_quantize,
    stacked_to_blocks,
)

log = logging.getLogger("dynamo_tpu.kvbm.host")


@dataclass
class HostBlock:
    block_hash: int
    parent_hash: Optional[int]
    k: Any  # np.ndarray [L, PS, Hk, D] (one token-major page), a
    v: Any  # quantized dict {"q","s","dt"}, or None (sim)
    stored_at: float = field(default_factory=time.monotonic)

    @property
    def nbytes(self) -> int:
        return block_nbytes(self.k) + block_nbytes(self.v)


class HostKvPool:
    def __init__(
        self,
        capacity_blocks: int = 4096,
        quantize: bool = False,
        capacity_bytes: Optional[int] = None,
    ):
        self.capacity = capacity_blocks
        self.capacity_bytes = capacity_bytes
        self.quantize = quantize
        self._blocks: "OrderedDict[int, HostBlock]" = OrderedDict()  # LRU
        self.stats = {"offloaded": 0, "onboarded": 0, "evicted": 0,
                      "stored_bytes": 0, "quant_blocks": 0}
        self._evict_listeners: List[Any] = []
        # demotion: called with the full HostBlock before an LRU drop so a
        # lower tier (G3 disk) can absorb the data
        self.spill_hook: Optional[Any] = None
        # prefetch pins: hashes capacity enforcement must not drop (a
        # promotion is reading them); capacity may transiently overshoot
        # while pins are held — pins are brief and TTL-bounded
        self._pinned: set = set()

    def pin(self, block_hash: int) -> None:
        self._pinned.add(block_hash)

    def unpin(self, block_hash: int) -> None:
        self._pinned.discard(block_hash)

    def on_evict(self, cb) -> None:
        """cb(list[int]) — hashes dropped from the host tier."""
        self._evict_listeners.append(cb)

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._blocks

    def __len__(self) -> int:
        return len(self._blocks)

    # -- offload (G1 → G2) --------------------------------------------------
    def put(
        self,
        hashes: List[int],
        parents: List[Optional[int]],
        k: Optional[np.ndarray],  # [L, n, PS, Hk, D] or None
        v: Optional[np.ndarray],
    ) -> None:
        for i, (h, p) in enumerate(zip(hashes, parents)):
            kb, vb = stacked_to_blocks(k, v, i)
            self.put_block(h, p, kb, vb)
        self._enforce_capacity()

    def put_block(
        self, block_hash: int, parent_hash: Optional[int], k: Any, v: Any
    ) -> None:
        """Store one block. Accepts a dense [L, PS, Hk, D] page, an
        already-quantized dict (promotion from a quantized G3 must not
        requantize — the fold is idempotent only on exact rehydration),
        or None (sim). Caller batches _enforce_capacity via put(); direct
        callers (prefetch promotion) get it per block."""
        if block_hash in self._blocks:
            self._blocks.move_to_end(block_hash)
            return
        if self.quantize:
            k, v = maybe_quantize(k), maybe_quantize(v)
        block = HostBlock(block_hash, parent_hash, k, v)
        self._blocks[block_hash] = block
        self.stats["offloaded"] += 1
        self.stats["stored_bytes"] += block.nbytes
        if is_quantized_block(k):
            self.stats["quant_blocks"] += 1
        self._enforce_capacity()

    def _over_budget(self) -> bool:
        if len(self._blocks) > self.capacity:
            return True
        return (self.capacity_bytes is not None
                and self.stats["stored_bytes"] > self.capacity_bytes)

    def _enforce_capacity(self) -> None:
        dropped: List[int] = []
        while self._over_budget():
            # LRU order, skipping pinned blocks; all-pinned → overshoot
            # until the pins release (prefetch pins are TTL-bounded)
            victim = next(
                (h for h in self._blocks if h not in self._pinned), None)
            if victim is None:
                break
            block = self._blocks.pop(victim)
            self.stats["stored_bytes"] -= block.nbytes
            if is_quantized_block(block.k):
                self.stats["quant_blocks"] -= 1
            if self.spill_hook is not None:
                self.spill_hook(block)
            dropped.append(victim)
            self.stats["evicted"] += 1
        if dropped:
            for cb in self._evict_listeners:
                cb(dropped)

    def clear(self) -> List[int]:
        """Drop EVERY block without spilling (policy flush: the data is
        invalid, demotion would preserve it). Fires removal events so
        router lower-tier credits drop too; returns the cleared hashes."""
        dropped = list(self._blocks)
        self._blocks.clear()
        self._pinned.clear()
        self.stats["stored_bytes"] = 0
        self.stats["quant_blocks"] = 0
        if dropped:
            for cb in self._evict_listeners:
                cb(dropped)
        return dropped

    # -- onboard (G2 → G1) --------------------------------------------------
    def match(self, hashes: List[int]) -> int:
        """Leading blocks of `hashes` resident in this tier."""
        n = 0
        for h in hashes:
            if h not in self._blocks:
                break
            n += 1
        return n

    def get(
        self, hashes: List[int]
    ) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        """Stacked dense [L, n, PS, Hk, D] arrays (None if sim/hash-only).
        Quantized blocks dequantize here — the engine/wire boundary stays
        dense regardless of tier storage."""
        blocks = [self._blocks[h] for h in hashes]
        for b in blocks:
            self._blocks.move_to_end(b.block_hash)
        self.stats["onboarded"] += len(blocks)
        if not blocks or blocks[0].k is None:
            return None, None
        k = np.stack([maybe_dequantize(b.k) for b in blocks], axis=1)
        v = np.stack([maybe_dequantize(b.v) for b in blocks], axis=1)
        return k, v

    def get_block_raw(self, block_hash: int) -> Tuple[Any, Any]:
        """One block's (k, v) exactly as stored — quantized dict when the
        tier quantizes. The native-pass-through onboard path (int8 device
        pools) uses this to skip the dequantize/requantize round trip.
        Raises KeyError if evicted since the caller's match()."""
        b = self._blocks[block_hash]
        self._blocks.move_to_end(block_hash)
        self.stats["onboarded"] += 1
        return b.k, b.v

    def lookup_chain(self, hashes: List[int]) -> List[int]:
        return [h for h in hashes if h in self._blocks]
