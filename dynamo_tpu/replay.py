"""`python -m dynamo_tpu.replay` — offline simulation runs (DynoSim analog,
reference `python -m dynamo.replay`, docs/dynosim/README.md:17-26).

Builds an in-process serving stack — N mocker workers with the TPU
step-time model behind the real scheduler/page-pool/router — replays a
trace (generated or loaded), and reports SLO goodput. TPU-free router and
scheduler A/B evaluation:

  python -m dynamo_tpu.replay --workers 2 --router-mode kv \
      --requests 200 --rps 20 --prefix-groups 8
"""

from __future__ import annotations

import argparse
import asyncio
import json

from dynamo_tpu.bench.loadgen import (
    compute_goodput,
    generate_trace,
    load_trace,
    run_trace_against_engine,
)
from dynamo_tpu.frontend.service import ModelManager, ModelWatcher
from dynamo_tpu.mocker.__main__ import build_mock_engine
from dynamo_tpu.mocker.__main__ import parse_args as mocker_args
from dynamo_tpu.runtime.discovery import MemDiscovery
from dynamo_tpu.runtime.logging_util import configure_logging
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.worker_common import serve_worker


def parse_args(argv=None):
    p = argparse.ArgumentParser("dynamo_tpu.replay")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--router-mode", default="kv", choices=["round_robin", "random", "kv"])
    p.add_argument("--trace", default=None, help="trace JSONL (else generated)")
    p.add_argument("--requests", type=int, default=100)
    p.add_argument("--rps", type=float, default=20.0)
    p.add_argument("--isl", type=int, default=256)
    p.add_argument("--osl", type=int, default=64)
    p.add_argument("--prefix-groups", type=int, default=0)
    p.add_argument("--ttft-slo", type=float, default=2.0)
    p.add_argument("--itl-slo", type=float, default=0.05)
    p.add_argument("--speed", type=float, default=1.0, help="sim timing scale")
    p.add_argument("--decode-base-ms", type=float, default=4.0)
    p.add_argument("--page-size", type=int, default=16)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--calibrate-records", default=None, metavar="DUMP_JSON",
                   help="flight-recorder dump (engine black box / anomaly "
                        "dump): fit SimTiming from its IterationRecords, "
                        "run the replay with the fitted model, and report "
                        "the fit error bounds in the output")
    return p.parse_args(argv)


def load_calibration(path: str, speed: float = 1.0):
    """Fit SimTiming from a flight-recorder dump file and report the fit's
    error against the very records it was fitted on (an upper bound on
    twin fidelity: if the model cannot reproduce its own training data
    within tolerance, no downstream number can be trusted)."""
    from dynamo_tpu.mocker.sim import SimTiming

    with open(path) as f:
        dump = json.load(f)
    records = dump.get("records", dump) if isinstance(dump, dict) else dump
    timing = SimTiming.fit_records(records, speed=speed)
    return timing, timing.calibration_error(records)


async def run_replay(args) -> dict:
    realm = f"replay-{args.seed}"
    timing, calibration = None, None
    if args.calibrate_records:
        timing, calibration = load_calibration(
            args.calibrate_records, speed=args.speed)
    workers = []
    for _ in range(args.workers):
        rt = DistributedRuntime(discovery=MemDiscovery(realm=realm), event_transport="inproc")
        margs = mocker_args([
            "--speed", str(args.speed),
            "--decode-base-ms", str(args.decode_base_ms),
            "--page-size", str(args.page_size),
        ])
        engine, card = build_mock_engine(margs, timing=timing)
        w = await serve_worker(rt, engine, card)
        workers.append((rt, w))

    frt = DistributedRuntime(discovery=MemDiscovery(realm=realm), event_transport="inproc")
    manager = ModelManager()
    watcher = ModelWatcher(frt, manager, router_mode=args.router_mode)
    await watcher.start()
    await watcher.wait_for_model(timeout=10)
    entry = manager.get("mock-model")

    if args.trace:
        trace = load_trace(args.trace)
    else:
        trace = generate_trace(
            args.requests, args.rps, isl_mean=args.isl, osl_mean=args.osl,
            prefix_groups=args.prefix_groups, seed=args.seed,
        )

    try:
        results, duration = await run_trace_against_engine(
            trace, entry.chain.generate, seed=args.seed
        )
        report = compute_goodput(results, duration, args.ttft_slo, args.itl_slo)
        out = json.loads(report.to_json())
        if calibration is not None:
            out["calibration"] = calibration
        return out
    finally:
        await watcher.stop()
        await frt.shutdown()
        for rt, w in workers:
            await w.stop()
            await rt.shutdown(drain_timeout=1)


def main(argv=None) -> None:
    configure_logging()
    args = parse_args(argv)
    report = asyncio.run(run_replay(args))
    print(json.dumps(report))


if __name__ == "__main__":
    main()
