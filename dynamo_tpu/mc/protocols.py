"""dynmc specs over the REAL control-plane protocols.

Each spec instantiates production classes — AdmissionQueue, KvIndexer,
PrefetchManager, Migration, spawn_tracked — and fakes only their I/O
planes (disk thread, event subscriber, request plane, wall clock), so
the interleavings the explorer enumerates are interleavings of the
actual shipped code. The buggy twins (`_UnbufferedIndexer`,
`_NoAdoptPrefetch`, `_EpochlessIndexer`) reproduce the pre-fix behavior
of the two ordering bugs dynmc surfaced; regression tests replay the
committed shrunk schedules against BOTH: the twin must violate, the
production class must pass — proving the schedule still exercises the
race and the fix still closes it.

SPECS / FIXTURES at the bottom are the CLI registry
(`scripts/dynmc.py`).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from dynamo_tpu.mc.faults import Fault, cancel_task
from dynamo_tpu.mc.spec import (
    InvariantViolation,
    LostWakeupFixture,
    Spec,
    SpecEnv,
)

_silent = logging.getLogger("dynamo_tpu.mc.silent")
_silent.addHandler(logging.NullHandler())
_silent.propagate = False

W = (1, 0)  # the worker under test, everywhere


def _iv(cond: bool, msg: str) -> None:
    if not cond:
        raise InvariantViolation(msg)


# ---------------------------------------------------------------------------
# admission_queue — grant hand-off under cancel/timeout churn
# ---------------------------------------------------------------------------

class AdmissionQueueSpec(Spec):
    """Three requesters park against a saturated AdmissionQueue; capacity
    frees two slots over time; one requester may be cancelled mid-wait
    (client disconnect). Contract: nobody parks forever (every waiter
    resolves as granted / queue_timeout / cancelled), a grant landing on
    a cancelled waiter is passed on, and no more grants are delivered
    than slots were freed."""

    name = "admission_queue"

    def build(self, env: SpecEnv) -> None:
        from dynamo_tpu.router.queue import AdmissionConfig, AdmissionQueue
        from dynamo_tpu.runtime.request_plane import RequestPlaneError

        q = AdmissionQueue(
            AdmissionConfig(busy_blocks=10, max_depth=8, max_wait_s=5.0),
            load_fn=lambda w: 100.0,           # permanently saturated
            workers_fn=lambda: [W],
        )
        env.data["q"] = q
        env.data["outcomes"] = {}

        async def requester(rid: str, pri: int) -> None:
            try:
                await q.acquire(pri)
                env.data["outcomes"][rid] = "granted"
            except RequestPlaneError as e:
                env.data["outcomes"][rid] = e.code
            except asyncio.CancelledError:
                env.data["outcomes"][rid] = "cancelled"
                raise

        async def capacity() -> None:
            await asyncio.sleep(1.0)
            q.notify(1)
            await asyncio.sleep(1.0)
            q.notify(1)

        env.spawn("req_a", requester("a", 0))
        env.spawn("req_b", requester("b", 1))
        env.spawn("req_c", requester("c", 2))
        env.spawn("capacity", capacity())

    def faults(self, env: SpecEnv) -> list:
        return [cancel_task("cancel_req_b", lambda loop: env.task("req_b"))]

    def invariant(self, env: SpecEnv) -> None:
        q = env.data["q"]
        outcomes: Dict[str, str] = env.data["outcomes"]
        for rid in ("a", "b", "c"):
            t = env.task(f"req_{rid}")
            _iv(t is not None and t.done(),
                f"requester {rid} parked forever (lost wakeup)")
            _iv(rid in outcomes, f"requester {rid} finished with no outcome")
        granted = sum(1 for o in outcomes.values() if o == "granted")
        _iv(granted <= 2, f"{granted} grants delivered for 2 freed slots")
        _iv(q.depth == 0, f"queue depth {q.depth} at quiescence")


# ---------------------------------------------------------------------------
# prefetch_ttl — hint-TTL expiry racing an in-flight disk read
# ---------------------------------------------------------------------------

class _FakeHostTier:
    quantize = False

    def __init__(self) -> None:
        self.blocks: Dict[int, Optional[int]] = {}

    def __contains__(self, h: int) -> bool:
        return h in self.blocks

    def put(self, hashes, parents, k, v) -> None:
        for h, p in zip(hashes, parents):
            self.blocks[h] = p

    def put_block(self, h, parent, k, v) -> None:
        self.blocks[h] = parent

    def get(self, hashes):
        for h in hashes:
            if h not in self.blocks:
                raise KeyError(h)
        return (None, None)


class _FakeDisk:
    """Disk tier whose async read completes on a virtual timer, checking
    the two contracts the real writer thread depends on: at most one
    read in flight per hash, and the eviction pin held for the read's
    whole flight (DiskKvPool pins are a SET — a double pin/unpin pair
    silently drops protection early)."""

    def __init__(self, env: SpecEnv, blocks, latency: float) -> None:
        self.env = env
        self.blocks = set(blocks)
        self.latency = latency
        self.pinned: set = set()
        self.inflight: List[int] = []
        env.data.setdefault("disk_violations", [])

    def pin(self, h: int) -> None:
        self.pinned.add(h)

    def unpin(self, h: int) -> None:
        self.pinned.discard(h)

    def __contains__(self, h: int) -> bool:
        return h in self.blocks

    def read_block_async(self, h: int, cb) -> bool:
        if h in self.inflight:
            self.env.data["disk_violations"].append(
                f"duplicate concurrent read of block {h}")
        self.inflight.append(h)

        def _complete() -> None:
            self.inflight.remove(h)
            if h not in self.pinned:
                self.env.data["disk_violations"].append(
                    f"read of block {h} completed UNPINNED "
                    "(eviction window while file IO in flight)")
            cb(h, None, None, None, True)

        self.env.loop.call_later(self.latency, _complete)
        return True


class _FakeInbox:
    """Engine inbox: ops land back on the (virtual) step thread as
    schedulable callbacks."""

    def __init__(self, env: SpecEnv) -> None:
        self.env = env
        self.mgr = None  # wired after the manager exists

    def put(self, item) -> None:
        op, payload = item
        if op == "prefetch_disk":
            self.env.loop.call_soon(self.mgr.on_disk_read, *payload)


class _SimRunner:
    # no export_pages_device attr => PrefetchManager runs in sim mode
    def import_pages(self, pages, seq, payload) -> None:
        pass


class _FakeMetricsNode:
    def child(self, **kw):
        return self

    def counter(self, name, help=""):
        return self

    def inc(self, n: int = 1) -> None:
        pass


class _FakeEngine:
    def __init__(self, env: SpecEnv, pool, tiered) -> None:
        self.pool = pool
        self.host_pool = tiered
        self.runner = _SimRunner()
        self._inbox = _FakeInbox(env)


class _Tiered:
    def __init__(self, host, disk) -> None:
        self.host = host
        self.disk = disk


class PrefetchTtlSpec(Spec):
    """A disk-resident block is hinted; the read's latency exceeds the
    hint TTL, so tick() expires the job mid-read; a re-hint for the same
    block lands while the read is still in flight. Contract (checked by
    the fake disk + pin accounting): never two concurrent reads of one
    hash, the disk pin covers every read's full flight, and at teardown
    every pin — disk and device — is released."""

    name = "prefetch_ttl"
    manager_cls: Any = None  # default: production PrefetchManager

    def build(self, env: SpecEnv) -> None:
        from dynamo_tpu.engine.kv_pool import PagePool
        from dynamo_tpu.kvbm.prefetch import PrefetchManager

        cls = self.manager_cls or PrefetchManager
        pool = PagePool(8, 16)
        disk = _FakeDisk(env, blocks=[101], latency=0.2)
        tiered = _Tiered(_FakeHostTier(), disk)
        engine = _FakeEngine(env, pool, tiered)
        mgr = cls(
            engine, max_inflight=2, hint_ttl_s=0.1, pin_ttl_s=0.2,
            metrics=_FakeMetricsNode(), clock=env.loop.time,
        )
        engine._inbox.mgr = mgr
        env.data.update(mgr=mgr, pool=pool, disk=disk)

        async def hinter() -> None:
            mgr.on_hint({"hashes": [101], "parents": [None]})

        async def ticker() -> None:
            for _ in range(8):
                await asyncio.sleep(0.06)
                mgr.tick()

        async def rehinter() -> None:
            await asyncio.sleep(0.15)
            mgr.on_hint({"hashes": [101], "parents": [None]})

        t_hint = env.spawn("hinter", hinter())
        t_tick = env.spawn("ticker", ticker())
        t_rehint = env.spawn("rehinter", rehinter())

        async def closer() -> None:
            # production stop() runs after the step thread joined — i.e.
            # strictly after every hint/tick; model that ordering, then
            # leave the in-flight read time to drain before stopping
            await asyncio.gather(t_hint, t_tick, t_rehint)
            await asyncio.sleep(0.5)
            mgr.stop()

        env.spawn("closer", closer())

    def invariant(self, env: SpecEnv) -> None:
        mgr, pool, disk = env.data["mgr"], env.data["pool"], env.data["disk"]
        for v in env.data["disk_violations"]:
            raise InvariantViolation(v)
        _iv(not disk.inflight, f"reads still in flight: {disk.inflight}")
        _iv(not disk.pinned, f"leaked disk pins: {sorted(disk.pinned)}")
        _iv(not mgr._reading, f"_reading not drained: {sorted(mgr._reading)}")
        _iv(not mgr._jobs, f"jobs leaked past stop(): {list(mgr._jobs)}")
        _iv(not pool.pinned, f"leaked device pins: {sorted(pool.pinned)}")


class _NoAdoptPrefetch:
    """Pre-fix on_hint: always queues a fresh job, double-dispatching the
    disk read when the previous job's read is still in flight. Built
    lazily so importing this module never constructs it by accident."""

    def __new__(cls, *a, **kw):
        from dynamo_tpu.kvbm.prefetch import QUEUED, PrefetchManager, _Job

        class _Twin(PrefetchManager):
            def on_hint(self, hint):
                hashes = [int(h) for h in (hint.get("hashes") or [])]
                parents = list(hint.get("parents") or [])
                if not hashes:
                    return
                self.stats["hints"] += 1
                now = self._clock()
                for i, h in enumerate(hashes):
                    if h in self._jobs or h in self.pool.by_hash:
                        continue
                    parent = parents[i] if i < len(parents) else None
                    parent = int(parent) if parent is not None else None
                    self._jobs[h] = _Job(h, parent, now,
                                         now + self.hint_ttl_s)
                    self._queue.append(h)
                    self.stats["hinted_blocks"] += 1
                self._pump()

        return _Twin(*a, **kw)


class PrefetchTtlBuggySpec(PrefetchTtlSpec):
    name = "prefetch_ttl_buggy"
    expect_violation = True
    manager_cls = _NoAdoptPrefetch


# ---------------------------------------------------------------------------
# indexer_resync — live events racing the seed/recovery dump
# ---------------------------------------------------------------------------

class _NullSub:
    def connect(self, address: str) -> None:
        pass

    def disconnect(self, address: str) -> None:
        pass


class _FakeWorkerState:
    """The worker's own ground truth: feeder events mutate it in the same
    breath they are emitted toward the indexer, and the dump endpoint
    snapshots it at call time (the RPC *response* may still arrive after
    later events — exactly the production race)."""

    def __init__(self) -> None:
        self.blocks: Dict[int, Optional[int]] = {1: None, 2: 1}
        self.last = 3

    def emit(self, idx, event_id: int, kind: str, h: int) -> None:
        from dynamo_tpu.router.protocols import RouterEvent

        if kind == "store":
            self.blocks[h] = None
        else:
            self.blocks.pop(h, None)
        self.last = event_id
        idx._apply(RouterEvent(worker=W, event_id=event_id, kind=kind,
                               block_hashes=[h], parent_hash=None))

    def dump(self, delay: float, alive=None):
        """Snapshot at call time, delivered `delay` later. `alive()`
        models production `_dump_worker`, which raises for an instance
        discovery no longer lists — a dump STARTED after removal fails;
        one captured before and landing after is the epoch guard's job."""

        async def _dump(instance_id: int) -> Dict[str, Any]:
            if alive is not None and not alive():
                raise RuntimeError(f"worker {instance_id:x} gone")
            snap = {"blocks": [(h, p) for h, p in self.blocks.items()],
                    "last_event_id": self.last}
            await asyncio.sleep(delay)
            return snap

        return _dump


class IndexerResyncSpec(Spec):
    """A seed resync (dump RPC in flight for 0.05 virtual seconds) races
    two live events: store(3) at ev4 and remove(1) at ev5. Sequential
    model: whatever the interleaving, the index must converge to the
    worker's true final state {2, 3} with watermark 5 — the unbuffered
    indexer wipes live-applied events with the older snapshot,
    resurrects the removed block, and rewinds the watermark."""

    name = "indexer_resync"
    indexer_cls: Any = None

    def build(self, env: SpecEnv) -> None:
        from dynamo_tpu.router.indexer import KvIndexer
        from dynamo_tpu.router.radix_tree import BlockIndex

        cls = self.indexer_cls or KvIndexer
        truth = _FakeWorkerState()
        idx = cls(_NullSub(), index=BlockIndex(),
                  dump_fn=truth.dump(delay=0.05))
        env.data.update(idx=idx, truth=truth)

        async def resyncer() -> None:
            await idx.resync_worker(W)

        async def feeder() -> None:
            await asyncio.sleep(0.01)
            truth.emit(idx, 4, "store", 3)
            await asyncio.sleep(0.01)
            truth.emit(idx, 5, "remove", 1)

        env.spawn("resyncer", resyncer())
        env.spawn("feeder", feeder())

    def invariant(self, env: SpecEnv) -> None:
        idx, truth = env.data["idx"], env.data["truth"]
        got = set(idx.index.worker_blocks.get(W, set()))
        want = set(truth.blocks)
        # the explorer may stall the loop past DUMP_TIMEOUT_S, in which
        # case the snapshot never applies and only the live events count:
        # degraded ({3}) but correct — a later resync would backfill. What
        # must NEVER appear: the removed block resurrected or the stored
        # block lost ({1, 2} — the unbuffered wipe-and-rewind signature).
        live_only = {3}
        _iv(got in (want, live_only),
            f"index diverged from worker truth: {sorted(got)} != "
            f"{sorted(want)} (lost/resurrected blocks across resync)")
        _iv(idx._last_event_id.get(W) == truth.last,
            f"watermark rewound: {idx._last_event_id.get(W)} != "
            f"{truth.last} — the rewind window re-applies or drops events")


class _UnbufferedIndexer:
    """Pre-fix resync_worker: no event buffering, no epoch guard — the
    dump lands over whatever the live stream did during the await."""

    def __new__(cls, *a, **kw):
        from dynamo_tpu.router.indexer import KvIndexer
        from dynamo_tpu.router.protocols import RouterEvent

        class _Twin(KvIndexer):
            async def resync_worker(self, worker):
                if self._dump_fn is None:
                    return
                try:
                    dump = await asyncio.wait_for(
                        self._dump_fn(worker[0]),
                        timeout=self.DUMP_TIMEOUT_S)
                except asyncio.CancelledError:
                    raise
                except (asyncio.TimeoutError, Exception):
                    return
                self.index.remove_worker(worker)
                blocks = {int(h): (int(p) if p is not None else None)
                          for h, p in dump.get("blocks", [])}
                emitted = set()
                for h0 in list(blocks):
                    chain = []
                    h = h0
                    while (h is not None and h not in emitted
                           and h in blocks):
                        chain.append(h)
                        h = blocks[h]
                    for h in reversed(chain):
                        self.index.apply_event(
                            RouterEvent(worker=worker, event_id=0,
                                        kind="store", block_hashes=[h],
                                        parent_hash=blocks[h]),
                            ttl=self.ttl)
                        emitted.add(h)
                self._last_event_id[worker] = int(
                    dump.get("last_event_id", 0))

        return _Twin(*a, **kw)


class IndexerResyncBuggySpec(IndexerResyncSpec):
    name = "indexer_resync_buggy"
    expect_violation = True
    indexer_cls = _UnbufferedIndexer


# ---------------------------------------------------------------------------
# indexer_churn — discovery delete racing an in-flight resync
# ---------------------------------------------------------------------------

class IndexerChurnSpec(Spec):
    """A discovery delete (remove_worker) lands while the worker's resync
    dump is in flight. Contract: once removed, the worker must stay out
    of the index — a resync completing afterwards must not repopulate it
    with a corpse's blocks (the epoch guard)."""

    name = "indexer_churn"
    indexer_cls: Any = None

    def build(self, env: SpecEnv) -> None:
        from dynamo_tpu.router.indexer import KvIndexer
        from dynamo_tpu.router.radix_tree import BlockIndex

        cls = self.indexer_cls or KvIndexer
        truth = _FakeWorkerState()
        env.data["alive"] = True
        idx = cls(_NullSub(), index=BlockIndex(),
                  dump_fn=truth.dump(delay=0.05,
                                     alive=lambda: env.data["alive"]))
        env.data.update(idx=idx)

        async def resyncer() -> None:
            await idx.resync_worker(W)

        async def remover() -> None:
            await asyncio.sleep(0.03)
            env.data["alive"] = False
            idx.remove_worker(W)

        env.spawn("resyncer", resyncer())
        env.spawn("remover", remover())

    def invariant(self, env: SpecEnv) -> None:
        idx = env.data["idx"]
        ghost = sorted(idx.index.worker_blocks.get(W, set()))
        _iv(not ghost,
            f"removed worker resurrected in the index with blocks {ghost}")
        _iv(W not in idx._last_event_id,
            "removed worker still has an event watermark")


class IndexerChurnBuggySpec(IndexerChurnSpec):
    name = "indexer_churn_buggy"
    expect_violation = True
    indexer_cls = _UnbufferedIndexer


# ---------------------------------------------------------------------------
# migration_handoff — mid-stream worker death and token replay
# ---------------------------------------------------------------------------

class _FlakyEngine:
    """Request-plane fake: two concurrent streams; stream 'a' dies with a
    migratable disconnect after two tokens, the retry finishes it."""

    def __init__(self, env: SpecEnv) -> None:
        self.env = env
        self.attempts: Dict[str, int] = {}

    async def generate(self, request, context):
        from dynamo_tpu.runtime.request_plane import RequestPlaneError

        rid = request["rid"]
        attempt = self.attempts.get(rid, 0) + 1
        self.attempts[rid] = attempt
        base = list(request["token_ids"])
        if rid == "a" and attempt == 1:
            await asyncio.sleep(0.01)
            yield {"token_ids": [101]}
            await asyncio.sleep(0.01)
            yield {"token_ids": [102]}
            await asyncio.sleep(0.01)
            raise RequestPlaneError("worker died", code="disconnected")
        # a retry must carry the already-delivered tokens in its prompt
        self.env.data["replayed"][rid] = base
        await asyncio.sleep(0.01)
        yield {"token_ids": [103], "finish_reason": "stop"}


class MigrationHandoffSpec(Spec):
    """Two requests stream through Migration concurrently; one worker
    connection dies mid-stream. Contract: downstream consumers see every
    token exactly once and in order, the retry's prompt replays exactly
    the tokens already delivered, and the non-failing stream is
    unaffected."""

    name = "migration_handoff"

    def build(self, env: SpecEnv) -> None:
        from dynamo_tpu.frontend.migration import Migration
        from dynamo_tpu.runtime.context import Context

        env.data["replayed"] = {}
        env.data["tokens"] = {"a": [], "b": []}
        engine = _FlakyEngine(env)
        mig = Migration(engine, migration_limit=3, backoff_base_s=0.05)
        env.data.update(engine=engine, mig=mig)

        async def consume(rid: str) -> None:
            ctx = Context(request_id=rid)
            req = {"rid": rid, "token_ids": [1, 2], "stop": {}}
            async for item in mig.generate(req, ctx):
                env.data["tokens"][rid].extend(item.get("token_ids") or [])

        env.spawn("stream_a", consume("a"))
        env.spawn("stream_b", consume("b"))

    def invariant(self, env: SpecEnv) -> None:
        toks = env.data["tokens"]
        _iv(toks["a"] == [101, 102, 103],
            f"stream a delivered {toks['a']} != [101, 102, 103] "
            "(token lost or double-delivered across migration)")
        _iv(toks["b"] == [103], f"stream b delivered {toks['b']} != [103]")
        _iv(env.data["replayed"].get("a") == [1, 2, 101, 102],
            f"retry prompt {env.data['replayed'].get('a')} != "
            "[1, 2, 101, 102] (delivered tokens not folded into replay)")
        _iv(env.data["engine"].attempts == {"a": 2, "b": 1},
            f"attempt counts {env.data['engine'].attempts}")


# ---------------------------------------------------------------------------
# spawn_tracked — fire-and-forget lifecycle accounting
# ---------------------------------------------------------------------------

class SpawnTrackedSpec(Spec):
    """Three tracked background tasks: one finishes, one raises, one is
    cancelled by a fault mid-sleep. Contract: the strong-ref registry
    returns to its baseline (no leak, no premature GC window), the raise
    is consumed by the done-callback (never reaches the loop's unhandled
    sink), and cancellation is not logged as a failure."""

    name = "spawn_tracked"

    def build(self, env: SpecEnv) -> None:
        from dynamo_tpu.runtime.tasks import spawn_tracked, tracked_count

        env.data["baseline"] = tracked_count()
        env.data["done"] = []

        async def ok() -> None:
            await asyncio.sleep(0.01)
            env.data["done"].append("ok")

        async def boom() -> None:
            await asyncio.sleep(0.02)
            raise ValueError("background failure")

        async def sleeper() -> None:
            await asyncio.sleep(5.0)
            env.data["done"].append("sleeper")

        env.data["victim"] = spawn_tracked(
            sleeper(), name="victim", logger=_silent)
        spawn_tracked(ok(), name="ok", logger=_silent)
        spawn_tracked(boom(), name="boom", logger=_silent)

    def faults(self, env: SpecEnv) -> list:
        return [Fault("kill_sleeper",
                      lambda loop: env.data["victim"].cancel(),
                      when=lambda loop: not env.data["victim"].done())]

    def invariant(self, env: SpecEnv) -> None:
        from dynamo_tpu.runtime.tasks import tracked_count

        _iv(tracked_count() == env.data["baseline"],
            f"tracked-task registry leaked "
            f"{tracked_count() - env.data['baseline']} task(s)")
        _iv("ok" in env.data["done"], "completed task lost its side effect")


# ---------------------------------------------------------------------------
# actuator_apply — decide->rehearse->apply claim protocol (DYN-A007)
# ---------------------------------------------------------------------------

class _ActLoads:
    """One busy worker row; only the attributes the Actuator senses."""

    class _Row:
        worker = W
        n_samples = 8
        mean_waiting = 10.0
        mean_running = 4.0
        kv_usage = 0.9
        prefill_tok_s = 100.0
        decode_tok_s = 100.0

    def loads(self, now=None):
        return [self._Row()]


class _ActSlo:
    """Permanently breached fleet view: the condition never clears, so
    re-validation after the rehearsal await always passes — the CLAIM is
    the only thing standing between two overlapping ticks."""

    class _Policy:
        breach_burn = 2.0

    policy = _Policy()

    def evaluate(self, now=None):
        from dynamo_tpu.planner.slo import BREACH

        return {"state": BREACH,
                "fleet": {"ttft_p99": {"phase": "ttft", "state": BREACH,
                                       "fast": {"burn": 4.0}}},
                "workers": {}}


class _ActConnector:
    """Recording connector with a yield inside the apply — the window a
    second unclaimed tick would need to double-send."""

    def __init__(self, applied):
        self.applied = applied

    async def scale_to(self, component, target):
        await asyncio.sleep(0)
        self.applied.append((component, int(target)))


class _SlowOracle:
    """Rehearsal that parks across a timer: the decide->apply span is
    forced open so the explorer can land a whole second tick inside it."""

    async def rehearse(self, decision):
        await asyncio.sleep(0.01)
        return {"improves": True, "oracle": "static"}


class ActuatorApplySpec(Spec):
    """Three actuation ticks race over a breached fleet (the live shape:
    the periodic loop fires while an operator-triggered tick runs, or
    two frontends share a decisions root), with one tick cancellable
    mid-flight (actuator.stop during a rehearsal). The
    decide->rehearse->apply span crosses the rehearsal await, so the
    REAL Actuator claims the (kind, target) in `_inflight` BEFORE
    awaiting and re-checks after (planner/actuator.py `_execute`).
    Contract: the breach is acted on at most once — overlapping ticks
    must not double-scale — exactly once when nothing is cancelled,
    decisions reach terminal journal status, and no claim outlives its
    tick (cancellation included: the finally must release)."""

    name = "actuator_apply"

    actuator_cls = None  # default: the production Actuator

    def build(self, env: SpecEnv) -> None:
        from dynamo_tpu.planner.actuator import Actuator, ActuatorConfig

        applied: List[Any] = []
        env.data["applied"] = applied
        cls = self.actuator_cls or Actuator
        act = cls(
            _ActLoads(), _ActSlo(), _ActConnector(applied),
            ActuatorConfig(hysteresis_ticks=1, cooldown_s=1e9,
                           flap_guard_s=1e9, min_samples=1,
                           waiting_high=1.0),
            shadow=_SlowOracle(),
            replicas_fn=lambda: 1,
            clock=env.loop.time,
        )
        env.data["act"] = act

        async def ticker(name: str) -> None:
            try:
                await act.tick()
            except asyncio.CancelledError:
                env.data["cancelled"] = True
                raise

        env.spawn("tick_a", ticker("a"))
        env.spawn("tick_b", ticker("b"))
        env.spawn("tick_c", ticker("c"))

    def faults(self, env: SpecEnv) -> list:
        return [cancel_task("cancel_tick_b",
                            lambda loop: env.task("tick_b"))]

    def invariant(self, env: SpecEnv) -> None:
        act = env.data["act"]
        applied = env.data["applied"]
        cancelled = env.data.get("cancelled", False)
        for t in ("tick_a", "tick_b", "tick_c"):
            task = env.task(t)
            _iv(task is not None and task.done(), f"{t} parked forever")
        _iv(len(applied) <= 1,
            f"breach applied {len(applied)}x (claim protocol broken: "
            f"{applied})")
        if not cancelled:
            _iv(len(applied) == 1, "sustained breach never acted on")
        _iv(not act._inflight, f"leaked in-flight claims: {act._inflight}")
        from dynamo_tpu.planner.actuator import TERMINAL

        stuck = [d for d in act.journal.decisions()
                 if d.status not in TERMINAL]
        # a cancelled tick may orphan ITS decision mid-rehearsal; any
        # other non-terminal decision is a journaling bug
        _iv(len(stuck) <= (1 if cancelled else 0),
            f"decisions stuck non-terminal: "
            f"{[(d.decision_id, d.status) for d in stuck]}")


class _RacyActuator:
    """Buggy twin: claims the target AFTER the rehearsal await — the
    pre-claim-protocol shape. Two overlapping ticks both pass the gates,
    both rehearse, both apply: a double-scale."""

    def __new__(cls, *a, **kw):
        from dynamo_tpu.planner.actuator import Actuator

        class _Twin(Actuator):
            async def _execute(self, d):
                key = d.target_key
                if key in self._inflight:
                    self._finish(d, "skipped", note="in-flight")
                    return
                self._record(d, "rehearsed")
                d.verdict = await self.shadow.rehearse(d)  # BUG: no claim
                self._inflight.add(key)                    # ...until here
                try:
                    if await self._apply(d):
                        self._cooldown_until[key] = (
                            self.clock() + self.config.cooldown_s)
                        self._finish(d, "applied")
                    else:
                        self._finish(d, "failed")
                finally:
                    self._inflight.discard(key)

        return _Twin(*a, **kw)


class ActuatorApplyBuggySpec(ActuatorApplySpec):
    name = "actuator_apply_buggy"
    expect_violation = True
    actuator_cls = _RacyActuator


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

# production specs: every interleaving must hold (mc_ok gate)
SPECS: Dict[str, Any] = {
    s.name: s for s in (
        AdmissionQueueSpec,
        PrefetchTtlSpec,
        IndexerResyncSpec,
        IndexerChurnSpec,
        MigrationHandoffSpec,
        SpawnTrackedSpec,
        ActuatorApplySpec,
    )
}

# known-bad twins + seeded fixture: the checker must FIND a violation
FIXTURES: Dict[str, Any] = {
    s.name: s for s in (
        LostWakeupFixture,
        PrefetchTtlBuggySpec,
        IndexerResyncBuggySpec,
        IndexerChurnBuggySpec,
        ActuatorApplyBuggySpec,
    )
}

ALL_SPECS: Dict[str, Any] = {**SPECS, **FIXTURES}
