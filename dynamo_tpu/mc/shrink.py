"""Schedule shrinking: minimal replayable reproduction of a violation.

A violation usually surfaces deep in the DFS with a long decision list,
most of which is incidental. The shrinker reduces it with three passes,
each preserving "still violates" as the invariant:

1. prefix minimization — decisions past the forced prefix default to 0,
   so `sched[:k]` is a legal schedule; binary-search the shortest
   failing prefix (with a linear fallback, since failure need not be
   monotone in k);
2. zero-out — try rewriting each non-default decision to 0, repeating
   to a fixpoint (greedy delta debugging at granularity 1);
3. strip trailing zeros — they are literally the default.

The result is what gets committed under tests/data/mc_schedules/ as a
regression: small enough to read as a story ("consumer checks, producer
publishes, consumer parks") and replayed verbatim by tier-1.
"""

from __future__ import annotations

from typing import Callable, List

__all__ = ["shrink"]


def _strip(sched: List[int]) -> List[int]:
    out = list(sched)
    while out and out[-1] == 0:
        out.pop()
    return out


def shrink(
    fails: Callable[[List[int]], bool],
    schedule: List[int],
    budget: int = 200,
) -> List[int]:
    """Minimize `schedule` while `fails(schedule)` stays True. `fails`
    must be deterministic (replay the spec under the candidate schedule
    and report whether it still violates). `budget` caps replay calls."""
    calls = [0]

    def check(s: List[int]) -> bool:
        if calls[0] >= budget:
            return False
        calls[0] += 1
        return fails(s)

    sched = _strip(schedule)
    if not check(sched):
        return _strip(schedule)  # not reproducible under budget: keep as-is

    # 1. shortest failing prefix: binary search first (cheap when failure
    # is prefix-monotone), then a linear tightening pass to be safe
    lo, hi = 0, len(sched)
    while lo < hi:
        mid = (lo + hi) // 2
        if check(sched[:mid]):
            hi = mid
        else:
            lo = mid + 1
    if check(sched[:hi]):
        sched = _strip(sched[:hi])
    while sched and check(sched[:-1]):
        sched = _strip(sched[:-1])

    # 2. zero-out non-default decisions to a fixpoint
    changed = True
    while changed and calls[0] < budget:
        changed = False
        for i, d in enumerate(sched):
            if d == 0:
                continue
            cand = _strip(sched[:i] + [0] + sched[i + 1:])
            if check(cand):
                sched = cand
                changed = True
                break

    return _strip(sched)
