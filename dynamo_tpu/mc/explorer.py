"""Schedule executor and interleaving explorer.

`Scheduler` runs ONE schedule: it drives a `VirtualLoop` step by step,
and at every branch point (more than one enabled action) consumes the
next index from the schedule — 0 (stock asyncio order) once the list is
exhausted. It records the decision actually taken at every branch point
plus the alternative indices worth exploring (after the footprint
reduction), which is exactly what the explorer needs to extend the
search frontier.

`Explorer` is iterative DFS over schedules: run a schedule, and for
every branch point at or past the forced prefix, push
`decisions[:i] + [alt]` for each unexplored alternative. Alternatives
whose label resolves inside a function the static pass flagged
(DYN-A007/R008, via `footprint.hazard_names`) are pushed last, so the
LIFO frontier explores them first — static findings steer the dynamic
search. Violating runs are recorded but not expanded (their suffix is
already broken; the shrinker minimizes them instead).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Set, Tuple

from dynamo_tpu.mc.footprint import branch_candidates, enabled_choices
from dynamo_tpu.mc.spec import InvariantViolation, Spec, SpecEnv, schedule_id
from dynamo_tpu.mc.vloop import VirtualLoop

__all__ = ["Scheduler", "Explorer", "RunResult", "ExploreResult"]

# branch record: (decision_index, [(alt_choice_index, alt_label), ...])
Branch = Tuple[int, List[Tuple[int, str]]]


@dataclass
class RunResult:
    spec: str
    decisions: List[int]          # decision taken at each branch point
    sid: str                      # schedule_id(decisions)
    steps: int
    violation: Optional[str]      # invariant message, or None
    trace: List[str]              # label of the action chosen at each step
    branches: List[Branch] = field(default_factory=list)
    quiescent: bool = True

    @property
    def ok(self) -> bool:
        return self.violation is None


@dataclass
class ExploreResult:
    spec: str
    runs: int                     # distinct schedules executed
    violations: List[RunResult]
    max_decisions: int
    frontier_left: int            # schedules still unexplored at budget

    @property
    def ok(self) -> bool:
        return not self.violations


class Scheduler:
    """Deterministically execute one schedule of one spec instance."""

    def __init__(self, spec: Spec, schedule: List[int]) -> None:
        self.spec = spec
        self.schedule = list(schedule)

    def run(self) -> RunResult:
        spec = self.spec
        loop = VirtualLoop()
        env = SpecEnv(loop)
        decisions: List[int] = []
        branches: List[Branch] = []
        trace: List[str] = []
        violation: Optional[str] = None
        steps = 0
        quiescent = False
        with loop:
            try:
                spec.build(env)
                faults = list(spec.faults(env))
                while steps < spec.max_steps:
                    cands = enabled_choices(loop, spec.footprints, faults)
                    if not cands:
                        quiescent = True
                        break
                    if len(cands) > 1:
                        di = len(decisions)
                        want = (self.schedule[di]
                                if di < len(self.schedule) else 0)
                        idx = want if 0 <= want < len(cands) else 0
                        alts = [(a, cands[a].label)
                                for a in branch_candidates(cands)
                                if a != idx]
                        branches.append((di, alts))
                        decisions.append(idx)
                    else:
                        idx = 0
                    c = cands[idx]
                    trace.append(c.label)
                    if c.kind == "run":
                        loop.current_footprint = c.footprint
                        try:
                            loop.run_handle(c.handle)
                        finally:
                            loop.current_footprint = None
                    elif c.kind == "advance":
                        loop.advance_to_next_timer()
                    else:
                        c.fault.fire(loop)
                    steps += 1
                    spec.step_invariant(env)
                try:
                    spec.invariant(env)
                except InvariantViolation as e:
                    violation = str(e)
                if violation is None and not quiescent:
                    violation = (f"did not quiesce within "
                                 f"{spec.max_steps} steps")
                if (violation is None and spec.fail_on_loop_exceptions
                        and loop.exceptions):
                    ctx = loop.exceptions[0]
                    violation = ("unhandled loop exception: "
                                 f"{ctx.get('message')}: "
                                 f"{ctx.get('exception')!r}")
            except InvariantViolation as e:
                violation = str(e)
            finally:
                self._teardown(loop)
        loop.close()
        return RunResult(
            spec=spec.name, decisions=decisions,
            sid=schedule_id(decisions), steps=steps, violation=violation,
            trace=trace, branches=branches, quiescent=quiescent,
        )

    @staticmethod
    def _teardown(loop: VirtualLoop) -> None:
        """Cancel every live task and drain, so no coroutine outlives the
        run (a pending task warns at GC from a DIFFERENT run's context,
        which would poison that run's exception check)."""
        for t in loop.tasks:
            if not t.done():
                t.cancel()
        for _ in range(2000):
            handles = loop.ready_handles()
            if handles:
                loop.run_handle(handles[0])
            elif loop.next_timer_due() is not None:
                loop.advance_to_next_timer()
            else:
                break
        for t in loop.tasks:
            if t.done() and not t.cancelled():
                t.exception()  # retrieve, silencing GC-time warnings


class Explorer:
    """Bounded DFS over the schedule tree of one spec.

    `spec_factory` must return a FRESH spec instance per run — specs
    hold per-run protocol state. `hazards` is the set of function names
    the static pass flagged; matching alternatives explore first.
    """

    def __init__(
        self,
        spec_factory: Callable[[], Spec],
        *,
        max_runs: int = 200,
        hazards: Optional[Set[str]] = None,
        stop_on_first: bool = False,
    ) -> None:
        self.spec_factory = spec_factory
        self.max_runs = max(1, int(max_runs))
        self.hazards = hazards or set()
        self.stop_on_first = stop_on_first

    def run_schedule(self, schedule: List[int]) -> RunResult:
        return Scheduler(self.spec_factory(), schedule).run()

    def _hazardous(self, label: str) -> bool:
        # task labels look like "name@func:line", callbacks "cb:qualname"
        if "@" in label:
            fn = label.rsplit("@", 1)[1].rsplit(":", 1)[0]
        elif label.startswith("cb:"):
            fn = label[3:].rsplit(".", 1)[-1]
        else:
            return False
        return fn in self.hazards

    def explore(self) -> ExploreResult:
        frontier: List[List[int]] = [[]]
        seen = {schedule_id([])}
        violations: List[RunResult] = []
        runs = 0
        max_decisions = 0
        name = self.spec_factory().name
        while frontier and runs < self.max_runs:
            sched = frontier.pop()
            rr = self.run_schedule(sched)
            runs += 1
            max_decisions = max(max_decisions, len(rr.decisions))
            if rr.violation is not None:
                violations.append(rr)
                if self.stop_on_first:
                    break
                continue  # a broken suffix is not worth extending
            fresh: List[Tuple[bool, List[int]]] = []
            for di, alts in rr.branches:
                if di < len(sched):
                    continue  # fixed by the forced prefix
                prefix = rr.decisions[:di]
                for alt, label in alts:
                    s2 = prefix + [alt]
                    sid = schedule_id(s2)
                    if sid not in seen:
                        seen.add(sid)
                        fresh.append((self._hazardous(label), s2))
            # LIFO frontier: push hazard-flagged alternatives last so they
            # pop (and therefore run) first
            fresh.sort(key=lambda t: t[0])
            frontier.extend(s for _, s in fresh)
        return ExploreResult(
            spec=name, runs=runs, violations=violations,
            max_decisions=max_decisions, frontier_left=len(frontier),
        )
