"""dynmc — deterministic-schedule concurrency model checker.

The control plane is a stack of asyncio protocol state machines
(admission queue hand-offs, KV prefetch promotion, indexer resync,
discovery churn, migration retries). pytest observes exactly ONE
interleaving of those coroutines per run — whichever order the wall
clock happens to produce. dynmc removes the wall clock: protocol specs
run the *real production coroutines* on a virtual-clock event loop
(`vloop.VirtualLoop`) where every ready callback, timer expiry, and
injected fault is a schedulable choice, and an explorer
(`explorer.Explorer`) enumerates the choice tree:

- schedules are plain decision-index lists, so any run replays
  deterministically from its schedule id;
- a DPOR-style reduction (`footprint.py`) prunes orderings of actions
  whose declared shared-state footprints are disjoint;
- faults (`faults.py`) — task cancel, peer death, slow plane — appear
  as extra one-shot candidates at every branch point;
- failures shrink (`shrink.py`) to a minimal schedule that is committed
  as a regression spec and replayed in tier-1;
- the static pass seeds the search: DYN-A007/R008 sites from
  `dynamo_tpu.lint.project.atomicity_hazards` mark the functions whose
  yield points the explorer perturbs first.

See docs/concurrency.md for the architecture and a spec-writing guide;
`scripts/dynmc.py` is the CLI (smoke tier in check_tier1, `--deep` for
the full budget).
"""

from dynamo_tpu.mc.explorer import ExploreResult, Explorer, RunResult, Scheduler
from dynamo_tpu.mc.faults import Fault
from dynamo_tpu.mc.shrink import shrink
from dynamo_tpu.mc.spec import (
    InvariantViolation,
    Spec,
    SpecEnv,
    decode_schedule_id,
    schedule_id,
)
from dynamo_tpu.mc.vloop import VirtualLoop

__all__ = [
    "Explorer",
    "ExploreResult",
    "RunResult",
    "Scheduler",
    "Fault",
    "InvariantViolation",
    "Spec",
    "SpecEnv",
    "VirtualLoop",
    "schedule_id",
    "decode_schedule_id",
    "shrink",
]
