"""Fault injection as scheduling choices.

A fault is a one-shot action the environment can take at any branch
point: cancel a task mid-await, kill a fake peer, fail a pending
future, stall a plane. Representing faults as *candidates* (rather than
spec-scripted events) means the explorer decides WHEN they land — the
entire point, since the bugs live in the window between two particular
yield points, not in whether the fault happens at all.

Each fault fires at most once per run (`armed` resets via `reset()`
between runs) and may gate itself on loop state via `enabled` (e.g.
"only after the consumer parked"). The action runs synchronously at the
branch point; anything it schedules (callbacks from `Task.cancel`,
futures it resolves) lands on the virtual ready queue and is itself
schedulable.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

__all__ = ["Fault", "cancel_task"]


class Fault:
    """One-shot environment action, offered as a branch-point candidate.

    `action(loop)` performs the fault; `when(loop) -> bool` (optional)
    gates whether it is currently offered. Exploration treats an armed,
    enabled fault exactly like a ready handle: firing it is one more
    decision index.
    """

    def __init__(
        self,
        name: str,
        action: Callable[[Any], None],
        when: Optional[Callable[[Any], bool]] = None,
    ) -> None:
        self.name = name
        self._action = action
        self._when = when
        self.armed = True

    def enabled(self, loop: Any) -> bool:
        return self._when is None or bool(self._when(loop))

    def fire(self, loop: Any) -> None:
        self.armed = False
        self._action(loop)

    def reset(self) -> None:
        self.armed = True

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Fault({self.name!r}, armed={self.armed})"


def cancel_task(name: str, pick: Callable[[Any], Any]) -> Fault:
    """Fault that cancels the task `pick(loop)` returns (None → disabled).
    Offered only while the task is alive and suspended."""

    def _alive(loop: Any) -> bool:
        t = pick(loop)
        return t is not None and not t.done()

    def _cancel(loop: Any) -> None:
        t = pick(loop)
        if t is not None and not t.done():
            t.cancel()

    return Fault(name, _cancel, when=_alive)
