"""Virtual-clock asyncio event loop with externally-scheduled callbacks.

`VirtualLoop` subclasses `asyncio.AbstractEventLoop` but never runs a
poll loop of its own: it only *collects* ready handles and timers, and a
driver (`dynamo_tpu.mc.explorer.Scheduler`) decides which handle runs
next and when virtual time advances. Everything else is genuine CPython
asyncio — real `asyncio.Task`, real `asyncio.Future`, real
`asyncio.Handle` — so coroutines under test execute with production
semantics (including the 3.10 `wait_for` completed-before-cancelled
hand-off this repo's AdmissionQueue depends on). The loop is installed
via `asyncio.events._set_running_loop`, so `get_running_loop()`,
`asyncio.sleep`, `asyncio.Queue`, locks, and `create_task` inside the
code under test all land here.

Determinism contract: no wall clock (time starts at 0.0 and only moves
when the driver fires a timer), FIFO ready order (choice 0 always equals
what stock asyncio would run next), and heap-with-sequence-tiebreak
timer order.
"""

from __future__ import annotations

import asyncio
import heapq
from asyncio import events
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

__all__ = ["VirtualLoop", "task_location"]


def task_location(task: asyncio.Task) -> str:
    """`func:line` of the innermost suspended coroutine frame — where the
    task will resume. Used for trace rendering and hazard prioritization."""
    try:
        coro = task.get_coro()
        while True:
            inner = getattr(coro, "cr_await", None)
            if inner is None or not hasattr(inner, "cr_code"):
                break
            coro = inner
        code = getattr(coro, "cr_code", None)
        frame = getattr(coro, "cr_frame", None)
        if code is None:
            return "?"
        line = frame.f_lineno if frame is not None else code.co_firstlineno
        return f"{code.co_name}:{line}"
    except Exception:
        return "?"


class _McHandle(asyncio.Handle):
    # Handle is slotted; one extra slot carries the scheduling footprint
    __slots__ = ("_mc_footprint",)


class _McTimerHandle(asyncio.TimerHandle):
    __slots__ = ("_mc_footprint",)


class VirtualLoop(asyncio.AbstractEventLoop):
    """The schedulable substrate. Public driver surface:

    - `ready_handles()` — live `call_soon` handles, FIFO order
    - `run_handle(h)` — execute one handle (removed from the queue)
    - `next_timer_due()` / `advance_to_next_timer()` — virtual time
    - `exceptions` — contexts passed to `call_exception_handler`
    - `tasks` — every task the loop created, in creation order
    """

    def __init__(self) -> None:
        self._time = 0.0
        self._ready: Deque[asyncio.Handle] = deque()
        self._timers: List[Tuple[float, int, asyncio.TimerHandle]] = []
        self._seq = 0
        self._closed = False
        self.exceptions: List[Dict[str, Any]] = []
        self.tasks: List[asyncio.Task] = []
        # footprint of the handle currently executing; handles scheduled
        # from inside it inherit this (see explorer.Scheduler)
        self.current_footprint: Optional[frozenset] = None

    # -- clock -------------------------------------------------------------
    def time(self) -> float:
        return self._time

    # -- scheduling primitives asyncio machinery calls ---------------------
    def call_soon(self, callback, *args, context=None) -> asyncio.Handle:
        handle = _McHandle(callback, args, self, context)
        handle._mc_footprint = self.current_footprint
        self._ready.append(handle)
        return handle

    # single-threaded model checking: "threadsafe" is the same queue
    call_soon_threadsafe = call_soon

    def call_later(self, delay, callback, *args, context=None):
        return self.call_at(self._time + max(0.0, delay), callback, *args,
                            context=context)

    def call_at(self, when, callback, *args, context=None):
        handle = _McTimerHandle(when, callback, args, self, context)
        handle._mc_footprint = self.current_footprint
        self._seq += 1
        heapq.heappush(self._timers, (when, self._seq, handle))
        return handle

    def _timer_handle_cancelled(self, handle) -> None:
        pass  # cancelled timers are skipped lazily when popped

    # -- futures / tasks ---------------------------------------------------
    def create_future(self) -> asyncio.Future:
        return asyncio.Future(loop=self)

    def create_task(self, coro, *, name=None, context=None) -> asyncio.Task:
        if name is None:
            # asyncio's default Task-<n> names use a process-global counter,
            # which would make traces differ between otherwise identical
            # runs; number per-loop instead so replay stays byte-identical
            name = f"task#{len(self.tasks) + 1}"
        task = asyncio.Task(coro, loop=self, name=name)
        self.tasks.append(task)
        return task

    # -- loop state the asyncio internals probe ----------------------------
    def is_running(self) -> bool:
        return not self._closed

    def is_closed(self) -> bool:
        return self._closed

    def close(self) -> None:
        self._closed = True
        self._ready.clear()
        self._timers.clear()

    def get_debug(self) -> bool:
        return False

    def set_debug(self, enabled: bool) -> None:
        pass

    # -- error sink --------------------------------------------------------
    def default_exception_handler(self, context) -> None:
        self.exceptions.append(dict(context))

    def call_exception_handler(self, context) -> None:
        self.exceptions.append(dict(context))

    # -- driver surface ----------------------------------------------------
    def ready_handles(self) -> List[asyncio.Handle]:
        """Live ready handles, FIFO. Cancelled handles are purged."""
        while self._ready and self._ready[0]._cancelled:
            self._ready.popleft()
        return [h for h in self._ready if not h._cancelled]

    def run_handle(self, handle: asyncio.Handle) -> None:
        """Execute one ready handle out of the queue. Removal is by
        identity — TimerHandle defines value equality, and two timers for
        the same (when, callback) must stay distinct."""
        for i, x in enumerate(self._ready):
            if x is handle:
                del self._ready[i]
                break
        else:
            return  # already run or cancelled-and-purged
        if not handle._cancelled:
            handle._run()

    def _purge_timers(self) -> None:
        while self._timers and self._timers[0][2]._cancelled:
            heapq.heappop(self._timers)

    def next_timer_due(self) -> Optional[float]:
        self._purge_timers()
        return self._timers[0][0] if self._timers else None

    def advance_to_next_timer(self) -> int:
        """Jump virtual time to the earliest pending deadline and move
        every timer due at that instant onto the ready queue. Returns how
        many timers fired. Modeling note: offering this as a choice even
        while callbacks are ready is what expresses timeout races — a
        busy loop CAN let wall time pass before servicing a callback."""
        self._purge_timers()
        if not self._timers:
            return 0
        due = self._timers[0][0]
        self._time = max(self._time, due)
        fired = 0
        while self._timers and self._timers[0][0] <= due:
            _, _, th = heapq.heappop(self._timers)
            if th._cancelled:
                continue
            # the TimerHandle itself moves to ready (stock BaseEventLoop
            # behavior): a cancel() that lands before it runs still wins
            self._ready.append(th)
            fired += 1
        return fired

    def quiescent(self) -> bool:
        self._purge_timers()
        return not self.ready_handles() and not self._timers

    # -- context manager: install as the running loop ----------------------
    def __enter__(self) -> "VirtualLoop":
        if events._get_running_loop() is not None:
            raise RuntimeError("VirtualLoop cannot nest inside a running loop")
        events._set_running_loop(self)
        return self

    def __exit__(self, *exc) -> None:
        events._set_running_loop(None)
