"""Protocol specs: production coroutines + environment + invariants.

A Spec packages three things: `build(env)` constructs the protocol under
test (REAL production objects — AdmissionQueue, KvIndexer,
PrefetchManager — with only their I/O planes faked) and spawns the
driver tasks; `faults(env)` declares the one-shot environment actions
the explorer may inject; `invariant(env)` (at quiescence) and
`step_invariant(env)` (after every scheduled action) raise
InvariantViolation when the protocol's contract is broken.

Schedules are plain decision-index lists: at every branch point (>1
enabled action) the scheduler consumes the next index, defaulting to 0
(stock-asyncio order) when the list is exhausted. `schedule_id` encodes
the list as a replayable string (`s.0.1.2`), so a violation in CI is
one `scripts/dynmc.py --replay <spec> <id>` away from a deterministic
local reproduction.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Any, Dict, FrozenSet, List, Optional

__all__ = [
    "InvariantViolation",
    "Spec",
    "SpecEnv",
    "schedule_id",
    "decode_schedule_id",
    "LostWakeupFixture",
]


class InvariantViolation(AssertionError):
    """A spec invariant failed under some interleaving."""


def schedule_id(schedule: List[int]) -> str:
    """`[0, 1, 2]` -> `"s.0.1.2"`; `[]` -> `"s"` (the default run)."""
    return "s" + "".join(f".{int(d)}" for d in schedule)


def decode_schedule_id(sid: str) -> List[int]:
    if not sid or sid[0] != "s":
        raise ValueError(f"not a schedule id: {sid!r}")
    body = sid[1:]
    if not body:
        return []
    if not body.startswith("."):
        raise ValueError(f"not a schedule id: {sid!r}")
    return [int(x) for x in body[1:].split(".")]


class SpecEnv:
    """Per-run world handed to the spec: the virtual loop, the named
    driver tasks, and a scratch dict for protocol state + counters."""

    def __init__(self, loop) -> None:
        self.loop = loop
        self.tasks: Dict[str, asyncio.Task] = {}
        self.data: Dict[str, Any] = {}

    def spawn(self, name: str, coro) -> asyncio.Task:
        task = self.loop.create_task(coro, name=name)
        self.tasks[name] = task
        return task

    def task(self, name: str) -> Optional[asyncio.Task]:
        return self.tasks.get(name)


class Spec:
    """Base spec. Subclass and override `build` + `invariant`."""

    name = "spec"
    # hard cap on scheduled actions per run (divergence guard)
    max_steps = 4000
    # fixture specs are EXPECTED to violate; excluded from production gating
    expect_violation = False
    # task name -> shared-state footprint for the POR reduction; anything
    # absent conflicts with everything (sound default)
    footprints: Dict[str, FrozenSet[str]] = {}
    # treat contexts reaching loop.call_exception_handler as violations
    fail_on_loop_exceptions = True

    def build(self, env: SpecEnv) -> None:
        raise NotImplementedError

    def faults(self, env: SpecEnv) -> list:
        return []

    def step_invariant(self, env: SpecEnv) -> None:
        pass

    def invariant(self, env: SpecEnv) -> None:
        pass


# ---------------------------------------------------------------------------
# Seeded fixture: a known lost-wakeup, kept as the checker's own regression.
# ---------------------------------------------------------------------------

class LeakyQueue:
    """Deliberately buggy hand-rolled queue: `get` checks emptiness, hits
    a yield point, then parks WITHOUT re-checking — the textbook DYN-A007
    shape. A put landing inside that window sees no parked waiter (it has
    not registered yet) while the consumer parks forever next to a
    non-empty buffer. Exists to prove dynmc finds and shrinks real lost
    wakeups; never import this outside tests."""

    def __init__(self) -> None:
        self._items: deque = deque()
        self._waiters: deque = deque()

    def put_nowait(self, item: Any) -> None:
        self._items.append(item)
        while self._waiters:
            w = self._waiters.popleft()
            if not w.done():
                w.set_result(None)
                break

    async def get(self) -> Any:
        if not self._items:
            await asyncio.sleep(0)  # BUG: check-then-park spans a yield
            w = asyncio.get_running_loop().create_future()
            self._waiters.append(w)
            await w
        return self._items.popleft()


class LostWakeupFixture(Spec):
    """Consumer parks on LeakyQueue.get while a timer-delayed producer
    puts one item. The stock-asyncio order passes; the interleaving where
    the put lands between the consumer's emptiness check and its park
    loses the wakeup. Acceptance fixture: the explorer must find it and
    shrink it to a handful of decisions."""

    name = "fixture_lost_wakeup"
    expect_violation = True
    max_steps = 200

    def build(self, env: SpecEnv) -> None:
        q = LeakyQueue()
        env.data["q"] = q

        async def consumer() -> None:
            env.data["got"] = await q.get()

        async def producer() -> None:
            await asyncio.sleep(0.01)
            q.put_nowait("x")

        env.spawn("consumer", consumer())
        env.spawn("producer", producer())

    def invariant(self, env: SpecEnv) -> None:
        t = env.task("consumer")
        if t is None or not t.done() or env.data.get("got") != "x":
            raise InvariantViolation(
                "lost wakeup: consumer parked forever while the queue "
                "holds an item")
