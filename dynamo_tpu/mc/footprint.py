"""Scheduling choices, shared-state footprints, and DPOR-style pruning.

At every scheduler step the enabled actions are: run one ready handle
(one candidate per handle, FIFO order — candidate 0 is what stock
asyncio would do), advance virtual time to the next timer deadline (the
"the loop was busy long enough for the timeout to fire" branch), or
fire an armed fault. Exploring every permutation of those is factorial;
most of it is noise because most actions touch disjoint state.

The reduction here is footprint-based partial-order reduction in the
DPOR spirit, deliberately conservative: each candidate carries a
footprint — a frozenset of state keys declared per task by the spec
(`Spec.footprints`), inherited by callbacks a task schedules, with
`{"*"}` (conflicts with everything) as the default for anything
undeclared. At a branch point, a candidate that conflicts with no other
enabled candidate commutes with all of them, so only its canonical
(default-order) position is explored; alternatives are generated only
for candidates that conflict with something. Soundness note: with
default `{"*"}` footprints nothing is pruned; pruning only happens
where a spec explicitly declares independence, which keeps the
reduction's correctness a local, reviewable claim per spec.

The static seed: `hazard_names(paths)` runs the dynlint fact extractor
over production modules and returns the function names flagged by
DYN-A007/R008. The explorer orders alternative branches so candidates
about to resume inside a flagged function are explored first — the
static pass points the dynamic search at the code most likely to race.
"""

from __future__ import annotations

import asyncio
import functools
import os
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Set

from dynamo_tpu.mc.vloop import VirtualLoop, task_location

__all__ = ["Choice", "CONFLICTS_ALL", "branch_candidates", "hazard_names"]

CONFLICTS_ALL: FrozenSet[str] = frozenset({"*"})


@dataclass
class Choice:
    """One enabled action at a branch point."""

    kind: str  # "run" | "advance" | "fault"
    label: str
    footprint: FrozenSet[str] = CONFLICTS_ALL
    handle: Any = None  # asyncio.Handle for kind="run"
    fault: Any = None   # Fault for kind="fault"

    def conflicts(self, other: "Choice") -> bool:
        if "*" in self.footprint or "*" in other.footprint:
            return True
        return bool(self.footprint & other.footprint)


def _owner_task(handle) -> Optional[asyncio.Task]:
    cb = getattr(handle, "_callback", None)
    owner = getattr(cb, "__self__", None)
    return owner if isinstance(owner, asyncio.Task) else None


def choice_for_handle(
    handle,
    footprints: Dict[str, FrozenSet[str]],
) -> Choice:
    """Label + footprint for a ready handle. Task steps get the task's
    declared footprint and a `name@func:line` label; bare callbacks
    inherit the footprint of the task that scheduled them (stamped by
    VirtualLoop.call_soon), else conflict with everything."""
    task = _owner_task(handle)
    if task is not None:
        name = task.get_name()
        fp = footprints.get(name, CONFLICTS_ALL)
        return Choice("run", f"{name}@{task_location(task)}", fp, handle=handle)
    cb = getattr(handle, "_callback", None)
    while isinstance(cb, functools.partial):  # partial repr embeds 0x addrs
        cb = cb.func
    label = getattr(cb, "__qualname__", repr(cb))
    inherited = getattr(handle, "_mc_footprint", None)
    return Choice("run", f"cb:{label}", inherited or CONFLICTS_ALL,
                  handle=handle)


def enabled_choices(
    loop: VirtualLoop,
    footprints: Dict[str, FrozenSet[str]],
    faults: Sequence[Any] = (),
) -> List[Choice]:
    """The full candidate list at the current state, index-stable for
    replay: ready handles in FIFO order, then time-advance if any timer
    is pending, then armed faults in declaration order."""
    cands = [choice_for_handle(h, footprints) for h in loop.ready_handles()]
    if loop.next_timer_due() is not None:
        cands.append(Choice("advance",
                            f"advance-time->{loop.next_timer_due():g}"))
    for f in faults:
        if f.armed and f.enabled(loop):
            cands.append(Choice("fault", f"fault:{f.name}", fault=f))
    return cands


def branch_candidates(cands: List[Choice]) -> List[int]:
    """Indices worth exploring as ALTERNATIVES to the default (index 0).
    A candidate disjoint from every other enabled candidate commutes with
    all of them — running it now vs. later yields an equivalent trace, so
    its default-order position is canonical and it generates no branch."""
    if len(cands) <= 1:
        return []
    out = []
    for i, c in enumerate(cands):
        if i == 0:
            continue  # index 0 is the default path, always taken
        if any(c.conflicts(d) for j, d in enumerate(cands) if j != i):
            out.append(i)
    return out


def hazard_names(paths: Sequence[str], root: Optional[str] = None) -> Set[str]:
    """Function names flagged DYN-A007/R008 across `paths` — the static
    atomicity pass as dynamic-exploration seeds. Suppressed findings are
    included on purpose (see `atomicity_hazards`)."""
    from dynamo_tpu.lint.project import atomicity_hazards, extract_module_facts

    facts = []
    for path in paths:
        files = [path] if os.path.isfile(path) else [
            os.path.join(dp, f)
            for dp, _, fs in os.walk(path) for f in sorted(fs)
            if f.endswith(".py")
        ]
        for f in files:
            rel = os.path.relpath(f, root) if root else f
            try:
                with open(f, encoding="utf-8") as fh:
                    facts.append(extract_module_facts(rel, fh.read()))
            except OSError:
                continue
    return {h["fn"] for h in atomicity_hazards(facts)}
