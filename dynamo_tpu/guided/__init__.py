"""Guided decoding: constrained generation via byte-level DFAs.

Analog of the reference's guided-decoding surface (tool_choice
enforcement, JSON-schema response_format, structural tags — ref
lib/llm/src/preprocessor.rs:286 and lib/llm/src/preprocessor/tools/),
re-designed for the TPU engine:

- constraints compile on the FRONTEND to a compact byte-level DFA
  (regex subset / JSON schema → regex / structural-tag composite);
- the worker lifts the byte DFA to per-state TOKEN masks against its
  tokenizer (lazy per-state rows, so 128k-vocab tables never
  materialize);
- the engine samples with the mask applied to logits inside the jitted
  step (mask rides as a [B, V] input array — no recompile per schema),
  host-advancing each sequence's DFA state per accepted token.

Wire format (PreprocessedRequest["guided"]):
  {"kind": "regex", "pattern": <pattern>}
  {"kind": "structural", "triggers": [...],
   "structures": [{"begin": s, "pattern": p, "end": s}, ...]}
"""

from dynamo_tpu.guided.regex_dfa import ByteDFA, compile_regex
from dynamo_tpu.guided.json_schema import schema_to_regex, GENERIC_JSON
from dynamo_tpu.guided.token_mask import GuidedMatcher, TokenLifter
from dynamo_tpu.guided.structural import compile_structural

__all__ = [
    "ByteDFA",
    "compile_regex",
    "schema_to_regex",
    "GENERIC_JSON",
    "GuidedMatcher",
    "TokenLifter",
    "compile_structural",
]
