"""JSON Schema → regex pattern (for the byte-DFA compiler).

The outlines-style reduction: a (non-recursive) JSON schema induces a
regular language once array/object sizes are bounded and generation is
pinned to a canonical surface form (minimal whitespace: one optional
space after ':' and ','). Supported keywords: type (string, integer,
number, boolean, null, object, array), enum, const, properties /
required / additionalProperties:false, items, minItems/maxItems,
minLength/maxLength/pattern for strings, minimum/maximum sign hints,
anyOf/oneOf, $ref into $defs/definitions. Recursive $refs raise (a
pushdown language — not expressible as a DFA; the reference's guided
backends bound or reject these too).

Empty schema / {"type": "object"} without properties compile to a
GENERIC depth-bounded JSON value grammar.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from dynamo_tpu.guided.regex_dfa import escape

WS = "[ ]?"  # canonical optional single space
STRING_CHAR = '([^"\\\\\\x00-\\x1f]|\\\\(["\\\\/bfnrt]|u[0-9a-fA-F]{4}))'
STRING = f'"{STRING_CHAR}*"'
INTEGER = "-?(0|[1-9][0-9]*)"
NUMBER = "-?(0|[1-9][0-9]*)(\\.[0-9]+)?([eE][+-]?[0-9]+)?"
BOOLEAN = "(true|false)"
NULL = "null"

# depth-bounded generic JSON value (response_format: json_object).
# Unbounded member/element counts on purpose: bounded {0,n} repetition
# duplicates the whole item NFA n times PER NESTING LEVEL and explodes
# the DFA; `*` costs one loop block.
_GENERIC_DEPTH = 3


def _generic_value(depth: int) -> str:
    prims = f"({STRING}|{NUMBER}|{BOOLEAN}|{NULL})"
    if depth <= 0:
        return prims
    v = _generic_value(depth - 1)
    obj = f'(\\{{{WS}\\}}|\\{{{WS}{STRING}{WS}:{WS}{v}({WS},{WS}{STRING}{WS}:{WS}{v})*{WS}\\}})'
    arr = f"(\\[{WS}\\]|\\[{WS}{v}({WS},{WS}{v})*{WS}\\])"
    return f"({prims}|{obj}|{arr})"


def _generic_object(depth: int = _GENERIC_DEPTH) -> str:
    v = _generic_value(depth - 1)
    return f'(\\{{{WS}\\}}|\\{{{WS}{STRING}{WS}:{WS}{v}({WS},{WS}{STRING}{WS}:{WS}{v})*{WS}\\}})'


GENERIC_JSON = _generic_object()


class SchemaError(ValueError):
    pass


def schema_to_regex(schema: Any, max_depth: int = 32) -> str:
    """Compile a JSON schema dict (or bool) to a pattern string."""
    return _compile(schema, schema, max_depth)


def _compile(schema: Any, root: Any, depth: int) -> str:
    if depth <= 0:
        raise SchemaError("schema nesting too deep (recursive $ref?)")
    if schema is True or schema == {}:
        return _generic_value(_GENERIC_DEPTH)
    if schema is False:
        raise SchemaError("schema `false` admits nothing")
    if not isinstance(schema, dict):
        raise SchemaError(f"bad schema node {schema!r}")

    if "$ref" in schema:
        return _compile(_resolve_ref(schema["$ref"], root), root, depth - 1)
    if "const" in schema:
        return escape(json.dumps(schema["const"], separators=(",", ":")))
    if "enum" in schema:
        opts = [
            escape(json.dumps(v, separators=(",", ":"))) for v in schema["enum"]
        ]
        if not opts:
            raise SchemaError("empty enum")
        return "(" + "|".join(opts) + ")"
    for key in ("anyOf", "oneOf"):
        if key in schema:
            return (
                "("
                + "|".join(_compile(s, root, depth - 1) for s in schema[key])
                + ")"
            )
    if "allOf" in schema:
        merged: Dict[str, Any] = {}
        for part in schema["allOf"]:
            if "$ref" in part:
                part = _resolve_ref(part["$ref"], root)
            if not isinstance(part, dict):
                raise SchemaError("allOf parts must be objects")
            for k, v in part.items():
                if k == "properties":
                    merged.setdefault("properties", {}).update(v)
                elif k == "required":
                    merged["required"] = list(
                        dict.fromkeys(merged.get("required", []) + v)
                    )
                else:
                    merged[k] = v
        merged.update({k: v for k, v in schema.items() if k != "allOf"})
        return _compile(merged, root, depth - 1)

    t = schema.get("type")
    if isinstance(t, list):
        return "(" + "|".join(
            _compile({**schema, "type": one}, root, depth - 1) for one in t
        ) + ")"
    if t == "string":
        return _string(schema)
    if t == "integer":
        return INTEGER
    if t == "number":
        return NUMBER
    if t == "boolean":
        return BOOLEAN
    if t == "null":
        return NULL
    if t == "array":
        return _array(schema, root, depth)
    if t == "object" or "properties" in schema:
        return _object(schema, root, depth)
    if t is None:
        return _generic_value(_GENERIC_DEPTH)
    raise SchemaError(f"unsupported type {t!r}")


def _resolve_ref(ref: str, root: Any):
    if not ref.startswith("#/"):
        raise SchemaError(f"only local $refs supported, got {ref!r}")
    node = root
    for part in ref[2:].split("/"):
        part = part.replace("~1", "/").replace("~0", "~")
        if not isinstance(node, dict) or part not in node:
            raise SchemaError(f"unresolvable $ref {ref!r}")
        node = node[part]
    return node


def _string(schema: Dict[str, Any]) -> str:
    if "pattern" in schema:
        pat = schema["pattern"]
        if pat.startswith("^"):
            pat = pat[1:]
        if pat.endswith("$") and not pat.endswith("\\$"):
            pat = pat[:-1]
        _check_string_pattern(pat)
        return f'"({pat})"'
    lo = schema.get("minLength")
    hi = schema.get("maxLength")
    if lo is None and hi is None:
        return STRING
    lo = int(lo or 0)
    rep = f"{{{lo},{int(hi)}}}" if hi is not None else f"{{{lo},}}"
    return f'"{STRING_CHAR}{rep}"'


def _check_string_pattern(pat: str) -> None:
    """The user pattern is embedded verbatim inside '"(pat)"' at the JSON
    TEXT level, with no escaping translation — so a pattern able to emit
    a raw '"' would let generation escape the string context entirely,
    and a bare '\\' or control byte would force output that is not valid
    JSON. Enforce the restriction exactly: compile the pattern to its
    byte DFA and reject if any transition accepts an offending byte.
    (Schema `pattern` semantics apply to the DECODED value; supporting
    those bytes would need a JSON-escape-transducing compile.)"""
    from dynamo_tpu.guided.regex_dfa import RegexError, compile_regex

    try:
        dfa = compile_regex(pat)
    except RegexError as e:
        raise SchemaError(f"unsupported string pattern {pat!r}: {e}") from e
    bad = [0x22, 0x5C] + list(range(0x20))
    if (dfa.trans[:, bad] >= 0).any():
        raise SchemaError(
            f"string pattern {pat!r} can match '\"', '\\' or a control "
            "character, which cannot be embedded in a JSON string "
            "constraint without escape translation"
        )


def _array(schema: Dict[str, Any], root: Any, depth: int) -> str:
    item = _compile(schema.get("items", True), root, depth - 1)
    lo = int(schema.get("minItems", 0))
    hi = schema.get("maxItems")
    if hi is not None and int(hi) == 0:
        return f"\\[{WS}\\]"
    # n items = first item + (n-1) comma-items
    tail_lo = max(0, lo - 1)
    tail = f"({WS},{WS}{item})"
    tail_rep = (
        f"{tail}{{{tail_lo},{int(hi) - 1}}}" if hi is not None
        else (f"{tail}{{{tail_lo},}}" if tail_lo else f"{tail}*")
    )
    body = f"{item}{tail_rep}"
    if lo == 0:
        return f"(\\[{WS}\\]|\\[{WS}{body}{WS}\\])"
    return f"\\[{WS}{body}{WS}\\]"


_MAX_OPTIONAL = 8


def _object(schema: Dict[str, Any], root: Any, depth: int) -> str:
    props: Dict[str, Any] = schema.get("properties") or {}
    if not props:
        if schema.get("additionalProperties") is False:
            return f"\\{{{WS}\\}}"
        return _generic_object()
    required = set(schema.get("required") or [])
    items: List[tuple] = []  # (pattern, required)
    n_opt = 0
    for key, sub in props.items():
        pat = f'"{escape(key)}"{WS}:{WS}{_compile(sub, root, depth - 1)}'
        req = key in required
        if not req:
            n_opt += 1
        items.append((pat, req))
    if n_opt > _MAX_OPTIONAL:
        raise SchemaError(
            f"{n_opt} optional properties — the ordered-optional encoding "
            f"blows up past {_MAX_OPTIONAL}; mark more properties required"
        )

    # rest(i, first): properties i.. with `first` = nothing emitted yet.
    def rest(i: int, first: bool) -> str:
        if i == len(items):
            return ""
        pat, req = items[i]
        lead = "" if first else f"{WS},{WS}"
        with_it = f"{lead}{pat}{rest(i + 1, False)}"
        if req:
            return with_it
        without = rest(i + 1, first)
        return f"(({with_it})|({without}))" if without else f"({with_it})?"

    body = rest(0, True)
    if not required:
        return f"(\\{{{WS}\\}}|\\{{{WS}{body}{WS}\\}})"
    return f"\\{{{WS}{body}{WS}\\}}"


def tool_call_regex(tools: List[Dict[str, Any]],
                    name: Optional[str] = None) -> str:
    """Hermes-format tool-call pattern for `tool_choice` enforcement:
    <tool_call>{"name": ..., "arguments": {...}}</tool_call>, one or more
    calls, each constrained to a declared tool's parameter schema (or to
    the single named tool). Matches what the default chat template
    instructs and what frontend/tool_calls.py parses."""
    alts = []
    for t in tools:
        fn = t.get("function", t)
        if name is not None and fn.get("name") != name:
            continue
        call_schema = {
            "type": "object",
            "properties": {
                "name": {"const": fn.get("name", "")},
                "arguments": fn.get("parameters") or {"type": "object"},
            },
            "required": ["name", "arguments"],
            "additionalProperties": False,
        }
        alts.append(schema_to_regex(call_schema))
    if not alts:
        raise SchemaError(
            f"tool_choice names unknown function {name!r}"
            if name else "tool_choice requires non-empty tools"
        )
    one = "(" + "|".join(alts) + ")"
    call = f"<tool_call>{WS}{one}{WS}</tool_call>"
    return f"{call}({WS}{call})*"
