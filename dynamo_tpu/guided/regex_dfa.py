"""Byte-level regex → DFA compiler for constrained generation.

Supports the subset JSON-schema compilation emits (and typical
user-supplied guided_regex patterns): literals, `.`, character classes
(ranges, negation, class escapes), groups, alternation, `* + ?` and
bounded `{m}`/`{m,n}`/`{m,}` repetition. Operates on BYTES: non-ASCII
literal characters compile to their UTF-8 byte sequence, and negated
classes / `.` admit all bytes (so arbitrary UTF-8 content streams
through byte-by-byte — the right semantics for generation masks).

Pipeline: parse → Thompson NFA → subset construction over byte
equivalence classes → dense DFA table [S, 256] int32 (-1 = reject),
pruned so every surviving state can still reach an acceptor (no dead
ends: a sampled prefix can always be completed).
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

_ALL = frozenset(range(256))
_DIGIT = frozenset(range(0x30, 0x3A))
_WORD = _DIGIT | frozenset(range(0x41, 0x5B)) | frozenset(range(0x61, 0x7B)) | {0x5F}
_SPACE = frozenset(b" \t\n\r\f\v")
_CLASS_ESC = {
    "d": _DIGIT, "D": _ALL - _DIGIT,
    "w": _WORD, "W": _ALL - _WORD,
    "s": _SPACE, "S": _ALL - _SPACE,
}
_CHAR_ESC = {"n": 0x0A, "r": 0x0D, "t": 0x09, "f": 0x0C, "v": 0x0B, "0": 0x00}


class RegexError(ValueError):
    pass


# -- AST ----------------------------------------------------------------------
# ("lit", frozenset[int])  one byte from the set
# ("seq", [nodes])
# ("alt", [nodes])
# ("rep", node, m, n|None)  m..n repetitions (None = unbounded)


class _Parser:
    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        # per-instance (NOT class-level): a shared set mutated in place
        # would leak character-class escapes across concurrently compiled
        # patterns, silently corrupting their DFAs
        self._cls_extra: set = set()

    def peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def next(self) -> str:
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise RegexError(f"unexpected {self.p[self.i]!r} at {self.i}")
        return node

    def _alt(self):
        branches = [self._seq()]
        while self.peek() == "|":
            self.next()
            branches.append(self._seq())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _seq(self):
        items = []
        while (c := self.peek()) is not None and c not in "|)":
            items.append(self._quantified())
        if len(items) == 1:
            return items[0]
        return ("seq", items)

    def _quantified(self):
        atom = self._atom()
        c = self.peek()
        if c == "*":
            self.next()
            return ("rep", atom, 0, None)
        if c == "+":
            self.next()
            return ("rep", atom, 1, None)
        if c == "?":
            self.next()
            return ("rep", atom, 0, 1)
        if c == "{":
            save = self.i
            self.next()
            spec = ""
            while (c := self.peek()) is not None and c != "}":
                spec += self.next()
            if self.peek() != "}" or not _valid_repeat(spec):
                # not a quantifier — treat '{' as a literal (JSON braces)
                self.i = save
                return atom
            self.next()
            if "," in spec:
                lo, hi = spec.split(",", 1)
                m = int(lo)
                n = None if hi == "" else int(hi)
            else:
                m = n = int(spec)
            if n is not None and n < m:
                raise RegexError(f"bad repeat {{{spec}}}")
            return ("rep", atom, m, n)
        return atom

    def _atom(self):
        c = self.next()
        if c in "^$":
            # anchors are zero-width no-ops: the DFA always fullmatches
            # (vLLM/outlines-style guided_regex patterns routinely anchor)
            return ("seq", [])
        if c == "(":
            if self.p[self.i : self.i + 2] == "?:":
                self.i += 2
            node = self._alt()
            if self.peek() != ")":
                raise RegexError("unbalanced (")
            self.next()
            return node
        if c == "[":
            return ("lit", self._char_class())
        if c == ".":
            return ("lit", _ALL - {0x0A})
        if c == "\\":
            return self._escape()
        if c in "*+?":
            raise RegexError(f"dangling quantifier {c!r}")
        return _char_lit(c)

    def _escape(self):
        c = self.next()
        if c in _CLASS_ESC:
            return ("lit", _CLASS_ESC[c])
        if c in _CHAR_ESC:
            return ("lit", frozenset({_CHAR_ESC[c]}))
        if c == "x":
            h = self.next() + self.next()
            return ("lit", frozenset({int(h, 16)}))
        return _char_lit(c)  # escaped punctuation: \. \[ \{ \\ ...

    def _char_class(self):
        neg = False
        if self.peek() == "^":
            self.next()
            neg = True
        out: set = set()
        first = True
        while True:
            c = self.peek()
            if c is None:
                raise RegexError("unterminated [")
            if c == "]" and not first:
                self.next()
                break
            first = False
            lo = self._class_char()
            if lo is None:  # class escape like \d inside [...]
                continue
            if self.peek() == "-" and self.p[self.i + 1 : self.i + 2] not in ("]", ""):
                self.next()
                hi = self._class_char()
                if hi is None or hi < lo:
                    raise RegexError("bad range in class")
                out.update(range(lo, hi + 1))
            else:
                out.add(lo)
        # class escapes accumulate in self._cls_extra
        if self._cls_extra:
            out.update(self._cls_extra)
            self._cls_extra = set()
        s = frozenset(out)
        return _ALL - s if neg else s

    def _class_char(self) -> Optional[int]:
        c = self.next()
        if c == "\\":
            e = self.next()
            if e in _CLASS_ESC:
                self._cls_extra = set(self._cls_extra) | set(_CLASS_ESC[e])
                return None
            if e in _CHAR_ESC:
                return _CHAR_ESC[e]
            if e == "x":
                return int(self.next() + self.next(), 16)
            b = e.encode("utf-8")
            if len(b) != 1:
                raise RegexError("non-ASCII char in class")
            return b[0]
        b = c.encode("utf-8")
        if len(b) != 1:
            raise RegexError("non-ASCII char in class (use literals outside classes)")
        return b[0]


def _valid_repeat(spec: str) -> bool:
    if "," in spec:
        lo, hi = spec.split(",", 1)
        return lo.isdigit() and (hi == "" or hi.isdigit())
    return spec.isdigit()


def _char_lit(c: str):
    b = c.encode("utf-8")
    if len(b) == 1:
        return ("lit", frozenset({b[0]}))
    return ("seq", [("lit", frozenset({x})) for x in b])


def escape(text: str) -> str:
    """Escape a literal string for embedding in a pattern."""
    return "".join(
        "\\" + c if c in ".\\()[]{}|*+?^$" else c for c in text
    )


# -- NFA ----------------------------------------------------------------------


class _NFA:
    def __init__(self):
        self.n = 0
        self.eps: List[List[int]] = []
        self.edges: List[List[Tuple[FrozenSet[int], int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        self.n += 1
        return self.n - 1

    def add(self, a: int, byteset: FrozenSet[int], b: int) -> None:
        self.edges[a].append((byteset, b))

    def add_eps(self, a: int, b: int) -> None:
        self.eps[a].append(b)


def _build(nfa: _NFA, node) -> Tuple[int, int]:
    kind = node[0]
    if kind == "lit":
        a, b = nfa.state(), nfa.state()
        if not node[1]:
            raise RegexError("empty character class")
        nfa.add(a, node[1], b)
        return a, b
    if kind == "seq":
        if not node[1]:
            a = nfa.state()
            return a, a
        a, b = _build(nfa, node[1][0])
        for item in node[1][1:]:
            c, d = _build(nfa, item)
            nfa.add_eps(b, c)
            b = d
        return a, b
    if kind == "alt":
        a, b = nfa.state(), nfa.state()
        for br in node[1]:
            c, d = _build(nfa, br)
            nfa.add_eps(a, c)
            nfa.add_eps(d, b)
        return a, b
    if kind == "rep":
        _, inner, m, n = node
        a = nfa.state()
        cur = a
        for _ in range(m):
            c, d = _build(nfa, inner)
            nfa.add_eps(cur, c)
            cur = d
        if n is None:  # unbounded tail: one loop block
            c, d = _build(nfa, inner)
            nfa.add_eps(cur, c)
            nfa.add_eps(d, c)
            end = nfa.state()
            nfa.add_eps(cur, end)
            nfa.add_eps(d, end)
            return a, end
        end = nfa.state()
        nfa.add_eps(cur, end)
        for _ in range(n - m):
            c, d = _build(nfa, inner)
            nfa.add_eps(cur, c)
            cur = d
            nfa.add_eps(cur, end)
        return a, end
    raise RegexError(f"bad node {kind}")


# -- DFA ----------------------------------------------------------------------


class ByteDFA:
    """Dense byte-transition table. `trans[s, b]` = next state or -1;
    `accept[s]` marks states where the match may end (EOS is legal)."""

    def __init__(self, trans: np.ndarray, accept: np.ndarray, start: int = 0):
        self.trans = trans  # [S, 256] int32
        self.accept = accept  # [S] bool
        self.start = start

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]

    def matches(self, data: bytes) -> bool:
        s = self.start
        for b in data:
            s = int(self.trans[s, b])
            if s < 0:
                return False
        return bool(self.accept[s])

    def to_wire(self) -> Dict[str, object]:
        return {
            "trans": self.trans.astype(np.int32).tobytes(),
            "n_states": int(self.n_states),
            "accept": np.packbits(self.accept).tobytes(),
            "start": int(self.start),
        }

    @classmethod
    def from_wire(cls, d: Dict[str, object]) -> "ByteDFA":
        S = int(d["n_states"])
        trans = np.frombuffer(d["trans"], np.int32).reshape(S, 256).copy()
        accept = np.unpackbits(
            np.frombuffer(d["accept"], np.uint8), count=S
        ).astype(bool)
        return cls(trans, accept, int(d["start"]))


def _eps_closure(nfa: _NFA, states: FrozenSet[int]) -> FrozenSet[int]:
    stack = list(states)
    seen = set(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


def _byte_classes(nfa: _NFA) -> Tuple[np.ndarray, int]:
    """Partition 0..255 into equivalence classes: bytes that fall in
    exactly the same edge bytesets transition identically, so subset
    construction runs over ~10-40 columns instead of 256."""
    uniq = {byteset for edges in nfa.edges for (byteset, _) in edges}
    if not uniq:
        return np.zeros(256, np.int32), 1
    M = np.zeros((len(uniq), 256), bool)
    for i, byteset in enumerate(uniq):
        M[i, list(byteset)] = True
    _, cls = np.unique(M, axis=1, return_inverse=True)
    return cls.astype(np.int32), int(cls.max()) + 1


def compile_regex(pattern: str, max_states: int = 20000) -> ByteDFA:
    """pattern → pruned byte DFA. Raises RegexError on unsupported syntax
    or state blow-up (protects the worker from pathological schemas)."""
    nfa = _NFA()
    start, end = _build(nfa, _Parser(pattern).parse())
    accept_nfa = end

    cls_of, n_cls = _byte_classes(nfa)
    # representative byte per class
    rep = np.zeros(n_cls, np.int32)
    for c in range(n_cls):
        rep[c] = int(np.argmax(cls_of == c))

    init = _eps_closure(nfa, frozenset({start}))
    index: Dict[FrozenSet[int], int] = {init: 0}
    order = [init]
    rows: List[List[int]] = []
    i = 0
    while i < len(order):
        cur = order[i]
        i += 1
        row = [-1] * n_cls
        for c in range(n_cls):
            b = int(rep[c])
            nxt = set()
            for s in cur:
                for byteset, t in nfa.edges[s]:
                    if b in byteset:
                        nxt.add(t)
            if nxt:
                closed = _eps_closure(nfa, frozenset(nxt))
                j = index.get(closed)
                if j is None:
                    j = len(order)
                    if j >= max_states:
                        raise RegexError(
                            f"DFA exceeds {max_states} states — simplify the "
                            "pattern/schema"
                        )
                    index[closed] = j
                    order.append(closed)
                row[c] = j
        rows.append(row)

    S = len(order)
    trans_c = np.asarray(rows, np.int32)  # [S, n_cls]
    accept = np.asarray([accept_nfa in st for st in order], bool)

    # prune states that cannot reach an acceptor (reverse BFS)
    co = accept.copy()
    changed = True
    while changed:
        changed = False
        reach = co[np.where(trans_c >= 0, trans_c, 0)] & (trans_c >= 0)
        new = co | reach.any(axis=1)
        if (new != co).any():
            co = new
            changed = True
    if not co[0]:
        raise RegexError("pattern matches nothing")
    remap = -np.ones(S, np.int32)
    remap[co] = np.arange(int(co.sum()), dtype=np.int32)
    trans_c = np.where(trans_c >= 0, remap[np.where(trans_c >= 0, trans_c, 0)], -1)
    trans_c = trans_c[co]
    accept = accept[co]

    trans = trans_c[:, cls_of]  # expand classes → full 256 columns
    return ByteDFA(np.ascontiguousarray(trans), accept, int(remap[0]))
