"""Lift a byte-level DFA to per-state token masks for a tokenizer.

The worker-side half of guided decoding: the frontend ships a compact
byte DFA; this module walks every token's byte string through it to
answer "from DFA state s, which TOKENS may be sampled next, and where
does each land?". Rows are computed lazily per visited state (a
generation visits tens of states; a dense [S, V] table for a 128k vocab
would be hundreds of MB) and vectorized over the vocab (one numpy
advance per byte position, ~Lmax*V ops per row).

EOS is never part of the DFA alphabet: it is legal exactly in accepting
states (the constraint is complete), and a state whose row allows
nothing else force-stops generation there.
"""

from __future__ import annotations

import threading
from functools import lru_cache
from typing import Dict, List, Optional

import numpy as np

from dynamo_tpu.guided.regex_dfa import ByteDFA


@lru_cache(maxsize=1)
def _gpt2_byte_decoder() -> Dict[str, int]:
    """Inverse of the GPT-2 byte→unicode surface mapping used by
    byte-level BPE vocabs (printable ASCII stays itself; other bytes map
    to U+0100.. so every token string round-trips losslessly)."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(0xA1, 0xAD))
        + list(range(0xAE, 0x100))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return {chr(c): b for b, c in zip(bs, cs)}


class TokenLifter:
    """Per-tokenizer byte table, shared across all matchers.

    `token_bytes[i]` is token i's byte string (None/empty → the token can
    never be sampled under a constraint — special tokens, padding ids).
    """

    def __init__(self, token_bytes: List[Optional[bytes]], eos_id: int,
                 vocab_size: int):
        self.vocab_size = vocab_size
        self.eos_id = eos_id
        V = vocab_size
        lens = np.zeros(V, np.int32)
        maxlen = 1
        for i, b in enumerate(token_bytes[:V]):
            if b:
                lens[i] = len(b)
                maxlen = max(maxlen, len(b))
        mat = np.zeros((V, maxlen), np.uint8)
        for i, b in enumerate(token_bytes[:V]):
            if b:
                mat[i, : len(b)] = np.frombuffer(b, np.uint8)
        self.tok_mat = mat
        self.tok_len = lens

    @classmethod
    def for_tokenizer(cls, tokenizer, vocab_size: int) -> "TokenLifter":
        """Build from a dynamo_tpu Tokenizer (byte or HF).

        HF token strings are mapped to their REAL byte content from the
        vocab itself (per-id decode() mangles byte-fallback pieces into
        U+FFFD): byte-level-BPE vocabs (Ġ/Ċ surface forms) invert the
        GPT-2 byte↔unicode table; sentencepiece-style vocabs map ▁→space
        and <0xNN> byte-fallback tokens to their byte. Special/added
        tokens and anything unmappable are banned (None)."""
        hf = getattr(tokenizer, "_tok", None)
        if hf is None:
            tb: List[Optional[bytes]] = [
                bytes([i]) if i < 256 and i < tokenizer.vocab_size else None
                for i in range(vocab_size)
            ]
            return cls(
                tb, tokenizer.eos_id if tokenizer.eos_id is not None else -1,
                vocab_size,
            )
        special = set()
        try:
            for tid, tok in hf.get_added_tokens_decoder().items():
                if getattr(tok, "special", True):
                    special.add(int(tid))
        except AttributeError:
            pass
        byte_dec = _gpt2_byte_decoder()
        # decide the surface encoding once per vocab: byte-level BPE marks
        # spaces/newlines as Ġ/Ċ
        probe = [hf.id_to_token(i) for i in range(min(tokenizer.vocab_size, 512))]
        byte_level = any(s and ("Ġ" in s or "Ċ" in s) for s in probe)
        tb = []
        for i in range(vocab_size):
            s = hf.id_to_token(i) if i < tokenizer.vocab_size else None
            if s is None or i in special:
                tb.append(None)
                continue
            if len(s) == 6 and s.startswith("<0x") and s.endswith(">"):
                try:
                    tb.append(bytes([int(s[3:5], 16)]))
                    continue
                except ValueError:
                    pass
            if byte_level:
                try:
                    tb.append(bytes(byte_dec[c] for c in s))
                except KeyError:
                    tb.append(None)  # added token with non-surface chars
            else:
                s = s.replace("▁", " ")  # sentencepiece space marker
                tb.append(None if "�" in s else s.encode("utf-8"))
        return cls(tb, tokenizer.eos_id if tokenizer.eos_id is not None else -1,
                   vocab_size)

    def lift(self, dfa: ByteDFA) -> "GuidedMatcher":
        return GuidedMatcher(self, dfa)


# Bound on cached per-state rows ([V] int32 each — ~0.5MB at 128k vocab).
# Literal-heavy constraints advance through a fresh state per byte, so an
# unbounded cache grows with generation length; recomputing an evicted row
# costs ~Lmax vectorized vocab passes (sub-ms), so a small cap is cheap.
_ROW_CACHE_MAX = 128


class GuidedMatcher:
    """One compiled constraint against one tokenizer. Thread-safe row
    cache (the engine step thread and admission path may both touch it)."""

    def __init__(self, lifter: TokenLifter, dfa: ByteDFA):
        self.lifter = lifter
        self.dfa = dfa
        self.start = dfa.start
        self._rows: Dict[int, np.ndarray] = {}  # insertion-ordered (FIFO)
        self._lock = threading.Lock()

    def _row(self, state: int) -> np.ndarray:
        """[V] int32: token id → DFA state after consuming the token's
        bytes from `state` (-1 = token not allowed)."""
        row = self._rows.get(state)
        if row is not None:
            return row
        lf = self.lifter
        V = lf.vocab_size
        states = np.full(V, state, np.int32)
        for pos in range(lf.tok_mat.shape[1]):
            live = (lf.tok_len > pos) & (states >= 0)
            if not live.any():
                break
            states[live] = self.dfa.trans[states[live], lf.tok_mat[live, pos]]
        states[lf.tok_len == 0] = -1  # empty tokens would loop forever
        with self._lock:
            while len(self._rows) >= _ROW_CACHE_MAX:
                self._rows.pop(next(iter(self._rows)))
            self._rows[state] = states
        return states

    def allowed(self, state: int) -> np.ndarray:
        """[V] bool sampling mask for a sequence in `state`."""
        mask = self._row(state) >= 0
        if self.dfa.accept[state] and 0 <= self.lifter.eos_id < len(mask):
            mask = mask.copy()
            mask[self.lifter.eos_id] = True
        return mask

    def advance(self, state: int, token: int) -> int:
        """State after sampling `token`. EOS (legal only in accepting
        states) is terminal: returns the state unchanged."""
        if token == self.lifter.eos_id:
            return state
        nxt = int(self._row(state)[token])
        if nxt < 0:
            raise ValueError(
                f"token {token} is not allowed in constraint state {state} "
                "(mask desync)"
            )
        return nxt

    def is_accepting(self, state: int) -> bool:
        return bool(self.dfa.accept[state])
