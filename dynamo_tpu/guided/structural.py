"""Structural tags: free text with trigger-activated constrained regions.

Reference semantics (lib/llm/src/preprocessor/tools/ structural-tag
support): generation is unconstrained until the model emits a *trigger*
string (e.g. "<tool_call>"); from that point the output must complete
one of the trigger's *structures* — begin tag + constrained content +
end tag — after which generation is free again (and further structures
may fire). EOS is legal only outside a structure.

The whole thing is one regular language, compiled here into a single
byte DFA:

- free states = an Aho-Corasick automaton over the trigger set (PMA
  with failure links, completed into a dense goto table) — every byte
  is allowed, the state just tracks trigger progress; all free states
  accept;
- when a goto lands on a trigger match, the edge is REDIRECTED into
  that trigger's structure DFA (compiled from
  "(begin_tail content end | ...)" with begin_tail = begin minus the
  trigger prefix);
- structure accept states get the free-root's transitions grafted on
  (back to free text) and become accepting.

Triggers must be prefixes of their structures' begin tags (validated).
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from dynamo_tpu.guided.regex_dfa import ByteDFA, RegexError, compile_regex, escape
from dynamo_tpu.guided.json_schema import WS, schema_to_regex


def _aho_corasick(patterns: List[bytes]):
    """Dense goto table for the pattern set: (goto [S,256] int32,
    match [S] int32 = index of the longest pattern ending here or -1)."""
    # trie
    children: List[Dict[int, int]] = [{}]
    match: List[int] = [-1]
    for pi, pat in enumerate(patterns):
        s = 0
        for b in pat:
            nxt = children[s].get(b)
            if nxt is None:
                nxt = len(children)
                children.append({})
                match.append(-1)
                children[s][b] = nxt
            s = nxt
        match[s] = pi
    # BFS failure links → dense goto
    S = len(children)
    goto = np.zeros((S, 256), np.int32)
    fail = [0] * S
    from collections import deque

    q = deque()
    for b in range(256):
        nxt = children[0].get(b)
        if nxt is None:
            goto[0, b] = 0
        else:
            goto[0, b] = nxt
            fail[nxt] = 0
            q.append(nxt)
    while q:
        s = q.popleft()
        if match[fail[s]] >= 0 and match[s] < 0:
            match[s] = match[fail[s]]  # suffix completes a pattern
        for b in range(256):
            nxt = children[s].get(b)
            if nxt is None:
                goto[s, b] = goto[fail[s], b]
            else:
                goto[s, b] = nxt
                fail[nxt] = int(goto[fail[s], b])
                q.append(nxt)
    return goto, np.asarray(match, np.int32)


def structure_pattern(struct: Dict[str, Any]) -> str:
    """One structure's content pattern: schema → regex (or a raw
    pattern passthrough)."""
    if "pattern" in struct:
        return struct["pattern"]
    schema = struct.get("schema", {"type": "object"})
    return schema_to_regex(schema)


def compile_structural(spec: Dict[str, Any]) -> ByteDFA:
    """spec: {"triggers": [str, ...],
              "structures": [{"begin": str, "schema"|"pattern": ...,
                              "end": str}, ...]}
    → composite byte DFA (see module docstring)."""
    triggers: List[str] = list(spec.get("triggers") or [])
    structures: List[Dict[str, Any]] = list(spec.get("structures") or [])
    if not triggers or not structures:
        raise RegexError("structural spec needs triggers and structures")

    trig_bytes = [t.encode("utf-8") for t in triggers]
    # per trigger: alternation over its structures' begin_tail+content+end
    per_trigger: List[str] = []
    for ti, trig in enumerate(triggers):
        alts = []
        for st in structures:
            begin = st.get("begin", "")
            if not begin.startswith(trig):
                continue
            tail = begin[len(trig):]
            alts.append(
                escape(tail) + WS + "(" + structure_pattern(st) + ")" + WS
                + escape(st.get("end", ""))
            )
        if not alts:
            raise RegexError(
                f"trigger {trig!r} matches no structure begin tag"
            )
        per_trigger.append("(" + "|".join(alts) + ")")

    goto, match = _aho_corasick(trig_bytes)
    n_free = goto.shape[0]

    sub: List[ByteDFA] = [compile_regex(p) for p in per_trigger]
    offs: List[int] = []
    total = n_free
    for d in sub:
        offs.append(total)
        total += d.n_states

    trans = np.full((total, 256), -1, np.int32)
    accept = np.zeros(total, bool)
    # free block: goto edges; redirect trigger-completing edges into subs
    trans[:n_free] = goto
    accept[:n_free] = True
    for s in range(n_free):
        for b in range(256):
            m = int(match[int(goto[s, b])])
            if m >= 0:
                trans[s, b] = offs[m] + sub[m].start
    # structure blocks
    for m, d in enumerate(sub):
        o = offs[m]
        blk = np.where(d.trans >= 0, d.trans + o, -1)
        trans[o : o + d.n_states] = blk
        for st in np.where(d.accept)[0]:
            row = trans[o + st]
            free_row = trans[0]  # free root (trigger tracking restarts)
            # graft: bytes the structure doesn't consume continue as free
            take = row < 0
            row[take] = free_row[take]
            accept[o + st] = True
    return ByteDFA(trans, accept, start=0)
