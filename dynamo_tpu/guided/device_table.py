"""Device-resident guided DFA: dense token-level transition + mask tables.

`token_mask.GuidedMatcher` answers "which tokens may follow state s" one
row at a time, host-side — which is exactly right for admission-time
validation, but inside the fused decode loop it forces an ordered
`io_callback` per step (the DFA must advance between steps the host
never sees). This module compiles the WHOLE matcher down to two dense
arrays so the advance and the mask gather happen in-XLA:

- ``trans`` int32 ``[S+1, V]`` — token-level transition table. Row ``s``
  column ``t`` is the state after sampling token ``t`` in state ``s``;
  ``DEAD`` (== S, the last row) encodes every way a row leaves the
  constraint: the token was banned (desync), the token was EOS
  (terminal), or the row was never guided at all. ``DEAD`` self-loops
  and its mask row is all-True, mirroring ``GuidedMaskContext``'s
  ``alive=False`` rows.
- ``mask`` bool ``[S+1, V]`` — the sampling mask per state, with the
  same degrade rule as the host path: EOS is legal exactly in accepting
  states, and a state that allows nothing at all force-allows EOS so the
  row stops instead of sampling garbage.

Both tables are a function of (matcher, vocab) only, so they are built
once per compiled constraint and stay device-resident across every
dispatch that uses the schema — the per-step host round trip is gone.

The build is refused (``None``) past ``max_elems`` total table cells:
an unbounded-state schema (pathological regex, enormous byte DFA) would
cost S*V*5 bytes of HBM; the caller keeps the host `io_callback`
fallback for those, with a warn-once. Bounded real-world schemas (JSON
grammars, enums, tool-call shapes) compile to a few hundred states.

numpy-only on purpose: the mocker imports guided modules jax-free; the
device staging of these arrays lives in the runner.
"""

from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from dynamo_tpu.guided.token_mask import GuidedMatcher

# Cell budget for one schema's [S, V] tables (~5 bytes/cell: int32 trans
# + bool mask). The default admits S*V <= 8M — at a 128k vocab that is
# 64 DFA states (tool-call/enum/JSON-shape schemas), at test vocabs it
# is effectively unbounded. Env-tunable for bigger HBM budgets.
DEVICE_TABLE_MAX_ELEMS = int(
    os.environ.get("DYN_GUIDED_DEVICE_MAX_ELEMS", str(8 << 20))
)

_uid_lock = threading.Lock()
_uid_next = [1]


class DeviceGuidedTable:
    """One schema's dense token-level DFA, host-built, ready to stage.

    ``trans``/``mask`` are ``[S+1, V]`` with the DEAD row last (see
    module docstring). ``uid`` keys the runner's staged-combination
    cache (object identity is unstable across rebuilds; uids are not).
    """

    def __init__(self, trans: np.ndarray, mask: np.ndarray, start: int,
                 eos_id: int):
        assert trans.shape == mask.shape and trans.ndim == 2
        self.trans = trans  # int32 [S+1, V], DEAD row last
        self.mask = mask  # bool [S+1, V]
        self.start = int(start)
        self.eos_id = int(eos_id)
        self.n_states = int(trans.shape[0]) - 1  # excluding DEAD
        self.vocab_size = int(trans.shape[1])
        with _uid_lock:
            self.uid = _uid_next[0]
            _uid_next[0] += 1

    @property
    def dead(self) -> int:
        return self.n_states

    def nbytes(self) -> int:
        return int(self.trans.nbytes + self.mask.nbytes)


def build_device_table(
    matcher: GuidedMatcher, max_elems: Optional[int] = None
) -> Optional[DeviceGuidedTable]:
    """Compile a GuidedMatcher to a DeviceGuidedTable, or None when the
    schema's S*V cell count exceeds the budget (the caller falls back to
    the host `io_callback` path).

    Vectorized over (S, V) jointly: the same byte-position walk
    `GuidedMatcher._row` does for one state, run for every state at
    once. Byte-identical to the host path by construction — the mask
    table IS `matcher.allowed(s)` (plus the force-EOS degrade of
    `GuidedMaskContext._row_mask`) for every live state, and the
    transition table agrees with `matcher.advance` wherever the host
    path would not raise/deactivate."""
    dfa = matcher.dfa
    lf = matcher.lifter
    S = int(dfa.trans.shape[0])
    V = int(lf.vocab_size)
    budget = DEVICE_TABLE_MAX_ELEMS if max_elems is None else int(max_elems)
    if S * V > budget:
        return None

    # token-level transition for ALL states at once: walk every (state,
    # token) pair through the token's bytes
    states = np.repeat(np.arange(S, dtype=np.int32)[:, None], V, axis=1)
    tok_len = lf.tok_len[None, :]  # [1, V]
    for pos in range(lf.tok_mat.shape[1]):
        live = (tok_len > pos) & (states >= 0)
        if not live.any():
            break
        byte_col = np.repeat(lf.tok_mat[None, :, pos], S, axis=0)
        states[live] = dfa.trans[states[live], byte_col[live]]
    states[:, lf.tok_len == 0] = -1  # empty tokens would loop forever

    mask = states >= 0
    eos = lf.eos_id
    if 0 <= eos < V:
        mask[dfa.accept.astype(bool), eos] = True
        # degrade rule: a state allowing nothing force-allows EOS
        # (matches Engine._guided_mask / GuidedMaskContext._row_mask)
        dead_end = ~mask.any(axis=1)
        mask[dead_end, eos] = True
        # EOS is terminal: the row goes all-True afterwards (host sets
        # alive=False) — encode as a transition to DEAD
        states[:, eos] = -1

    dead = S
    trans_full = np.where(states >= 0, states, dead).astype(np.int32)
    trans_full = np.concatenate(
        [trans_full, np.full((1, V), dead, np.int32)], axis=0
    )
    mask_full = np.concatenate([mask, np.ones((1, V), bool)], axis=0)
    return DeviceGuidedTable(trans_full, mask_full, dfa.start, eos)


def combine_tables(
    tables: Sequence[DeviceGuidedTable],
) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """Stack several schemas' tables into ONE pair of device operands so
    a mixed batch (rows under different constraints) still gathers from
    a single ``[G, V]`` table — per-row states become global indices
    ``offset[i] + local_state``. Returns (trans [G, V], mask [G, V],
    offsets) with one shared DEAD row last; local DEAD entries are
    remapped to it. The common one-schema batch passes through with a
    trivial offset."""
    assert tables, "combine_tables needs at least one table"
    V = tables[0].vocab_size
    total = sum(t.n_states for t in tables)
    dead = total  # one shared DEAD row
    trans = np.empty((total + 1, V), np.int32)
    mask = np.empty((total + 1, V), bool)
    offsets: List[int] = []
    o = 0
    for t in tables:
        assert t.vocab_size == V, "mixed vocab sizes in one guided batch"
        s = t.n_states
        local = t.trans[:s]  # drop the per-table DEAD row
        trans[o : o + s] = np.where(local >= t.dead, dead, local + o)
        mask[o : o + s] = t.mask[:s]
        offsets.append(o)
        o += s
    trans[dead] = dead
    mask[dead] = True
    return trans, mask, offsets
