"""dynamo_tpu — TPU-native distributed LLM inference framework.

A ground-up, TPU-first re-design of the capabilities of NVIDIA Dynamo
(reference surveyed in SURVEY.md): an OpenAI-compatible frontend, a
KV-cache-aware smart router, disaggregated prefill/decode serving, a
multi-tier KV block manager, request migration / fault tolerance, an
SLA-driven planner, and — unlike the reference, which wraps external CUDA
engines — a native JAX/XLA/Pallas serving engine with paged attention,
continuous batching, and pjit mesh sharding (DP/TP/EP/SP) over ICI.

Layer map (mirrors reference layers L0–L8, SURVEY.md §1):
  runtime/   — distributed runtime: component model, discovery, request
               plane (TCP/msgpack), event plane (ZMQ), metrics
               (analog of lib/runtime, Rust, in the reference)
  tokens/    — token-block hashing contract (analog of lib/tokens +
               lib/kv-hashing)
  router/    — KV-aware routing: radix indexer, cost-based selection,
               active sequences, event publishing (analog of
               lib/kv-router + lib/llm/src/kv_router)
  frontend/  — OpenAI-compatible HTTP frontend, preprocessor,
               detokenizer/stop handling, migration (analog of lib/llm)
  engine/    — native JAX serving engine: paged KV cache, continuous
               batching scheduler, bucketed jit step functions
               (the reference delegates this to vLLM/SGLang/TRT-LLM)
  models/    — TPU-native model definitions (Llama family first)
  ops/       — Pallas TPU kernels: ragged paged attention, flash
               attention, block copy/permute, ring attention
  parallel/  — device mesh + sharding specs (dp/tp/ep/sp axes)
  kvbm/      — multi-tier KV block manager: G1 HBM / G2 host / G3 disk
  mocker/    — simulated engine with a TPU step-time model (CI without
               TPUs; analog of lib/mocker)
  planner/   — SLA autoscaler control loop (analog of dynamo.planner)
"""

__version__ = "0.1.0"


def ensure_platform() -> None:
    """Make a JAX_PLATFORMS env override effective even when the image's
    sitecustomize pre-imported jax pinned to another platform (the axon
    TPU relay). Call at process entrypoints before touching any backend —
    tests/subprocesses rely on it to force CPU."""
    import os

    want = os.environ.get("JAX_PLATFORMS")
    if want and want != "axon":
        try:
            import jax

            jax.config.update("jax_platforms", want)
        except Exception:
            # jax absent or backend already initialized — the env var
            # still applies to any later first-touch initialization
            import logging

            logging.getLogger("dynamo_tpu").debug(
                "jax_platforms override to %r not applied", want,
                exc_info=True)


def enable_compilation_cache(path=None):
    """Turn on JAX's persistent compilation cache (SURVEY.md §5.4 — fast
    replica spin-up; the compiled-program half of fast restart, next to
    the orbax weight snapshot). `path` falls back to
    JAX_COMPILATION_CACHE_DIR; returns the directory in effect (None =
    disabled). Zero thresholds so even small step programs are cached — a
    restarted worker's first request must not recompile ANY bucket it
    already served. Lives here (beside ensure_platform) because it is
    env-sensitive jax config every process entrypoint may need — the
    worker and bench.py both call it."""
    import os

    path = path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not path:
        return None
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path
