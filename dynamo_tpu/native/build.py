"""Build-on-demand for native components: compiles native/*.cpp into
shared libraries cached under native/build/ (keyed by source mtime)."""

from __future__ import annotations

import logging
import os
import subprocess
from pathlib import Path
from typing import Optional

log = logging.getLogger("dynamo_tpu.native")

NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
BUILD_DIR = NATIVE_DIR / "build"


def build_library(name: str, cxxflags: Optional[list] = None) -> Optional[Path]:
    """Compile native/{name}.cpp → native/build/lib{name}.so; returns the
    path, or None if the toolchain is unavailable or compilation fails."""
    src = NATIVE_DIR / f"{name}.cpp"
    if not src.exists():
        return None
    out = BUILD_DIR / f"lib{name}.so"
    if out.exists() and out.stat().st_mtime >= src.stat().st_mtime:
        return out
    BUILD_DIR.mkdir(parents=True, exist_ok=True)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC",
        *(cxxflags or []),
        str(src), "-o", str(out),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        log.info("built native library %s", out)
        return out
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError) as e:
        stderr = getattr(e, "stderr", b"") or b""
        log.warning("native build of %s failed (%s); using Python fallback",
                    name, stderr.decode(errors="replace")[:500])
        return None
