"""Native (C++) components with build-on-demand ctypes bindings.

Mirrors the reference's Rust-for-hot-paths / Python-for-control split
(README.md:38 "Built in Rust for performance, Python for extensibility"):
the hot data structures compile to a shared library at first use; every
consumer has a pure-Python fallback so the framework degrades gracefully
where no toolchain exists."""
