"""ctypes wrapper for native/frame_codec.cpp — the C++ request-plane
codec (reference zero_copy_decoder.rs role; VERDICT r4 #5 escalation).

`NativeSplitter.feed(chunk)` returns the msgpack bodies of every frame
completed by that chunk as memoryviews into the splitter's persistent
buffer — one Python call per socket burst instead of two awaited
readexactly() calls plus a struct unpack per frame. The views are decoded
(msgpack-python's C extension) before the next feed, which compacts the
buffer.

`encode_frames(bodies)` length-prefixes a burst of already-packed msgpack
bodies into one bytes object → one writer.write() per burst.

Falls back to None when the toolchain is unavailable; callers keep the
pure-Python per-frame path (request_plane._recv_frame).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional

from dynamo_tpu.native.build import build_library

_LIB = None
_LOAD_TRIED = False

MAX_FRAME = 256 * 1024 * 1024  # mirror request_plane.MAX_FRAME
_BATCH = 512  # frames returned per fc_frames call (looped until drained)


def _load():
    global _LIB, _LOAD_TRIED
    if _LOAD_TRIED:
        return _LIB
    _LOAD_TRIED = True
    path = build_library("frame_codec")
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.fc_new.restype = ctypes.c_void_p
    lib.fc_free.argtypes = [ctypes.c_void_p]
    lib.fc_feed.restype = ctypes.c_int
    lib.fc_feed.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    lib.fc_frames.restype = ctypes.c_long
    lib.fc_frames.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_size_t),
        ctypes.POINTER(ctypes.c_size_t), ctypes.c_long, ctypes.c_size_t,
    ]
    lib.fc_data.restype = ctypes.c_void_p
    lib.fc_data.argtypes = [ctypes.c_void_p]
    lib.fc_consume.argtypes = [ctypes.c_void_p]
    lib.fc_buffered.restype = ctypes.c_size_t
    lib.fc_buffered.argtypes = [ctypes.c_void_p]
    lib.fc_encode.argtypes = [
        ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t), ctypes.c_long,
        ctypes.c_char_p,
    ]
    _LIB = lib
    return lib


def available() -> bool:
    return _load() is not None


class FrameProtocolError(ValueError):
    pass


class NativeSplitter:
    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native frame codec unavailable")
        self._lib = lib
        self._h = lib.fc_new()
        if not self._h:
            raise MemoryError("fc_new failed")
        self._offs = (ctypes.c_size_t * _BATCH)()
        self._lens = (ctypes.c_size_t * _BATCH)()

    def feed(self, chunk: bytes) -> List[memoryview]:
        """Append a socket chunk; return the bodies of every frame it
        completed (memoryviews — decode before the next feed)."""
        lib = self._lib
        if lib.fc_feed(self._h, chunk, len(chunk)) != 0:
            raise MemoryError("fc_feed failed")
        out: List[memoryview] = []
        while True:
            n = lib.fc_frames(self._h, self._offs, self._lens, _BATCH,
                              MAX_FRAME)
            if n == -2:
                raise FrameProtocolError("frame too large")
            if n <= 0:
                break
            base = lib.fc_data(self._h)
            for i in range(n):
                buf = (ctypes.c_char * self._lens[i]).from_address(
                    base + self._offs[i]
                )
                out.append(memoryview(buf))
            if n < _BATCH:
                break
        return out

    def compact(self) -> None:
        """Drop parsed frames (call after decoding the feed() views)."""
        self._lib.fc_consume(self._h)

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.fc_free(h)


def encode_frames(bodies: List[bytes]) -> bytes:
    """Length-prefix a burst of packed msgpack bodies into one buffer.
    Pure-Python fallback when the toolchain is unavailable — callers get
    identical bytes either way."""
    lib = _load()
    if lib is None:
        import struct

        return b"".join(
            struct.pack(">I", len(b)) + b for b in bodies
        )
    n = len(bodies)
    cat = b"".join(bodies)
    lens = (ctypes.c_size_t * n)(*[len(b) for b in bodies])
    out = ctypes.create_string_buffer(len(cat) + 4 * n)
    lib.fc_encode(cat, lens, n, out)
    return out.raw
