"""ctypes binding for the C++ concurrent block index
(native/block_index.cpp) with the same interface as the Python BlockIndex
(dynamo_tpu/router/radix_tree.py) for the event-driven (non-TTL) mode.

Worker tuples (instance_id, dp_rank) are interned to dense u32 ids on the
Python side; block hashes cross the boundary as u64 arrays.
"""

from __future__ import annotations

import ctypes
from typing import Dict, List, Optional, Tuple

from dynamo_tpu.native.build import build_library
from dynamo_tpu.router.protocols import OverlapScores, RouterEvent

Worker = Tuple[int, int]

_LIB = None


def _load():
    global _LIB
    if _LIB is not None:
        return _LIB
    path = build_library("block_index")
    if path is None:
        return None
    lib = ctypes.CDLL(str(path))
    lib.bi_new.restype = ctypes.c_void_p
    lib.bi_free.argtypes = [ctypes.c_void_p]
    lib.bi_apply_store.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, ctypes.c_uint64, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.bi_apply_remove.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
    ]
    lib.bi_remove_worker.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.bi_find_matches.restype = ctypes.c_int
    lib.bi_find_matches.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_int,
    ]
    lib.bi_len.restype = ctypes.c_uint64
    lib.bi_len.argtypes = [ctypes.c_void_p]
    lib.bi_worker_block_count.restype = ctypes.c_uint64
    lib.bi_worker_block_count.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    _LIB = lib
    return lib


def available() -> bool:
    return _load() is not None


def _u64_array(values: List[int]):
    return (ctypes.c_uint64 * len(values))(*[v & 0xFFFFFFFFFFFFFFFF for v in values])


class CppBlockIndex:
    """Drop-in for router BlockIndex (event mode; TTL/approximate mode uses
    the Python index)."""

    MAX_WORKERS_OUT = 1024

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native block index unavailable")
        self._lib = lib
        self._h = lib.bi_new()
        self._worker_ids: Dict[Worker, int] = {}
        self._worker_by_id: Dict[int, Worker] = {}
        self._next = 0

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.bi_free(self._h)
            self._h = None

    def _wid(self, worker: Worker) -> int:
        w = tuple(worker)
        i = self._worker_ids.get(w)
        if i is None:
            i = self._next
            self._next += 1
            self._worker_ids[w] = i
            self._worker_by_id[i] = w
        return i

    # -- BlockIndex interface ----------------------------------------------
    def apply_event(self, ev: RouterEvent, ttl: Optional[float] = None) -> None:
        worker = self._wid(ev.worker)
        if ev.kind == "store":
            arr = _u64_array(ev.block_hashes)
            parent = ev.parent_hash
            self._lib.bi_apply_store(
                self._h, worker,
                (parent or 0) & 0xFFFFFFFFFFFFFFFF,
                1 if parent is not None else 0,
                arr, len(ev.block_hashes),
            )
        elif ev.kind == "remove":
            arr = _u64_array(ev.block_hashes)
            self._lib.bi_apply_remove(self._h, worker, arr, len(ev.block_hashes))
        elif ev.kind == "clear":
            self.remove_worker(ev.worker)

    def find_matches(self, block_hashes: List[int], early_exit: bool = False, now=None) -> OverlapScores:
        if not block_hashes:
            return OverlapScores(total_blocks=0)
        arr = _u64_array(block_hashes)
        out_w = (ctypes.c_uint32 * self.MAX_WORKERS_OUT)()
        out_s = (ctypes.c_uint32 * self.MAX_WORKERS_OUT)()
        n = self._lib.bi_find_matches(
            self._h, arr, len(block_hashes), out_w, out_s, self.MAX_WORKERS_OUT
        )
        scores = {
            self._worker_by_id[out_w[i]]: int(out_s[i])
            for i in range(n)
            if out_s[i] > 0
        }
        return OverlapScores(scores=scores, total_blocks=len(block_hashes))

    def remove_worker(self, worker: Worker) -> None:
        self._lib.bi_remove_worker(self._h, self._wid(worker))

    def worker_block_count(self, worker: Worker) -> int:
        return int(self._lib.bi_worker_block_count(self._h, self._wid(worker)))

    def __len__(self) -> int:
        return int(self._lib.bi_len(self._h))


def make_block_index(prefer_native: bool = True, ttl_mode: bool = False):
    """Best index for the mode: native (event mode) or Python (TTL mode /
    no toolchain)."""
    if prefer_native and not ttl_mode and available():
        return CppBlockIndex()
    from dynamo_tpu.router.radix_tree import BlockIndex

    return BlockIndex()
