"""Shared worker wiring: serve an InferenceEngine (real or mocker) with KV
event publishing, FPM publishing, and the kv_state recovery endpoint.

Mirrors the reference worker startup (components/src/dynamo/vllm/main.py:
engine boot → KV event publisher per dp_rank → register model → FPM relay →
serve_endpoint; SURVEY.md §3.2), collapsed into one helper both
`python -m dynamo_tpu.worker` and `python -m dynamo_tpu.mocker` use.
"""

from __future__ import annotations

import asyncio
import dataclasses
import logging
from typing import Optional

from dynamo_tpu.engine.engine import InferenceEngine
from dynamo_tpu.frontend.protocols import ModelCard
from dynamo_tpu.router.protocols import FPM_SUBJECT
from dynamo_tpu.router.publisher import KvEventPublisher
from dynamo_tpu.runtime.component import new_instance_id
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.tasks import spawn_tracked

log = logging.getLogger("dynamo_tpu.worker")

# cap per-pull payload (whole-KV msgpack messages; chunking is the P->D
# hardening item) — 64 blocks of a 3B model ~ 50MB bf16
MAX_HOST_FETCH_BLOCKS = 64


class ServedWorker:
    def __init__(self, runtime, engine, instance, publisher, close_hooks=None):
        self.runtime = runtime
        self.engine = engine
        self.instance = instance
        self.publisher = publisher
        self.digest_pub = None  # DigestPublisher when digests are on
        self._close_hooks = list(close_hooks or [])

    async def stop(self) -> None:
        self.engine.stop()
        if self.publisher is not None:
            await self.publisher.stop()
        for hook in self._close_hooks:
            try:
                r = hook()
                if hasattr(r, "__await__"):
                    await r
            except Exception:
                log.exception("worker close hook failed")


import weakref

# in-process engine registry: when prefill and decode engines share one
# process (colocated disagg — one TPU slice partitioned by role), the KV
# transfer stays entirely on device instead of a host-staged RPC
LOCAL_ENGINES: "weakref.WeakValueDictionary" = weakref.WeakValueDictionary()


class DisaggDecodeAdapter:
    """Wraps the engine endpoint: requests carrying kv_transfer_src pull
    the parked KV pages from the prefill worker before admission. Same-
    process prefill engines (colocated disagg) transfer device-to-device;
    remote ones go over the request plane (host-staged DCN path)."""

    def __init__(self, engine: InferenceEngine, runtime: DistributedRuntime,
                 chunk_pages: int = 16):
        self.engine = engine
        self.runtime = runtime
        self.chunk_pages = chunk_pages  # 0 = monolithic single-message pull
        self._fetch_clients = {}

    async def _fetch(self, src, parent_ctx=None) -> Optional[dict]:
        local = LOCAL_ENGINES.get(src["instance_id"])
        # device path needs real runners on BOTH ends (mockers track KV at
        # hash level only and must never touch jax)
        if (
            local is not None
            and local is not self.engine
            and hasattr(local.runner, "export_pages_device")
            and hasattr(self.engine.runner, "import_pages_device")
        ):
            # device-resident transfer: gather on the prefill engine's step
            # thread, scatter on ours — no bytes touch the host
            return await local.export_parked_kv_device(src["request_id"])
        path = src["path"]
        client = self._fetch_clients.get(path)
        if client is None:
            client = self.runtime.client(path)
            await client.start()
            self._fetch_clients[path] = client
        client.router.update_instance(src["instance_id"], src["address"])
        # carry the trace across the P->D pull so the kv_fetch hop joins
        # the request's trace
        md = {}
        if parent_ctx is not None and parent_ctx.metadata.get("traceparent"):
            md["traceparent"] = parent_ctx.metadata["traceparent"]
        from dynamo_tpu.runtime.context import Context as _Ctx

        req = {"request_id": src["request_id"]}
        if self.chunk_pages:
            req["chunk_pages"] = self.chunk_pages
        chunks = []
        async for item in client.direct(
            req, src["instance_id"], _Ctx(metadata=md)
        ):
            if not self.chunk_pages:
                return item
            if item:
                chunks.append(item)
        if not chunks:
            return None
        if len(chunks) == 1 and "offset" not in chunks[0]:
            return chunks[0]  # server fell back to the monolithic path
        if not any(c.get("data") or c.get("device") for c in chunks):
            return None  # simulated / empty transfer: recompute locally
        # a truncated stream (prefill-side expiry/abort mid-transfer) must
        # trigger local recompute, never a half-imported KV cache
        total = int(chunks[0].get("total_pages") or 0)
        covered = sum(int(c.get("n_pages") or 0) for c in chunks)
        if total and covered < total:
            log.warning(
                "chunked KV pull truncated (%d/%d pages); recomputing",
                covered, total,
            )
            return None
        return {"chunks": chunks}

    async def generate(self, request, context):
        src = request.get("kv_transfer_src")
        if src is not None:
            try:
                payload = await self._fetch(src, parent_ctx=context)
            except Exception as e:
                log.warning("kv fetch from prefill worker failed: %s", e)
                payload = None
            request = dict(request)
            if payload is not None and (
                payload.get("data") or payload.get("device") or payload.get("chunks")
            ):
                request["kv_import"] = payload
            else:
                # transfer failed → recompute prefill locally (aggregated)
                ann = dict(request.get("annotations") or {})
                ann.pop("disagg", None)
                request["annotations"] = ann
            request.pop("kv_transfer_src", None)
        async for item in self.engine.generate(request, context):
            yield item


async def serve_worker(
    runtime: DistributedRuntime,
    engine: InferenceEngine,
    card: ModelCard,
    namespace: str = "dyn",
    component: str = "tpu-worker",
    endpoint: str = "generate",
    publish_kv_events: bool = True,
    publish_fpm: bool = True,
    digest_period_s: float = 2.0,  # fleet digest publish period (0 = off)
    dp_rank: int = 0,
    disagg_role: Optional[str] = None,  # None/"both" | "prefill" | "decode"
    disagg_chunk_pages: int = 16,  # P->D pull chunk size (0 = monolithic)
    device_weight: Optional[float] = None,  # capacity for device_aware
    #   routing (default: chips this worker's mesh spans)
    http_address: Optional[str] = None,  # this pod's HTTP frontend (direct-
    #   mode sidecar) for the ext-proc endpoint picker (DYN_HTTP_ADDRESS)
) -> ServedWorker:
    import os as _os

    instance_id = new_instance_id()
    LOCAL_ENGINES[instance_id] = engine  # colocated-disagg device transfer
    metadata = {"model_card": card.to_dict(), "dp_rank": dp_rank}
    http_address = http_address or _os.environ.get("DYN_HTTP_ADDRESS")
    if http_address:
        metadata["http_address"] = http_address
    if disagg_role:
        metadata["disagg_role"] = disagg_role
    # topology label for link-class routing: same kv_slice = ICI island,
    # different = DCN hop (engine slice_id wins; env for bare deploys)
    kv_slice = getattr(engine, "slice_id", None) \
        or _os.environ.get("DYN_KV_SLICE")
    if kv_slice:
        metadata["kv_slice"] = str(kv_slice)
    if device_weight is None:
        mesh = getattr(getattr(engine, "runner", None), "mesh_config", None)
        if mesh is not None:
            device_weight = float(mesh.n_devices)
    if device_weight is not None:
        metadata["device_weight"] = device_weight

    publisher = None
    if publish_kv_events:
        publisher = KvEventPublisher(
            runtime.event_publisher(), instance_id, dp_rank=dp_rank
        )
        await publisher.start()
        engine.on_kv_event(publisher.on_engine_events)
        metadata["kv_publisher"] = publisher.address
        await runtime.serve_endpoint(
            f"{namespace}/{component}/kv_state",
            publisher.dump_state,
            instance_id=instance_id,
        )

    if publish_fpm:
        import asyncio

        loop = asyncio.get_running_loop()
        pub = runtime.event_publisher()

        def on_fpm(m) -> None:  # called from the engine step thread
            payload = dataclasses.asdict(m)
            payload["worker"] = [instance_id, dp_rank]

            def _send() -> None:
                spawn_tracked(pub.publish(FPM_SUBJECT, payload), logger=log)

            loop.call_soon_threadsafe(_send)

        engine.on_fpm(on_fpm)
        metadata["fpm_publisher"] = pub.address

    # fleet digest plane (runtime/fleet_observer.py): compact periodic
    # summaries — phase histograms, queue depth, KV tier occupancy,
    # prefetch/compile counters — pushed over the event plane so the
    # frontend's FleetObserver / SLO engine and the planner never scrape.
    # Accumulation hooks run on the engine step thread (bucket increments
    # only); the publish task lives on the event loop.
    digest_pub = None
    if digest_period_s and digest_period_s > 0:
        from dynamo_tpu.runtime.fleet_observer import (
            DigestBuilder, DigestPublisher,
        )

        builder = DigestBuilder(instance_id, dp_rank)
        engine.on_fpm(builder.observe_fpm)
        if hasattr(engine, "on_phases"):
            engine.on_phases(builder.observe_phases)
        digest_pub = DigestPublisher(
            builder, runtime.event_publisher(), engine=engine,
            period_s=digest_period_s,
        )
        digest_pub.start()
        metadata["digest_publisher"] = digest_pub.address
        metadata["digest_period_s"] = digest_pub.period_s

    # disagg endpoints: prefill workers serve parked-KV pulls; decode
    # workers (and aggregated) accept transfer-carrying requests.
    # chunk_pages in the request selects the streamed export (bounded
    # message sizes, chunk reads interleaved with the prefill engine's
    # decode steps — disagg-serving.md bootstrap handoff); absent keeps
    # the single-message path (mockers, old callers).
    async def kv_fetch(request, context):
        req = request or {}
        chunk = int(req.get("chunk_pages") or 0)
        if chunk > 0 and hasattr(engine, "export_parked_kv_stream"):
            any_sent = False
            finished = False
            try:
                async for part in engine.export_parked_kv_stream(
                    req.get("request_id"), chunk
                ):
                    any_sent = True
                    yield part
                finished = True
                if not any_sent:
                    yield {}  # parked entry gone: caller recomputes
            finally:
                if not finished:
                    # puller died mid-stream (disconnect/cancel): release
                    # the parked pages now instead of pinning them for the
                    # full TTL (the monolithic path releases on first read)
                    try:
                        await engine.export_parked_kv(
                            req.get("request_id"), discard=True
                        )
                    except Exception:
                        # discard is best-effort cleanup after a dead
                        # puller; the parked TTL reclaims on failure
                        log.debug("parked-KV discard for %s failed "
                                  "(TTL will reclaim)",
                                  req.get("request_id"), exc_info=True)
            return
        yield await engine.export_parked_kv(
            req.get("request_id"), discard=bool(req.get("discard"))
        )

    await runtime.serve_endpoint(
        f"{namespace}/{component}/kv_fetch", kv_fetch, instance_id=instance_id
    )

    # RL admin surface (reference lib/rl: dyn://ns.comp.rl endpoints with
    # frontend read-only fan-in): pause/resume admission around weight
    # refreshes, orbax weight hot-swap, version reporting, dynamic LoRA
    # registration
    _served = {"inst": None}  # generate instance (set at the end of boot)

    async def rl_admin(request, context):
        req = request or {}
        op = req.get("op", "describe")
        if op == "pause":
            engine.paused = True
        elif op == "resume":
            engine.paused = False
        elif op == "load_adapter":
            # dynamic multi-LoRA: install an adapter into a free slot and
            # republish the model card — the frontend watcher registers
            # the new name as a servable model and routes ONLY to holders
            # (the late-adapter path of LoRA-filtered routing)
            name = req.get("name")
            runner = getattr(engine, "runner", None)
            if not name:
                yield {"error": "load_adapter needs 'name'"}
                return
            if runner is None or getattr(runner, "lora", None) is None:
                yield {"error": "worker built without --lora slots"}
                return
            if name in getattr(runner, "_adapter_slots", {}):
                # register_adapter would return the existing slot WITHOUT
                # touching its factors — reporting success while serving
                # stale weights. Make rollover explicit: new name, or
                # restart (slots are append-only by design).
                yield {"error": f"adapter {name!r} already registered; "
                                "weight rollover needs a new name"}
                return
            import asyncio as _aio

            import numpy as _np

            from dynamo_tpu.models import lora as lora_mod

            try:
                if req.get("peft"):
                    factors = await _aio.to_thread(
                        lora_mod.load_peft_adapter, req["peft"], runner.config
                    )
                else:  # dev adapters: random factors, seeded (an
                    # over-rank request hits the loud check below, same
                    # as the PEFT path — never a silent clamp)
                    factors = lora_mod.random_adapter(
                        runner.config, seed=int(req.get("seed") or 0),
                        scale=float(req.get("scale") or 2.0),
                        rank=int(req.get("rank") or runner.lora_rank),
                        targets=runner.lora_targets,
                    )
                # zero-pad up to the stacked tree's rank (same contract as
                # the boot path, worker._lora_kwargs): padded rows/cols
                # contribute nothing to A @ B. A HIGHER rank cannot fit
                # the fixed slot arrays — fail it loudly below instead of
                # truncating weights.
                for k, arr in list(factors.items()):
                    axis = -1 if k.endswith("_a") else -2
                    r = arr.shape[axis]
                    if r > runner.lora_rank:
                        raise ValueError(
                            f"adapter rank {r} exceeds the worker's "
                            f"--lora-rank {runner.lora_rank}"
                        )
                    if r < runner.lora_rank:
                        pad = [(0, 0)] * arr.ndim
                        pad[axis] = (0, runner.lora_rank - r)
                        factors[k] = _np.pad(arr, pad)
                slot = runner.register_adapter(name, factors)
            except Exception as e:
                yield {"error": f"adapter load failed: {e}"}
                return
            if name not in (card.adapters or []):
                card.adapters = list(card.adapters or []) + [name]
            if _served["inst"] is not None:
                await runtime.update_instance_metadata(
                    _served["inst"], {"model_card": card.to_dict()}
                )
            yield {"model": card.name, "adapter": name, "slot": slot,
                   "adapters": list(card.adapters), "instance": instance_id}
            return
        elif op == "update_weights":
            path = req.get("orbax")
            if not path:
                yield {"error": "update_weights needs 'orbax': <snapshot dir>"}
                return
            try:
                version = await engine.update_weights(path)
            except Exception as e:
                yield {"error": f"weight reload failed: {e}"}
                return
            yield {
                "model": card.name, "paused": bool(engine.paused),
                "weights_version": version, "instance": instance_id,
            }
            return
        elif op != "describe":
            yield {"error": f"unknown rl op {op!r}"}
            return
        yield {
            "model": card.name,
            "paused": bool(getattr(engine, "paused", False)),
            "weights_version": int(getattr(engine, "weights_version", 0)),
            "instance": instance_id,
        }

    if hasattr(engine, "update_weights"):
        await runtime.serve_endpoint(
            f"{namespace}/{component}/rl", rl_admin, instance_id=instance_id
        )

    # cross-worker KVBM onboarding (reference kvbm-engine onboarding
    # sessions): peers pull lower-tier blocks from this worker, and this
    # worker pulls from peers when the router's hint names one
    async def kv_host_fetch(request, context):
        hashes = [int(h) for h in (request or {}).get("hashes") or []]
        return await engine.export_host_blocks(hashes[:MAX_HOST_FETCH_BLOCKS])

    await runtime.serve_endpoint(
        f"{namespace}/{component}/kv_host_fetch", kv_host_fetch,
        instance_id=instance_id,
    )

    # predictive prefetch plane (kvbm/prefetch.py): the router announces
    # what the inbound request will need BEFORE dispatching it; the
    # engine's PrefetchManager promotes those blocks up the KVBM ladder
    # while the request is still queueing. Advertised via metadata so
    # routers skip workers without a manager.
    if getattr(engine, "prefetch", None) is not None:
        metadata["kv_prefetch"] = True
        # counters must live in the runtime's registry or the status
        # port's /metrics never sees them
        engine.prefetch.bind_metrics(runtime.metrics.child(dynamo_namespace=namespace))

    # compile-cache observability: per step-function family (forward /
    # decode_loop / mixed / ragged), compiled-variant count and cumulative
    # trace+compile seconds. Refreshed from the step thread's FPM hook —
    # compiles only happen during steps, so the gauges are never stale
    # when someone scrapes after a step completed. The ragged mixed path's
    # cardinality collapse (variants <= |T buckets|) is read off these.
    _runner = getattr(engine, "runner", None)
    if hasattr(_runner, "compile_stats"):
        _cm = runtime.metrics.child(dynamo_namespace=namespace)

        def _update_compile_gauges(_m=None) -> None:
            for fam, st in _runner.compile_stats().items():
                _cm.gauge(
                    "compile_variants",
                    "compiled XLA variants per step-function family",
                    family=fam,
                ).set(st["variants"])
                _cm.gauge(
                    "compile_seconds_total",
                    "cumulative trace+compile wall seconds per family",
                    family=fam,
                ).set(st["compile_s"])

        engine.on_fpm(_update_compile_gauges)
        _update_compile_gauges()

    # latency spine -> /metrics: per-finished-request phase durations as
    # histograms labeled by phase (queue_wait/ttft/kv_onboard/...; ITL
    # samples fold into one phase="itl" histogram). Fired from the engine
    # step thread via on_phases; histogram observe is lock-cheap.
    if hasattr(engine, "on_phases"):
        _pm = runtime.metrics.child(dynamo_namespace=namespace)

        def _observe_phases(phases: dict) -> None:
            for key, val in phases.items():
                if key == "itl_s" and isinstance(val, list):
                    h = _pm.histogram(
                        "request_phase_seconds",
                        "per-request latency spine phase durations",
                        phase="itl")
                    for s in val:
                        h.observe(float(s))
                elif isinstance(val, (int, float)):
                    _pm.histogram(
                        "request_phase_seconds",
                        "per-request latency spine phase durations",
                        phase=key.removesuffix("_s"),
                    ).observe(float(val))

        engine.on_phases(_observe_phases)

    # flight recorder: fired-anomaly counter onto the shared registry, and
    # advertise the recorder via metadata so tooling knows /debug/timeline
    # is live on this worker's status port
    _rec = getattr(engine, "recorder", None)
    if _rec is not None and getattr(_rec, "enabled", False):
        _rec.bind_metrics(
            runtime.metrics.child(dynamo_namespace=namespace))
        metadata["flight_recorder"] = True

    async def kv_prefetch(request, context):
        hint = (request or {}).get("kv_prefetch") or {}
        ok = False
        if getattr(engine, "prefetch", None) is not None and hint:
            ok = await engine.prefetch_hint_async(hint)
        yield {"ok": bool(ok)}

    await runtime.serve_endpoint(
        f"{namespace}/{component}/kv_prefetch", kv_prefetch,
        instance_id=instance_id,
    )

    _fetch_clients: dict = {}

    async def _remote_kv_fetch(hint):
        from dynamo_tpu.runtime import tracing

        path = hint["path"]
        client = _fetch_clients.get(path)
        if client is None:
            client = runtime.client(path)
            # cache before any await that can raise: a failed first pull
            # must not leak a client (and its discovery-watch task) per
            # request; direct() surfaces cannot_connect on its own
            _fetch_clients[path] = client
            await client.start()
        # cross-worker onboarding pull as a traced hop: the router stamped
        # the route span's traceparent into the hint, so this transfer
        # joins the request's trace with tier + size attribution
        with tracing.span(
            "kv.peer_pull", parent=hint.get("traceparent"), kind=3,
            attributes={
                "kv.n_blocks": len(hint.get("hashes") or []),
                "kv.peer_instance": int(hint["instance"]),
            },
        ):
            # first pull after client creation races the discovery watch:
            # give the target instance a moment to appear instead of
            # failing into the engine's 30s peer backoff
            deadline = asyncio.get_running_loop().time() + 2.0
            while (int(hint["instance"]) not in client.instances
                   and asyncio.get_running_loop().time() < deadline):
                await asyncio.sleep(0.05)
            req = {"hashes":
                   [int(h) for h in hint["hashes"][:MAX_HOST_FETCH_BLOCKS]]}
            async for item in client.direct(req, int(hint["instance"])):
                return item
            return None

    engine.remote_kv_fetch = _remote_kv_fetch

    async def _close_fetch_clients():
        for c in _fetch_clients.values():
            await c.close()

    close_hooks = [_close_fetch_clients]
    if digest_pub is not None:
        # final flush on stop: the last partial window still reaches the
        # observer (the chaos suite's mid-window death is the case where
        # it does NOT flush — SIGKILL — and the observer must cope)
        close_hooks.append(digest_pub.stop)
    handler = DisaggDecodeAdapter(engine, runtime, chunk_pages=disagg_chunk_pages)

    engine.start()
    inst = await runtime.serve_endpoint(
        f"{namespace}/{component}/{endpoint}",
        handler,
        metadata=metadata,
        instance_id=instance_id,
    )
    _served["inst"] = inst  # rl load_adapter republishes this card
    log.info("worker %x serving %s (role=%s)", instance_id, card.name, disagg_role or "both")
    served = ServedWorker(runtime, engine, inst, publisher, close_hooks=close_hooks)
    served.digest_pub = digest_pub
    return served
