"""Shared worker wiring: serve an InferenceEngine (real or mocker) with KV
event publishing, FPM publishing, and the kv_state recovery endpoint.

Mirrors the reference worker startup (components/src/dynamo/vllm/main.py:
engine boot → KV event publisher per dp_rank → register model → FPM relay →
serve_endpoint; SURVEY.md §3.2), collapsed into one helper both
`python -m dynamo_tpu.worker` and `python -m dynamo_tpu.mocker` use.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

from dynamo_tpu.engine.engine import InferenceEngine
from dynamo_tpu.frontend.protocols import ModelCard
from dynamo_tpu.router.protocols import FPM_SUBJECT
from dynamo_tpu.router.publisher import KvEventPublisher
from dynamo_tpu.runtime.component import new_instance_id
from dynamo_tpu.runtime.distributed import DistributedRuntime

log = logging.getLogger("dynamo_tpu.worker")


class ServedWorker:
    def __init__(self, runtime, engine, instance, publisher):
        self.runtime = runtime
        self.engine = engine
        self.instance = instance
        self.publisher = publisher

    async def stop(self) -> None:
        self.engine.stop()
        if self.publisher is not None:
            await self.publisher.stop()


async def serve_worker(
    runtime: DistributedRuntime,
    engine: InferenceEngine,
    card: ModelCard,
    namespace: str = "dyn",
    component: str = "tpu-worker",
    endpoint: str = "generate",
    publish_kv_events: bool = True,
    publish_fpm: bool = True,
    dp_rank: int = 0,
) -> ServedWorker:
    instance_id = new_instance_id()
    metadata = {"model_card": card.to_dict(), "dp_rank": dp_rank}

    publisher = None
    if publish_kv_events:
        publisher = KvEventPublisher(
            runtime.event_publisher(), instance_id, dp_rank=dp_rank
        )
        await publisher.start()
        engine.on_kv_event(publisher.on_engine_events)
        metadata["kv_publisher"] = publisher.address
        await runtime.serve_endpoint(
            f"{namespace}/{component}/kv_state",
            publisher.dump_state,
            instance_id=instance_id,
        )

    if publish_fpm:
        import asyncio

        loop = asyncio.get_running_loop()
        pub = runtime.event_publisher()

        def on_fpm(m) -> None:  # called from the engine step thread
            payload = dataclasses.asdict(m)
            payload["worker"] = [instance_id, dp_rank]

            def _send() -> None:
                asyncio.ensure_future(pub.publish(FPM_SUBJECT, payload))

            loop.call_soon_threadsafe(_send)

        engine.on_fpm(on_fpm)
        metadata["fpm_publisher"] = pub.address

    engine.start()
    inst = await runtime.serve_endpoint(
        f"{namespace}/{component}/{endpoint}",
        engine,
        metadata=metadata,
        instance_id=instance_id,
    )
    log.info("worker %x serving %s", instance_id, card.name)
    return ServedWorker(runtime, engine, inst, publisher)
