"""`python -m dynamo_tpu.global_planner` — multi-cluster scaling policy.

Analog of reference `components/src/dynamo/global_planner` (multi-DGD
shared-policy coordination): where each cluster's local Planner scales
its own workers against its own SLOs, the GLOBAL planner owns one shared
accelerator budget across clusters/DGDs and divides it by observed
demand — so a traffic surge in one region borrows chips another region
isn't using, instead of both planners fighting independent budgets.

Control loop (the reference's OBSERVE → PROPOSE → EXECUTE shape, one
level up):

  OBSERVE  — per cluster: demand signal (in-flight requests + queue
             depth from the frontend's Prometheus /metrics, or any
             injected observer callable)
  PROPOSE  — water-filling allocation: every cluster gets its floor
             (min_replicas), the remaining budget splits proportionally
             to demand-per-replica pressure, clamped to [min, max] and
             to the total budget
  EXECUTE  — per-cluster Connector.scale_to (KubernetesConnector PATCHes
             the DGD, the operator rolls pods; VirtualConnector for
             tests/sim)

Hysteresis: a cluster's allocation only moves when the proposal differs
from current by >= `step_threshold` replicas, and never more often than
`cooldown_s` per cluster — the same dampening the local planner applies,
preventing global/local oscillation.
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional

log = logging.getLogger("dynamo_tpu.global_planner")


@dataclass
class ClusterSpec:
    name: str
    connector: object  # planner.connector.Connector
    component: str = "workers"
    # demand observer: async () -> float (e.g. in-flight + queued reqs).
    observe: Optional[Callable[[], Awaitable[float]]] = None
    metrics_url: Optional[str] = None  # fallback: frontend /metrics
    min_replicas: int = 1
    max_replicas: int = 1 << 30
    last_scaled: float = field(default=0.0, compare=False)


async def _prometheus_demand(url: str) -> float:
    """Sum dynamo_frontend_in_flight + router queue depth from a
    frontend's Prometheus exposition (the same series the dashboards
    plot)."""
    import aiohttp

    total = 0.0
    async with aiohttp.ClientSession() as s:
        async with s.get(url, timeout=aiohttp.ClientTimeout(total=5)) as r:
            text = await r.text()
    for line in text.splitlines():
        if line.startswith(("dynamo_frontend_in_flight{",
                            "dynamo_frontend_in_flight ",
                            "dynamo_router_queue_depth{",
                            "dynamo_router_queue_depth ")):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def allocate(
    demands: Dict[str, float],
    current: Dict[str, int],
    budget: int,
    mins: Dict[str, int],
    maxs: Dict[str, int],
) -> Dict[str, int]:
    """Water-filling proposal: floors first, then the remaining budget
    proportional to demand, clamped per-cluster. Pure function (tested
    directly; the loop wraps it with hysteresis)."""
    names = list(demands)
    out = {n: min(mins[n], maxs[n]) for n in names}
    spend = sum(out.values())
    remaining = max(0, budget - spend)
    # proportional shares of the remaining budget by demand
    total_demand = sum(max(0.0, demands[n]) for n in names)
    if total_demand <= 0:
        return out  # idle everywhere: floors only
    # largest-remainder rounding so shares sum exactly to `remaining`
    raw = {
        n: remaining * max(0.0, demands[n]) / total_demand for n in names
    }
    base = {n: int(raw[n]) for n in names}
    leftover = remaining - sum(base.values())
    by_frac = sorted(names, key=lambda n: raw[n] - base[n], reverse=True)
    for n in by_frac[:leftover]:
        base[n] += 1
    # clamp to max, returning the overflow to the most-demanding others
    overflow = 0
    for n in names:
        want = out[n] + base[n]
        cap = maxs[n]
        if want > cap:
            overflow += want - cap
            want = cap
        out[n] = want
    if overflow:
        for n in sorted(names, key=lambda n: demands[n], reverse=True):
            room = maxs[n] - out[n]
            take = min(room, overflow)
            out[n] += take
            overflow -= take
            if overflow <= 0:
                break
    return out


class GlobalPlanner:
    def __init__(
        self,
        clusters: List[ClusterSpec],
        budget: int,
        interval_s: float = 30.0,
        step_threshold: int = 1,
        cooldown_s: float = 60.0,
    ):
        self.clusters = {c.name: c for c in clusters}
        self.budget = budget
        self.interval_s = interval_s
        self.step_threshold = step_threshold
        self.cooldown_s = cooldown_s
        self._task: Optional[asyncio.Task] = None
        self.last_decision: Dict[str, int] = {}

    async def _demand(self, c: ClusterSpec) -> float:
        try:
            if c.observe is not None:
                return float(await c.observe())
            if c.metrics_url:
                return await _prometheus_demand(c.metrics_url)
        except Exception:
            log.exception("observe failed for %s", c.name)
        return 0.0

    async def tick(self, now: Optional[float] = None) -> Dict[str, int]:
        """One OBSERVE→PROPOSE→EXECUTE pass; returns the executed targets
        (clusters skipped by hysteresis keep their current count)."""
        now = time.monotonic() if now is None else now
        names = list(self.clusters)
        demands, current = {}, {}
        for n in names:
            c = self.clusters[n]
            demands[n] = await self._demand(c)
            cur = await c.connector.current_replicas(c.component)
            current[n] = int(cur if cur is not None else c.min_replicas)
        proposal = allocate(
            demands, current, self.budget,
            {n: self.clusters[n].min_replicas for n in names},
            {n: self.clusters[n].max_replicas for n in names},
        )
        executed: Dict[str, int] = {}
        for n in names:
            c = self.clusters[n]
            target = proposal[n]
            if abs(target - current[n]) < self.step_threshold:
                executed[n] = current[n]
                continue
            if now - c.last_scaled < self.cooldown_s:
                executed[n] = current[n]
                continue
            log.info("global: %s %d -> %d (demand %.1f)",
                     n, current[n], target, demands[n])
            await c.connector.scale_to(c.component, target)
            c.last_scaled = now
            executed[n] = target
        self.last_decision = executed
        return executed

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _loop(self) -> None:
        try:
            while True:
                try:
                    await self.tick()
                except Exception:
                    log.exception("global planner tick failed")
                await asyncio.sleep(self.interval_s)
        except asyncio.CancelledError:
            pass


def parse_args(argv=None):
    p = argparse.ArgumentParser("dynamo_tpu.global_planner")
    p.add_argument(
        "--cluster", action="append", default=[], metavar="SPEC",
        help="name=k8s_api_base,namespace,dgd,component[,metrics_url]"
             " — repeat per cluster",
    )
    p.add_argument("--budget", type=int, required=True,
                   help="total worker replicas shared across clusters")
    p.add_argument("--interval", type=float, default=30.0)
    p.add_argument("--cooldown", type=float, default=60.0)
    p.add_argument("--min-replicas", type=int, default=1)
    p.add_argument("--max-replicas", type=int, default=1 << 30)
    return p.parse_args(argv)


def build_clusters(args) -> List[ClusterSpec]:
    from dynamo_tpu.planner.connector import KubernetesConnector

    out = []
    for spec in args.cluster:
        name, _, rest = spec.partition("=")
        parts = rest.split(",")
        if len(parts) < 4:
            raise SystemExit(f"bad --cluster spec {spec!r}")
        api, ns, dgd, comp = parts[:4]
        out.append(ClusterSpec(
            name=name,
            connector=KubernetesConnector(
                namespace=ns, dgd=dgd or None, api_base=api,
            ),
            component=comp,
            metrics_url=parts[4] if len(parts) > 4 else None,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
        ))
    return out


def main(argv=None) -> None:
    from dynamo_tpu.runtime.logging_util import configure_logging

    configure_logging()
    args = parse_args(argv)
    gp = GlobalPlanner(
        build_clusters(args), budget=args.budget,
        interval_s=args.interval, cooldown_s=args.cooldown,
    )

    async def _run():
        await gp.start()
        await asyncio.Event().wait()

    try:
        asyncio.run(_run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
