"""etcd discovery backend over the v3 JSON gRPC-gateway.

The reference runtime's primary discovery/lease store is etcd
(lib/runtime/src/distributed.rs:149-180, transports/etcd.rs: lease-scoped
instance keys + prefix watches feeding ModelWatcher). This backend speaks
the same etcd semantics through the v3 HTTP/JSON gateway (`/v3/kv/*`,
`/v3/lease/*`, `/v3/watch`) so no native client library is required:

- register  → LeaseGrant(ttl) + Put(key, value, lease)
- heartbeat → LeaseKeepAlive (re-registers if the lease was lost)
- watch     → streaming /v3/watch with an initial Range replay; DELETE
              events are synthesized from the last-seen record since etcd
              delete notifications carry no value

Keys are the instance paths (`services/...`), values the instance JSON —
identical layout to the file backend, so operators can inspect state with
plain etcdctl.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
from typing import AsyncIterator, Dict, List, Optional

from dynamo_tpu.runtime.component import Instance
from dynamo_tpu.runtime.discovery import DiscoveryBackend, DiscoveryEvent

log = logging.getLogger("dynamo_tpu.runtime.etcd")


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


def _prefix_end(prefix: str) -> str:
    """etcd range_end for a prefix scan: prefix with last byte + 1."""
    b = bytearray(prefix.encode())
    b[-1] += 1
    return base64.b64encode(bytes(b)).decode()


class EtcdDiscovery(DiscoveryBackend):
    def __init__(
        self,
        endpoint: str = "http://127.0.0.1:2379",
        lease_ttl: int = 10,
    ):
        self.endpoint = endpoint.rstrip("/")
        self.lease_ttl = max(2, int(lease_ttl))
        self._session = None  # aiohttp.ClientSession, lazy
        self._lease_id: Optional[int] = None
        # serializes lease grant: two concurrent _lease() calls would each
        # grant, and the loser's lease leaks until its TTL (DYN-A007)
        self._lease_lock = asyncio.Lock()
        self._mine: Dict[str, Instance] = {}

    async def _http(self):
        if self._session is None:
            import aiohttp

            self._session = aiohttp.ClientSession()
        return self._session

    async def _post(self, path: str, body: dict) -> dict:
        s = await self._http()
        async with s.post(self.endpoint + path, json=body) as resp:
            resp.raise_for_status()
            return await resp.json()

    async def _lease(self) -> int:
        async with self._lease_lock:
            if self._lease_id is None:
                out = await self._post(
                    "/v3/lease/grant", {"TTL": self.lease_ttl})
                self._lease_id = int(out["ID"])
            return self._lease_id

    # -- DiscoveryBackend ---------------------------------------------------
    async def register(self, instance: Instance) -> None:
        lease = await self._lease()
        await self._post(
            "/v3/kv/put",
            {
                "key": _b64(instance.path),
                "value": _b64(json.dumps(instance.to_dict())),
                "lease": lease,
            },
        )
        self._mine[instance.path] = instance

    async def unregister(self, instance: Instance) -> None:
        self._mine.pop(instance.path, None)
        await self._post("/v3/kv/deleterange", {"key": _b64(instance.path)})

    async def heartbeat(self) -> None:
        if self._lease_id is None:
            return
        try:
            out = await self._post("/v3/lease/keepalive", {"ID": self._lease_id})
            ttl = int(out.get("result", out).get("TTL", 0))
        except Exception:
            ttl = 0
        if ttl <= 0:
            # lease expired (e.g. long GC pause / etcd restart): new lease,
            # re-register everything — the reference's lease-recovery path
            log.warning("etcd lease %s lost; re-registering %d instances",
                        self._lease_id, len(self._mine))
            self._lease_id = None
            for inst in list(self._mine.values()):
                await self.register(inst)

    async def _range(self, prefix: str):
        """(instances, revision) — the revision anchors a gap-free watch."""
        out = await self._post(
            "/v3/kv/range",
            {"key": _b64(prefix), "range_end": _prefix_end(prefix)},
        )
        result: List[Instance] = []
        for kv in out.get("kvs") or []:
            try:
                result.append(Instance.from_dict(json.loads(_unb64(kv["value"]))))
            except (ValueError, KeyError):
                continue
        rev = int((out.get("header") or {}).get("revision", 0))
        return result, rev

    async def list_instances(self, prefix: str = "") -> List[Instance]:
        return (await self._range(prefix or "services/"))[0]

    async def watch(self, prefix: str = "") -> AsyncIterator[DiscoveryEvent]:
        prefix = prefix or "services/"
        known: Dict[str, dict] = {}
        rev = 0
        # initial replay (retry until etcd is reachable)
        while True:
            try:
                insts, rev = await self._range(prefix)
                break
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("etcd initial range failed (%s); retrying", e)
                await asyncio.sleep(0.5)
        for inst in insts:
            known[inst.path] = inst.to_dict()
            yield DiscoveryEvent("put", inst)
        s = await self._http()
        while True:
            # watch from rev+1: events between the range/resync and the
            # stream creation are replayed, not lost
            body = {
                "create_request": {
                    "key": _b64(prefix),
                    "range_end": _prefix_end(prefix),
                    "start_revision": str(rev + 1),
                }
            }
            try:
                async with s.post(self.endpoint + "/v3/watch", json=body) as resp:
                    resp.raise_for_status()
                    async for line in resp.content:
                        if not line.strip():
                            continue
                        msg = json.loads(line)
                        result = msg.get("result") or {}
                        rev = max(
                            rev, int((result.get("header") or {}).get("revision", 0))
                        )
                        for ev in result.get("events") or []:
                            kind = "delete" if ev.get("type") == "DELETE" else "put"
                            key = _unb64(ev["kv"]["key"])
                            if kind == "put":
                                rec = json.loads(_unb64(ev["kv"]["value"]))
                                known[key] = rec
                                yield DiscoveryEvent("put", Instance.from_dict(rec))
                            else:
                                rec = known.pop(key, None)
                                if rec is not None:
                                    yield DiscoveryEvent(
                                        "delete", Instance.from_dict(rec)
                                    )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("etcd watch stream error (%s); resyncing", e)
                await asyncio.sleep(0.5)
                try:
                    current_insts, rev = await self._range(prefix)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    continue  # still down; keep retrying, don't kill the watch
                current = {i.path: i.to_dict() for i in current_insts}
                for path, rec in current.items():
                    if known.get(path) != rec:
                        known[path] = rec
                        yield DiscoveryEvent("put", Instance.from_dict(rec))
                for path in list(known):
                    if path not in current:
                        rec = known.pop(path)
                        yield DiscoveryEvent("delete", Instance.from_dict(rec))

    async def close(self) -> None:
        # claim both fields before their awaits: a concurrent close() must
        # not double-revoke the lease or double-close the session
        lease, self._lease_id = self._lease_id, None
        if lease is not None:
            try:
                await self._post("/v3/lease/revoke", {"ID": lease})
            except Exception:
                log.debug("lease revoke failed on close; etcd TTL will "
                          "expire it", exc_info=True)
        session, self._session = self._session, None
        if session is not None:
            await session.close()
