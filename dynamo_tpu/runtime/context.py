"""Request context with cancellation lifecycle.

Analog of the reference's `AsyncEngineContext` (lib/runtime/src/engine.rs:116-130):
every request carries an id, propagated metadata, and a two-stage stop
lifecycle — `stop_generating` (graceful: finish the current token, emit a
final chunk) and `kill` (immediate abandon).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Any, Dict, Optional


class CancellationError(Exception):
    """Raised inside engine streams when the context has been killed."""


class Context:
    """Per-request metadata + cancellation token hierarchy.

    Contexts form a tree: child contexts are stopped/killed when their
    parent is (mirrors the reference's cancellation-token hierarchy,
    lib/runtime/src/utils/graceful_shutdown.rs).
    """

    def __init__(
        self,
        request_id: Optional[str] = None,
        metadata: Optional[Dict[str, Any]] = None,
        parent: Optional["Context"] = None,
    ):
        self.id: str = request_id or uuid.uuid4().hex
        self.metadata: Dict[str, Any] = dict(metadata or {})
        self.created_at: float = time.monotonic()
        self._stop = asyncio.Event()
        self._kill = asyncio.Event()
        self._parent = parent
        self._children: list[Context] = []
        if parent is not None:
            parent._children.append(self)
            # inherit state if the parent was stopped/killed before we existed
            if parent.is_killed:
                self._kill.set()
                self._stop.set()
            elif parent.is_stopped:
                self._stop.set()

    # -- lifecycle ---------------------------------------------------------
    def stop_generating(self) -> None:
        """Graceful stop: engines should finish the in-flight step and end."""
        self._stop.set()
        for c in self._children:
            c.stop_generating()

    def kill(self) -> None:
        """Hard stop: abandon the stream immediately."""
        self._kill.set()
        self._stop.set()
        for c in self._children:
            c.kill()

    @property
    def is_stopped(self) -> bool:
        return self._stop.is_set() or (self._parent is not None and self._parent.is_stopped)

    @property
    def is_killed(self) -> bool:
        return self._kill.is_set() or (self._parent is not None and self._parent.is_killed)

    def raise_if_killed(self) -> None:
        if self.is_killed:
            raise CancellationError(f"request {self.id} killed")

    async def wait_stopped(self) -> None:
        await self._stop.wait()

    def child(self, request_id: Optional[str] = None) -> "Context":
        return Context(request_id=request_id or self.id, metadata=self.metadata, parent=self)

    # -- wire form ---------------------------------------------------------
    def to_headers(self) -> Dict[str, Any]:
        """Serializable subset propagated across the request plane."""
        return {"request_id": self.id, "metadata": self.metadata}

    @classmethod
    def from_headers(cls, headers: Dict[str, Any]) -> "Context":
        return cls(
            request_id=headers.get("request_id"),
            metadata=headers.get("metadata") or {},
        )

    def __repr__(self) -> str:  # pragma: no cover
        return f"Context(id={self.id!r}, stopped={self.is_stopped}, killed={self.is_killed})"
