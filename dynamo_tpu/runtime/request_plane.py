"""TCP/msgpack request plane (analog of reference
lib/runtime/src/pipeline/network/: PushEndpoint ingress, PushRouter egress,
two-part msgpack codec, connection pooling).

Frames are length-prefixed msgpack maps:
  client→server: {"t":"req","id",...,"endpoint","headers","payload"}
                 {"t":"cancel","id"}       (graceful stop_generating)
                 {"t":"kill","id"}         (hard kill)
  server→client: {"t":"item","id","data"} ...  {"t":"done","id"}
                 {"t":"err","id","msg","code"}

Connections are MULTIPLEXED: many id-tagged request streams interleave on
one TCP connection (reference zero_copy_decoder.rs + conn pooling — the
server has always demuxed by id; the client-side _MuxConn completes the
pair). A small per-address connection set fans out streams by
least-streams-first, so hundreds of concurrent requests ride a handful of
sockets instead of one socket each.
"""

from __future__ import annotations

import asyncio
import logging
import random
import struct
import time as _time
from typing import Any, AsyncIterator, Dict, Optional, Tuple

import msgpack

from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.context import CancellationError, Context
from dynamo_tpu.runtime.engine import AsyncEngine
from dynamo_tpu.runtime.tasks import spawn_tracked

log = logging.getLogger("dynamo_tpu.request_plane")

_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024


class RequestPlaneError(Exception):
    """Transport-level failure; carries a code used by migration
    classification (reference migration.rs:60-68)."""

    def __init__(self, msg: str, code: str = "internal"):
        super().__init__(msg)
        self.code = code


async def _send_frame(writer: asyncio.StreamWriter, obj: Dict[str, Any]) -> None:
    body = msgpack.packb(obj, use_bin_type=True)
    writer.write(_LEN.pack(len(body)) + body)
    await writer.drain()


async def _recv_frame(reader: asyncio.StreamReader) -> Optional[Dict[str, Any]]:
    try:
        # the idle wait between frames: blocking here forever is the
        # contract, and peer death surfaces as IncompleteReadError
        hdr = await reader.readexactly(4)  # dynlint: disable=DYN-R003
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise RequestPlaneError(f"frame too large: {n}", code="protocol")
    try:
        # body follows its length header; conn death is handled below
        body = await reader.readexactly(n)  # dynlint: disable=DYN-R003
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False)


def _codec_available() -> bool:
    """Probe (and on first call possibly BUILD) the native codec —
    blocking: `frame_codec._load` shells out to the compiler once. Only
    reached through `_native_codec_on`, which runs it off the loop."""
    try:
        from dynamo_tpu.native.frame_codec import available

        return available()
    except Exception:  # toolchain missing → Python path
        return False


_NATIVE_AVAILABLE: Optional[bool] = None


async def _native_codec_on() -> bool:
    """C++ frame codec (reference zero_copy_decoder.rs role): bulk-read
    both plane read loops and split frames natively — one Python call per
    socket burst instead of two awaited readexactly() per frame. Same
    wire protocol. ON by default when the toolchain is available: the
    scripts/bench_codec.py A/B has native ahead on every run even on a
    single-core host (1.01-1.12x, docs/perf_notes.md), and the native
    splitter additionally stays off the GIL on multi-core frontends.
    DYN_NATIVE_CODEC=0 forces the pure-Python loop (and remains the
    safety valve if a platform's build misbehaves).

    The env decision is re-read per call (tests flip it between planes);
    the availability probe — which may invoke the COMPILER on first use —
    runs in a thread exactly once, so the first connection no longer
    stalls the event loop behind a cc invocation (DYN-A001)."""
    import os

    raw = os.environ.get("DYN_NATIVE_CODEC", "").lower()
    if raw in ("0", "false", "off", "no"):
        return False
    global _NATIVE_AVAILABLE
    if _NATIVE_AVAILABLE is None:
        _NATIVE_AVAILABLE = await asyncio.to_thread(_codec_available)
    return _NATIVE_AVAILABLE


async def _bulk_frames(reader: asyncio.StreamReader, splitter, on_frame):
    """Native-codec read loop body: drain the socket in 256 KiB bursts,
    decode every completed frame, await `on_frame(dict)` for each.
    Returns on EOF; raises RequestPlaneError on protocol violations."""
    from dynamo_tpu.native.frame_codec import FrameProtocolError

    while True:
        try:
            chunk = await reader.read(262144)
        except (ConnectionResetError, BrokenPipeError):
            return
        if not chunk:
            return
        try:
            bodies = splitter.feed(chunk)
        except FrameProtocolError:
            raise RequestPlaneError("frame too large", code="protocol")
        for body in bodies:
            await on_frame(msgpack.unpackb(body, raw=False))
        splitter.compact()


class PushEndpoint:
    """Server side: serves one AsyncEngine per endpoint path on a TCP port
    (reference ingress/push_endpoint.rs:21,36). One server instance can host
    many endpoints (the reference's NetworkManager role)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._engines: Dict[str, AsyncEngine] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._active: Dict[str, Context] = {}
        self._conns: set = set()  # open connection writers (for shutdown)
        self._draining = False

    def add_endpoint(self, path: str, engine: AsyncEngine) -> None:
        self._engines[path] = engine

    def remove_endpoint(self, path: str) -> None:
        self._engines.pop(path, None)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def active_requests(self) -> int:
        return len(self._active)

    async def start(self) -> str:
        self._server = await asyncio.start_server(self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.address

    async def stop(self, drain_timeout: float = 30.0) -> None:
        """Graceful shutdown: refuse new requests, wait for in-flight to
        drain, then kill stragglers (reference graceful_shutdown.rs)."""
        self._draining = True
        if self._server is not None:
            self._server.close()
        deadline = asyncio.get_event_loop().time() + drain_timeout
        while self._active and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)
        for ctx in list(self._active.values()):
            ctx.kill()
        # close lingering (e.g. idle pooled) connections, else wait_closed()
        # blocks on parked connection handlers (py>=3.12.1 semantics)
        for w in list(self._conns):
            w.close()
        if self._server is not None:
            await self._server.wait_closed()

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        """Single reader loop per connection: `req` frames spawn response
        tasks; `cancel`/`kill` frames route to the matching in-flight context
        (avoids two tasks racing on one reader)."""
        conn_ctxs: Dict[str, Context] = {}
        tasks: set = set()
        wlock = asyncio.Lock()
        self._conns.add(writer)

        async def on_frame(frame: Dict[str, Any]) -> None:
            t = frame.get("t")
            if t == "req":

                async def send(obj: Dict[str, Any]) -> None:
                    async with wlock:
                        await _send_frame(writer, obj)

                task = asyncio.create_task(
                    self._handle_request(frame, send, conn_ctxs)
                )
                tasks.add(task)
                task.add_done_callback(tasks.discard)
            elif t == "cancel":
                ctx = conn_ctxs.get(frame.get("id"))
                if ctx is not None:
                    ctx.stop_generating()
            elif t == "kill":
                ctx = conn_ctxs.get(frame.get("id"))
                if ctx is not None:
                    ctx.kill()

        try:
            if await _native_codec_on():
                from dynamo_tpu.native.frame_codec import NativeSplitter

                await _bulk_frames(reader, NativeSplitter(), on_frame)
                return
            while True:
                frame = await _recv_frame(reader)
                if frame is None:
                    return
                await on_frame(frame)
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            self._conns.discard(writer)
            for ctx in conn_ctxs.values():
                ctx.kill()  # client went away
            for task in tasks:
                task.cancel()
            writer.close()

    async def _handle_request(
        self,
        frame: Dict[str, Any],
        send,  # async callable(obj) — TCP frame write or NATS publish
        conn_ctxs: Dict[str, Context],
    ) -> None:
        rid = frame["id"]
        path = frame["endpoint"]
        engine = self._engines.get(path)
        if engine is None or self._draining:
            code = "draining" if self._draining else "no_endpoint"
            await send({"t": "err", "id": rid, "msg": f"{code}: {path}", "code": code})
            return
        ctx = Context.from_headers(frame.get("headers") or {})
        self._active[rid] = ctx
        conn_ctxs[rid] = ctx
        # server-hop span: continues the trace the caller's metadata carries
        # (reference: span per ingress hop, logging.rs:76-105) and re-points
        # the metadata so the engine's own egress calls nest under this hop
        attrs = {"rpc.endpoint": path, "request.id": rid}
        try:
            # metadata is raw wire input — a malformed value must not crash
            # the handler before the err-frame machinery is armed
            attrs["migration.attempt"] = int(ctx.metadata["migration_attempt"])
        except (KeyError, TypeError, ValueError):
            pass
        span_cm = tracing.span(
            f"rpc {path}", parent=ctx.metadata.get("traceparent"),
            kind=2, attributes=attrs,
        )
        try:
            with span_cm as sp:
                tracing.child_traceparent(ctx.metadata, sp)
                async for item in engine.generate(frame.get("payload"), ctx):
                    if ctx.is_killed:
                        raise CancellationError(rid)
                    await send({"t": "item", "id": rid, "data": item})
            await send({"t": "done", "id": rid})
        except CancellationError:
            try:
                await send({"t": "err", "id": rid, "msg": "killed", "code": "cancelled"})
            except ConnectionError:
                pass
        except ConnectionError:
            ctx.kill()
        except Exception as e:  # engine fault → error frame
            log.exception("engine error on %s", path)
            # preserve a handler-supplied error code (e.g. a remote router
            # service re-raising cannot_connect): flattening everything to
            # "engine" would break the caller's migration / affinity-
            # failover classification across a service hop
            code = getattr(e, "code", None) or "engine"
            try:
                await send({"t": "err", "id": rid, "msg": str(e), "code": code})
            except ConnectionError:
                pass
        finally:
            self._active.pop(rid, None)
            conn_ctxs.pop(rid, None)


class _MuxConn:
    """One TCP connection carrying many concurrent id-tagged streams. A
    single reader task demuxes inbound frames into per-stream queues; the
    shared writer is serialized by a lock. Death (EOF, reset, oversized
    frame) fans a disconnect sentinel out to every open stream."""

    _DISCONNECT = object()

    # Per-stream inbound buffer, in frames. Bounded so one slow consumer
    # (or a multi-GB chunked KV pull) applies TCP backpressure through the
    # shared socket instead of materializing in client memory — the cost is
    # head-of-line blocking on that conn once a stream is 16 frames behind,
    # which is the standard mux trade (HTTP/2 flow control plays this role).
    STREAM_BUF_FRAMES = 16

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 gen: int = 0):
        self._reader = reader
        self._writer = writer
        self._wlock = asyncio.Lock()
        self._streams: Dict[str, asyncio.Queue] = {}
        self.closed = False
        self.gen = gen  # pool dial generation (stale-retry bookkeeping)
        self._reader_task = asyncio.create_task(self._read_loop())

    @property
    def n_streams(self) -> int:
        return len(self._streams)

    def open_stream(self, rid: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=self.STREAM_BUF_FRAMES)
        self._streams[rid] = q
        return q

    def close_stream(self, rid: str) -> None:
        q = self._streams.pop(rid, None)
        # drain so a reader blocked on a full queue for this (now dead)
        # stream wakes up instead of wedging the whole connection
        while q is not None:
            try:
                q.get_nowait()
            except asyncio.QueueEmpty:
                break

    async def send(self, obj: Dict[str, Any]) -> None:
        async with self._wlock:
            await _send_frame(self._writer, obj)

    async def _read_loop(self) -> None:
        async def on_frame(frame: Dict[str, Any]) -> None:
            q = self._streams.get(frame.get("id"))
            # frames for unknown ids (stream abandoned client-side
            # before the server noticed the cancel) are dropped
            if q is not None:
                await q.put(frame)

        try:
            if await _native_codec_on():
                from dynamo_tpu.native.frame_codec import NativeSplitter

                await _bulk_frames(self._reader, NativeSplitter(), on_frame)
            else:
                while True:
                    frame = await _recv_frame(self._reader)
                    if frame is None:
                        break
                    await on_frame(frame)
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass  # peer went away: close() below poisons pending streams
        except Exception:
            log.debug("connection reader failed", exc_info=True)
        finally:
            self.close()

    @classmethod
    def _push_sentinel(cls, q: asyncio.Queue) -> None:
        try:
            q.put_nowait(cls._DISCONNECT)
        except asyncio.QueueFull:
            try:
                q.get_nowait()
            except asyncio.QueueEmpty:
                pass
            q.put_nowait(cls._DISCONNECT)

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._writer.close()
        for q in self._streams.values():
            self._push_sentinel(q)

    def shutdown(self) -> None:
        self.close()
        self._reader_task.cancel()


class _ConnPool:
    """Per-address set of multiplexed connections. Streams land on the
    live connection with the fewest open streams; a new connection is
    dialed only when every existing one is at `streams_per_conn`, up to
    `max_conns` (beyond that, streams keep stacking on the least-loaded
    socket — they're cheap, sockets aren't)."""

    def __init__(
        self,
        max_conns: int = 8,
        streams_per_conn: int = 32,
        connect_timeout: float = 5.0,
    ):
        self._conns: Dict[str, list] = {}
        self._dial_locks: Dict[str, asyncio.Lock] = {}
        self._gen: Dict[str, int] = {}  # per-address dial generation
        self.max_conns = max_conns
        self.streams_per_conn = streams_per_conn
        self.connect_timeout = connect_timeout

    async def _dial(self, address: str) -> _MuxConn:
        gen = self._gen.get(address, 0) + 1
        if address.startswith("inproc://"):
            # one-process fleet fast path: no socket, no listener — the
            # "dial" is a registry lookup. Fault hooks emulate the network
            # the sockets would have provided (partition → connect refusal).
            hook = _INPROC_FAULT_HOOK
            if hook is not None:
                try:
                    await hook("connect", address)
                except ConnectionResetError as e:
                    raise RequestPlaneError(
                        f"cannot connect to {address}: {e}",
                        code="cannot_connect",
                    )
            ep = _INPROC_ENDPOINTS.get(address)
            if ep is None:
                raise RequestPlaneError(
                    f"cannot connect to {address}: endpoint gone",
                    code="cannot_connect",
                )
            self._gen[address] = gen
            conn = _InprocMuxConn(address, ep, gen=gen)
            self._conns.setdefault(address, []).append(conn)
            return conn
        if address.startswith("nats://"):
            # brokered request plane: nats://host:port/rpc.<id> — one
            # broker connection per pooled "conn", same mux surface
            url, _, subject = address.rpartition("/")
            conn = _NatsMuxConn(url, subject, gen=gen)
            try:
                await asyncio.wait_for(conn.start(), self.connect_timeout)
            except (OSError, asyncio.TimeoutError) as e:
                conn.shutdown()
                raise RequestPlaneError(
                    f"cannot connect to {address}: {e}", code="cannot_connect"
                )
            self._gen[address] = gen
            self._conns.setdefault(address, []).append(conn)
            return conn
        host, port = address.rsplit(":", 1)
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)), self.connect_timeout
            )
        except (OSError, asyncio.TimeoutError) as e:
            raise RequestPlaneError(f"cannot connect to {address}: {e}", code="cannot_connect")
        self._gen[address] = gen
        conn = _MuxConn(reader, writer, gen=gen)
        self._conns.setdefault(address, []).append(conn)
        return conn

    def _best_live(self, address: str, gen_floor: int = -1) -> Optional[_MuxConn]:
        conns = self._conns.get(address, [])
        live = [c for c in conns if not c.closed]
        if len(live) != len(conns):
            self._conns[address] = live
        cands = [c for c in live if c.gen > gen_floor]
        if not cands:
            return None
        best = min(cands, key=lambda c: c.n_streams)
        if best.n_streams < self.streams_per_conn or len(live) >= self.max_conns:
            return best
        return None

    async def acquire(
        self, address: str, rid: str, after: Optional[_MuxConn] = None
    ) -> Tuple[_MuxConn, asyncio.Queue, bool]:
        """Returns (conn, stream queue, pooled) with stream `rid` already
        registered — registration happens HERE so concurrent acquires see
        each other's load and don't all stampede into new sockets.

        `after` marks a stale-retry (the given conn just died, e.g. the
        server restarted under a pooled socket): only connections dialed
        AFTER it qualify for reuse, so the retry is guaranteed a
        post-restart socket — but N simultaneous retries still share a
        handful of new dials instead of opening N (the dial lock
        serializes, and waiters land on the winner's socket)."""
        gen_floor = after.gen if after is not None else -1
        best = self._best_live(address, gen_floor)
        if best is not None:
            return best, best.open_stream(rid), after is None
        # dials are serialized per address, and capacity is re-checked
        # under the lock: waiters queued behind the winning dial land on
        # its socket instead of each opening their own
        lock = self._dial_locks.setdefault(address, asyncio.Lock())
        async with lock:
            best = self._best_live(address, gen_floor)
            if best is not None:
                return best, best.open_stream(rid), after is None
            conn = await self._dial(address)
            return conn, conn.open_stream(rid), False

    def close(self) -> None:
        for conns in self._conns.values():
            for c in conns:
                c.shutdown()
        self._conns.clear()


class RemoteEngine:
    """Client side: an AsyncEngine whose generate() pushes the request to a
    remote instance over TCP and yields the streamed response items."""

    def __init__(self, pool: _ConnPool, address: str, endpoint_path: str):
        self._pool = pool
        self.address = address
        self.endpoint_path = endpoint_path

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        """Stream the remote response. If a *pooled* connection turns out
        stale (server restarted since it was dialed) and nothing has been
        yielded yet, retry once on a fresh connection."""
        conn, q, pooled = await self._pool.acquire(self.address, context.id)
        yielded = False
        while True:
            try:
                async for item in self._stream_once(conn, q, request, context):
                    yielded = True
                    yield item
                return
            except RequestPlaneError as e:
                if pooled and not yielded and e.code == "disconnected":
                    conn, q, pooled = await self._pool.acquire(
                        self.address, context.id, after=conn
                    )
                    continue
                raise

    async def _stream_once(
        self, conn: _MuxConn, q: asyncio.Queue, request: Any, context: Context
    ) -> AsyncIterator[Any]:
        rid = context.id
        canceller: Optional[asyncio.Task] = None
        finished = False
        try:
            await conn.send(
                {
                    "t": "req",
                    "id": rid,
                    "endpoint": self.endpoint_path,
                    "headers": context.to_headers(),
                    "payload": request,
                },
            )
            # propagate stop/kill to the server even while blocked on recv
            async def _forward_cancel():
                await context.wait_stopped()
                try:
                    kind = "kill" if context.is_killed else "cancel"
                    await conn.send({"t": kind, "id": rid})
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass

            canceller = asyncio.create_task(_forward_cancel())
            while True:
                frame = await q.get()
                if frame is _MuxConn._DISCONNECT:
                    raise RequestPlaneError(
                        f"disconnected from {self.address}", code="disconnected"
                    )
                t = frame.get("t")
                if t == "item":
                    yield frame["data"]
                elif t == "done":
                    finished = True
                    return
                elif t == "err":
                    finished = True  # server already ended this stream
                    code = frame.get("code", "engine")
                    raise RequestPlaneError(frame.get("msg", "remote error"), code=code)
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            conn.close()  # writer failed mid-frame: poison the whole conn
            finished = True
            raise RequestPlaneError(f"connection lost to {self.address}: {e}", code="disconnected")
        finally:
            if canceller is not None:
                canceller.cancel()
            conn.close_stream(rid)
            if not finished and not conn.closed:
                # stream abandoned mid-flight (consumer stopped iterating):
                # the shared socket stays open, so tell the server to stop
                # instead of letting it stream into the void (best-effort —
                # the conn may die first, which achieves the same thing)
                async def _bg_kill():
                    try:
                        await conn.send({"t": "kill", "id": rid})
                    except Exception:
                        log.debug("kill for abandoned stream %s not "
                                  "delivered", rid, exc_info=True)

                spawn_tracked(_bg_kill(), logger=log)


class RouterMode:
    ROUND_ROBIN = "round_robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"  # handled one level up by KvPushRouter
    P2C = "p2c"  # power-of-two-choices by load
    LEAST_LOADED = "least_loaded"
    # weighted by published device capacity over current load (reference
    # push_router.rs:193 DeviceAwareWeighted); on TPU the natural weight is
    # the worker's slice size (chips spanned), published as instance
    # metadata `device_weight`
    DEVICE_AWARE = "device_aware"


class PushRouter:
    """Client-side fan-out over the live instance set of an endpoint
    (reference egress/push_router.rs:184-194 RouterMode{RoundRobin, Random,
    PowerOfTwoChoices, KV, Direct, LeastLoaded, ...}). Instance set is
    maintained by a discovery watch.

    Load-aware modes (p2c / least_loaded) rank instances by the router's
    own count of outstanding requests per instance; a worker-published
    load signal (FPM kv utilization, queue depth) can override it via
    update_load() — when present it wins, since it sees load from OTHER
    frontends too."""

    # how long a transport-failed instance is avoided. Discovery lease
    # expiry (seconds) is the authoritative removal; this cooldown only
    # bridges the gap so migration retries don't re-pick a corpse and
    # exhaust their budget before the lease lapses.
    SICK_COOLDOWN_S = 5.0
    # worker-published load goes stale after this long without an update:
    # a crashed/wedged worker must not pin routing with its last value
    # (a frozen low load would attract every request; a frozen high one
    # would starve a recovered worker) — fall back to the local
    # in-flight count until it publishes again.
    EXT_LOAD_TTL_S = 15.0

    # transport failures that put an instance into the failure cache:
    # unreachable / cut / timed-out / draining replicas are all equally
    # poor candidates for the migrating request's retry
    SICK_CODES = ("cannot_connect", "disconnected", "connection_timeout",
                  "draining")

    def __init__(
        self,
        endpoint_path: str,
        mode: str = RouterMode.ROUND_ROBIN,
        sick_cooldown_s: Optional[float] = None,
    ):
        self.endpoint_path = endpoint_path
        self.mode = mode
        self.sick_cooldown_s = (
            sick_cooldown_s if sick_cooldown_s is not None
            else self.SICK_COOLDOWN_S
        )
        self._pool = _ConnPool()
        self._instances: Dict[int, str] = {}  # instance_id -> address
        self._rr = 0
        self._inflight: Dict[int, int] = {}  # instance_id -> outstanding reqs
        self._ext_load: Dict[int, float] = {}  # worker-published load
        self._ext_load_ts: Dict[int, float] = {}  # last update (monotonic)
        self._weights: Dict[int, float] = {}  # published device capacity
        self._sick: Dict[int, float] = {}  # instance_id -> retry-after
        # routing decision audit ring (per-router instance, DYN-R001),
        # queried by the frontend's /debug/routing
        from dynamo_tpu.runtime.fleet_observer import RoutingAudit

        self.audit = RoutingAudit()

    def update_instance(self, instance_id: int, address: Optional[str]) -> None:
        if address is None:
            self._instances.pop(instance_id, None)
            self._inflight.pop(instance_id, None)
            self._ext_load.pop(instance_id, None)
            self._ext_load_ts.pop(instance_id, None)
            self._weights.pop(instance_id, None)
            self._sick.pop(instance_id, None)
        else:
            self._instances[instance_id] = address

    def mark_sick(self, instance_id: int, cooldown: Optional[float] = None) -> None:
        """Record a transport failure: selection avoids this instance for
        `cooldown` seconds (unless nothing else is available)."""
        import time as _time

        self._sick[instance_id] = _time.monotonic() + (
            cooldown if cooldown is not None else self.sick_cooldown_s
        )

    def sick_instances(self) -> set:
        """Instances currently in their failure cooldown."""
        import time as _time

        now = _time.monotonic()
        for iid, until in list(self._sick.items()):
            if until <= now:
                del self._sick[iid]
        return set(self._sick)

    def update_weight(self, instance_id: int, weight: Optional[float]) -> None:
        """Feed a published device-capacity weight (metadata
        `device_weight`; None clears → default 1.0)."""
        if weight is None:
            self._weights.pop(instance_id, None)
        else:
            self._weights[instance_id] = max(0.0, float(weight))

    def update_load(self, instance_id: int, load: Optional[float]) -> None:
        """Feed a worker-published load value (None clears it, falling back
        to the local outstanding-request count)."""
        import time as _time

        if load is None:
            self._ext_load.pop(instance_id, None)
            self._ext_load_ts.pop(instance_id, None)
        else:
            self._ext_load[instance_id] = load
            self._ext_load_ts[instance_id] = _time.monotonic()

    def _fresh_ext(self, instance_id: int, now: Optional[float] = None):
        """The published load iff it is younger than EXT_LOAD_TTL_S;
        lazily expires stale entries (mark_sick/sick_instances idiom)."""
        ext = self._ext_load.get(instance_id)
        if ext is None:
            return None
        import time as _time

        if (now if now is not None else _time.monotonic()) - \
                self._ext_load_ts.get(instance_id, 0.0) > self.EXT_LOAD_TTL_S:
            self._ext_load.pop(instance_id, None)
            self._ext_load_ts.pop(instance_id, None)
            return None
        return ext

    def load_of(self, instance_id: int) -> float:
        ext = self._fresh_ext(instance_id)
        return ext if ext is not None else float(self._inflight.get(instance_id, 0))

    def _load_key(self, ids):
        """Comparable load metric across `ids`: worker-published load only
        when EVERY candidate has published one RECENTLY — mixing published
        utilization (0..1) with local in-flight counts (0..N) would
        systematically misroute toward whichever instance happens to have
        the external signal, and a stale publication (crashed or wedged
        worker) would pin routing with its last value."""
        import time as _time

        now = _time.monotonic()
        ext = {i: self._fresh_ext(i, now) for i in ids}
        if all(v is not None for v in ext.values()):
            return ext.__getitem__
        return lambda i: float(self._inflight.get(i, 0))

    @property
    def instance_ids(self) -> list:
        return list(self._instances)

    def _pick(
        self, instance_id: Optional[int] = None, allowed=None
    ) -> Tuple[int, str]:
        """`allowed`: optional instance-id collection restricting selection
        (LoRA-filtered routing — only replicas holding the request's
        adapter are candidates; reference two-stage filter-then-cost
        routing, lib/llm entrypoint/input/common.rs:154-185)."""
        if not self._instances:
            raise RequestPlaneError(
                f"no instances for {self.endpoint_path}", code="no_instances"
            )
        if instance_id is not None:
            if allowed is not None and instance_id not in allowed:
                # an explicit pin (session affinity / direct) to a replica
                # outside the restriction fails loudly — silently ignoring
                # the filter would land the request on a worker without
                # the adapter
                raise RequestPlaneError(
                    f"instance {instance_id:x} excluded by the adapter "
                    "restriction", code="cannot_connect",
                )
            addr = self._instances.get(instance_id)
            if addr is None:
                raise RequestPlaneError(
                    f"instance {instance_id:x} not found", code="cannot_connect"
                )
            return instance_id, addr
        if self.mode == RouterMode.DIRECT:
            raise RequestPlaneError(
                "direct routing mode requires a target instance_id", code="no_target"
            )
        ids = sorted(
            self._instances if allowed is None
            else (i for i in self._instances if i in allowed)
        )
        if not ids:
            raise RequestPlaneError(
                f"no instances for {self.endpoint_path} satisfy the "
                "adapter restriction", code="no_instances",
            )
        sick = self.sick_instances()
        if sick:
            healthy = [i for i in ids if i not in sick]
            if healthy:  # all-sick: keep trying rather than failing hard
                ids = healthy
        if self.mode == RouterMode.RANDOM:
            iid = random.choice(ids)
        elif self.mode == RouterMode.P2C:
            # two independent uniform picks, keep the less loaded: load
            # awareness with O(1) state reads and provably exponential
            # improvement over random in the balls-in-bins sense
            load = self._load_key(ids)
            a, b = random.choice(ids), random.choice(ids)
            iid = a if load(a) <= load(b) else b
        elif self.mode == RouterMode.DEVICE_AWARE:
            # weighted draw by capacity / (1 + load): a worker spanning a
            # 4-chip slice absorbs ~4x a single-chip worker's share when
            # idle, degrading toward load-balance as queues build. Workers
            # that published no weight count as capacity 1.0.
            load = self._load_key(ids)
            ws = [
                self._weights.get(i, 1.0) / (1.0 + max(0.0, float(load(i))))
                for i in ids
            ]
            total = sum(ws)
            if total <= 0.0:
                iid = random.choice(ids)
            else:
                r = random.random() * total
                iid = ids[-1]
                for i, w in zip(ids, ws):
                    r -= w
                    if r <= 0.0:
                        iid = i
                        break
        elif self.mode == RouterMode.LEAST_LOADED:
            # round-robin tiebreak so equal-load instances share work
            # instead of the lowest id absorbing every burst
            self._rr += 1
            n = len(ids)
            iid = min(
                (ids[(self._rr + i) % n] for i in range(n)),
                key=self._load_key(ids),
            )
        else:  # round robin default
            iid = ids[self._rr % len(ids)]
            self._rr += 1
        return iid, self._instances[iid]

    def engine_for(self, instance_id: Optional[int] = None) -> RemoteEngine:
        _, addr = self._pick(instance_id)
        return RemoteEngine(self._pool, addr, self.endpoint_path)

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        t_route = _time.monotonic()
        # route hop span: covers the pick + audit; downstream rpc spans
        # child off it (child_traceparent), so the merged timeline reads
        # frontend -> route -> worker with no gap
        with tracing.span(
            "route.push", parent=context.metadata.get("traceparent"),
        ) as rspan:
            allowed = context.metadata.get("allowed_instances")
            iid, addr = self._pick(
                context.metadata.get("target_instance"),
                set(allowed) if allowed is not None else None,
            )
            # report the choice so wrappers (session affinity) can pin to it
            context.metadata["routed_instance"] = iid
            # latency spine: router-hop pick cost, accumulated across
            # migration retries (the metadata dict rides to the worker)
            ph = context.metadata.setdefault("phases", {})
            ph["route_s"] = (ph.get("route_s", 0.0)
                             + (_time.monotonic() - t_route))
            # routing decision audit: candidate loads as the picker saw
            # them, joinable to the phase spine by rid (/debug/routing?rid=)
            sick = set(self._sick)
            target = context.metadata.get("target_instance")
            self.audit.record(
                context.id, self.mode, iid,
                candidates=[
                    {
                        "instance": i,
                        "load": self.load_of(i),
                        "weight": self._weights.get(i, 1.0),
                        "sick": i in sick,
                        "chosen": i == iid,
                    }
                    for i in sorted(
                        self._instances if allowed is None
                        else (j for j in self._instances if j in set(allowed))
                    )
                ],
                pinned=target is not None,
            )
            rspan.set_attribute("request.id", context.id)
            rspan.set_attribute("router.mode", str(self.mode))
            rspan.set_attribute("routed.instance", iid)
            tracing.child_traceparent(context.metadata, rspan)
        engine = RemoteEngine(self._pool, addr, self.endpoint_path)
        self._inflight[iid] = self._inflight.get(iid, 0) + 1
        try:
            async for item in engine.generate(request, context):
                yield item
        except RequestPlaneError as e:
            if e.code in self.SICK_CODES:
                # dead/unreachable replica: cool it down so the migration
                # retry lands on a healthy one instead of this corpse
                self.mark_sick(iid)
            raise
        finally:
            left = self._inflight.get(iid, 1) - 1
            if left > 0:
                self._inflight[iid] = left
            else:
                self._inflight.pop(iid, None)

    def close(self) -> None:
        self._pool.close()


class NatsPushEndpoint(PushEndpoint):
    """Request-plane mode over the NATS broker — `RequestPlaneMode::Nats`
    (reference lib/runtime/src/distributed.rs:773-779). Same msgpack
    frames and stream semantics as the TCP plane; the transport is broker
    subjects instead of sockets: the server subscribes to one rpc.<id>
    subject, clients attach a `reply` inbox subject per request and
    responses stream there. The advertised address is self-contained:
    nats://host:port/rpc.<id> (clients parse broker + subject out of it).

    Delivery is NATS-core at-most-once: a broker restart drops in-flight
    streams, which surfaces as `disconnected` — exactly the migratable
    error class the TCP plane produces on a cut socket, so frontend
    Migration replays the request transparently."""

    def __init__(self, nats_url: Optional[str] = None):
        super().__init__()
        import os as _os
        import uuid as _uuid

        from dynamo_tpu.runtime.nats_plane import DEFAULT_URL

        self.nats_url = nats_url or _os.environ.get("DYN_NATS_URL", DEFAULT_URL)
        self.subject = f"rpc.{_uuid.uuid4().hex[:12]}"
        self._client = None
        self._dispatch_task: Optional[asyncio.Task] = None
        self._nats_ctxs: Dict[str, Context] = {}

    @property
    def address(self) -> str:
        return f"{self.nats_url}/{self.subject}"

    async def start(self) -> str:
        from dynamo_tpu.runtime.nats_plane import NatsClient

        self._client = NatsClient(self.nats_url)
        await self._client.subscribe(self.subject)
        self._dispatch_task = asyncio.create_task(self._dispatch())
        return self.address

    async def _dispatch(self) -> None:
        tasks: set = set()
        client = self._client
        try:
            while True:
                # endpoint dispatch loop: waiting forever for the next
                # request is the contract; broker death yields None
                item = await client.next_msg()  # dynlint: disable=DYN-R003
                if item is None:
                    if client._closed:
                        return
                    # broker dropped: redial until it returns (the SUB is
                    # re-established by ensure_connected's re-SUB replay)
                    while not client._closed:
                        await asyncio.sleep(0.2)
                        try:
                            await client.ensure_connected()
                            break
                        except (ConnectionError, OSError):
                            continue
                    continue
                _, raw = item
                try:
                    frame = msgpack.unpackb(raw, raw=False)
                except Exception:
                    continue  # malformed wire input must not kill dispatch
                t = frame.get("t")
                if t == "req":
                    reply = frame.get("reply")
                    if not reply:
                        continue

                    async def send(obj: Dict[str, Any], _r=reply) -> None:
                        await client.publish(
                            _r, msgpack.packb(obj, use_bin_type=True)
                        )

                    task = asyncio.create_task(
                        self._handle_request(frame, send, self._nats_ctxs)
                    )
                    tasks.add(task)
                    task.add_done_callback(tasks.discard)
                elif t == "cancel":
                    ctx = self._nats_ctxs.get(frame.get("id"))
                    if ctx is not None:
                        ctx.stop_generating()
                elif t == "kill":
                    ctx = self._nats_ctxs.get(frame.get("id"))
                    if ctx is not None:
                        ctx.kill()
        finally:
            for task in tasks:
                task.cancel()

    async def stop(self, drain_timeout: float = 30.0) -> None:
        self._draining = True
        deadline = asyncio.get_event_loop().time() + drain_timeout
        while self._active and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)
        for ctx in list(self._active.values()):
            ctx.kill()
        if self._dispatch_task is not None:
            self._dispatch_task.cancel()
        if self._client is not None:
            await self._client.close()


class _NatsMuxConn:
    """Client half of the NATS request plane: the _MuxConn surface
    (open/close_stream, send, closed/gen/n_streams) over one broker
    connection. Requests go to the server's rpc subject with this conn's
    private inbox as `reply`; a reader task demuxes inbox frames into the
    per-stream queues. Queues are unbounded — a broker provides no
    per-stream backpressure, and blocking the shared demux on one slow
    stream would stall every other (the TCP plane gets this from the
    socket; here at-most-once semantics bound the exposure)."""

    _DISCONNECT = _MuxConn._DISCONNECT

    def __init__(self, url: str, subject: str, gen: int = 0):
        import uuid as _uuid

        from dynamo_tpu.runtime.nats_plane import NatsClient

        self._subject = subject
        self._client = NatsClient(url)
        self._inbox = f"_INBOX.{_uuid.uuid4().hex[:12]}"
        self._streams: Dict[str, asyncio.Queue] = {}
        self.closed = False
        self.gen = gen
        self._reader_task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        await self._client.subscribe(self._inbox)
        self._reader_task = asyncio.create_task(self._read_loop())

    @property
    def n_streams(self) -> int:
        return len(self._streams)

    def open_stream(self, rid: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue()
        self._streams[rid] = q
        return q

    def close_stream(self, rid: str) -> None:
        self._streams.pop(rid, None)

    async def send(self, obj: Dict[str, Any]) -> None:
        if obj.get("t") == "req":
            obj = dict(obj)
            obj["reply"] = self._inbox
        try:
            await self._client.publish(
                self._subject, msgpack.packb(obj, use_bin_type=True)
            )
        except (ConnectionError, OSError):
            self.close()
            raise

    async def _read_loop(self) -> None:
        try:
            while True:
                # mux reader loop: idle conns legitimately wait forever;
                # broker death yields None and fans out disconnect below
                item = await self._client.next_msg()  # dynlint: disable=DYN-R003
                if item is None:
                    # broker dropped: in-flight streams cannot be resumed
                    # (core NATS replays nothing) — fan disconnect so the
                    # pool retires this conn and callers migrate/retry
                    break
                _, raw = item
                try:
                    frame = msgpack.unpackb(raw, raw=False)
                except Exception:
                    continue
                q = self._streams.get(frame.get("id"))
                if q is not None:
                    q.put_nowait(frame)
        except asyncio.CancelledError:
            pass
        finally:
            self.close()

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        for q in self._streams.values():
            q.put_nowait(self._DISCONNECT)
        self._client.close_nowait()

    def shutdown(self) -> None:
        self.close()
        if self._reader_task is not None:
            self._reader_task.cancel()


# ---------------------------------------------------------------------------
# In-proc request plane — `RequestPlaneMode::Inproc`
# ---------------------------------------------------------------------------
# A 500-worker fleet simulator cannot afford 500 TCP listeners plus N x M
# mux sockets in one process (fd limits, accept-loop wakeups, kernel
# buffers). The in-proc plane keeps every request-plane semantic — the
# same frames, the same per-stream bounded queues, the same disconnect /
# draining / cannot_connect error codes migration classifies on — but the
# "socket" is a registry lookup and the "wire" is a msgpack round-trip.
# Fault hooks stand in for the network, so a sim can cut, delay, or
# partition any worker's plane the way a real network would.

_INPROC_ENDPOINTS: Dict[str, "InprocPushEndpoint"] = {}
_INPROC_NEXT = [0]
# async hook(direction: "connect"|"send"|"recv", address) installed by the
# fleet simulator; may sleep (latency) or raise ConnectionResetError
# (partition / cut). None in production.
_INPROC_FAULT_HOOK = None


def set_inproc_fault_hook(hook) -> None:
    """Install (or clear, with None) the fault-injection hook applied to
    every in-proc plane edge. Sim-only."""
    global _INPROC_FAULT_HOOK
    _INPROC_FAULT_HOOK = hook


def reset_inproc() -> None:
    """Test/sim helper: drop every registered in-proc endpoint + hook."""
    _INPROC_ENDPOINTS.clear()
    set_inproc_fault_hook(None)


def _wire(obj: Dict[str, Any]) -> Dict[str, Any]:
    """msgpack round-trip: the in-proc plane keeps TCP-plane serialization
    semantics (tuples become lists, payloads are copies, non-serializable
    values fail here) so a sim fleet exercises the same wire shapes real
    sockets would — and a frontend can never share mutable state with a
    worker by accident."""
    return msgpack.unpackb(msgpack.packb(obj, use_bin_type=True), raw=False)


class InprocPushEndpoint(PushEndpoint):
    """Request-plane server for one-process fleets: the same
    `_handle_request` machinery as the TCP plane, addressed by an
    `inproc://` registry key instead of a socket. `abort()` is the
    SIGKILL twin — the endpoint vanishes without a goodbye and every
    attached client conn sees a disconnect, exactly like a cut socket."""

    def __init__(self):
        super().__init__()
        _INPROC_NEXT[0] += 1
        self._address = f"inproc://rp-{_INPROC_NEXT[0]}"
        self._inproc_conns: set = set()

    @property
    def address(self) -> str:
        return self._address

    # registry writes live in sync helpers: they are atomic with respect
    # to the event loop (no await can interleave), which is the invariant
    # that makes the lock-free registry safe
    def _register(self) -> None:
        _INPROC_ENDPOINTS[self._address] = self

    def _deregister(self) -> None:
        _INPROC_ENDPOINTS.pop(self._address, None)

    async def start(self) -> str:
        self._register()
        return self._address

    async def stop(self, drain_timeout: float = 30.0) -> None:
        """Graceful: deregister (new dials fail), drain in-flight, kill
        stragglers, then cut surviving conns."""
        self._draining = True
        self._deregister()
        deadline = asyncio.get_event_loop().time() + drain_timeout
        while self._active and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)
        for ctx in list(self._active.values()):
            ctx.kill()
        for conn in list(self._inproc_conns):
            conn.close()

    def abort(self) -> None:
        """Hard-kill (sim SIGKILL): no drain, no error frames — conns are
        cut FIRST so in-flight handlers' sends fail like a dead socket,
        then their contexts are killed. Clients observe `disconnected`,
        the migratable code a real worker crash produces."""
        self._draining = True
        self._deregister()
        for conn in list(self._inproc_conns):
            conn.close()
        for ctx in list(self._active.values()):
            ctx.kill()


class _InprocMuxConn:
    """Client half of the in-proc plane: the `_MuxConn` surface
    (open/close_stream, send, closed/gen/n_streams) where "the socket" is
    a direct `_handle_request` task on the server endpoint. Per-stream
    queues stay bounded, so backpressure semantics match TCP (a slow
    consumer stalls its handler's send, not the whole process)."""

    _DISCONNECT = _MuxConn._DISCONNECT
    STREAM_BUF_FRAMES = _MuxConn.STREAM_BUF_FRAMES

    def __init__(self, address: str, endpoint: InprocPushEndpoint,
                 gen: int = 0):
        self.address = address
        self.gen = gen
        self.closed = False
        self._ep = endpoint
        self._streams: Dict[str, asyncio.Queue] = {}
        self._ctxs: Dict[str, Context] = {}
        self._tasks: set = set()
        endpoint._inproc_conns.add(self)

    @property
    def n_streams(self) -> int:
        return len(self._streams)

    def open_stream(self, rid: str) -> asyncio.Queue:
        q: asyncio.Queue = asyncio.Queue(maxsize=self.STREAM_BUF_FRAMES)
        self._streams[rid] = q
        return q

    def close_stream(self, rid: str) -> None:
        q = self._streams.pop(rid, None)
        # drain so a handler blocked on this (now dead) stream's full
        # queue wakes up instead of wedging (same contract as _MuxConn)
        while q is not None:
            try:
                q.get_nowait()
            except asyncio.QueueEmpty:
                break

    async def _fault(self, direction: str) -> None:
        hook = _INPROC_FAULT_HOOK
        if hook is None:
            return
        try:
            await hook(direction, self.address)
        except ConnectionResetError:
            # a partition cuts the whole "socket", not one frame: fan
            # disconnect to every stream so nothing hangs waiting on a
            # response that can never arrive
            self.close()
            raise

    async def send(self, obj: Dict[str, Any]) -> None:
        if self.closed:
            raise ConnectionResetError(
                f"in-proc conn to {self.address} closed")
        await self._fault("send")
        t = obj.get("t")
        if t == "req":
            if _INPROC_ENDPOINTS.get(self.address) is not self._ep:
                # endpoint vanished or restarted under us: dead socket
                self.close()
                raise ConnectionResetError(f"{self.address} is gone")
            frame = _wire(obj)
            task = asyncio.create_task(
                self._ep._handle_request(frame, self._respond, self._ctxs)
            )
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)
        elif t == "cancel":
            ctx = self._ctxs.get(obj.get("id"))
            if ctx is not None:
                ctx.stop_generating()
        elif t == "kill":
            ctx = self._ctxs.get(obj.get("id"))
            if ctx is not None:
                ctx.kill()

    async def _respond(self, obj: Dict[str, Any]) -> None:
        """Server→client frame delivery (the handler's `send`)."""
        await self._fault("recv")
        if self.closed:
            raise ConnectionResetError(
                f"in-proc conn to {self.address} closed")
        q = self._streams.get(obj.get("id"))
        if q is not None:
            # frames for unknown ids (stream abandoned client-side) drop,
            # matching the TCP demux loop
            await q.put(_wire(obj))

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        self._ep._inproc_conns.discard(self)
        for q in self._streams.values():
            _MuxConn._push_sentinel(q)
        # the client side of this conn is gone: kill its in-flight server
        # contexts the way a broken socket's handler teardown would
        for ctx in list(self._ctxs.values()):
            ctx.kill()

    def shutdown(self) -> None:
        self.close()
