"""TCP/msgpack request plane (analog of reference
lib/runtime/src/pipeline/network/: PushEndpoint ingress, PushRouter egress,
two-part msgpack codec, connection pooling).

Frames are length-prefixed msgpack maps:
  client→server: {"t":"req","id",...,"endpoint","headers","payload"}
                 {"t":"cancel","id"}       (graceful stop_generating)
                 {"t":"kill","id"}         (hard kill)
  server→client: {"t":"item","id","data"} ...  {"t":"done","id"}
                 {"t":"err","id","msg","code"}

One in-flight request per pooled connection (the reference pools TCP
connections similarly; multiplexing is an optimization for a later round).
"""

from __future__ import annotations

import asyncio
import logging
import random
import struct
from typing import Any, AsyncIterator, Dict, Optional, Tuple

import msgpack

from dynamo_tpu.runtime.context import CancellationError, Context
from dynamo_tpu.runtime.engine import AsyncEngine

log = logging.getLogger("dynamo_tpu.request_plane")

_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024


class RequestPlaneError(Exception):
    """Transport-level failure; carries a code used by migration
    classification (reference migration.rs:60-68)."""

    def __init__(self, msg: str, code: str = "internal"):
        super().__init__(msg)
        self.code = code


async def _send_frame(writer: asyncio.StreamWriter, obj: Dict[str, Any]) -> None:
    body = msgpack.packb(obj, use_bin_type=True)
    writer.write(_LEN.pack(len(body)) + body)
    await writer.drain()


async def _recv_frame(reader: asyncio.StreamReader) -> Optional[Dict[str, Any]]:
    try:
        hdr = await reader.readexactly(4)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise RequestPlaneError(f"frame too large: {n}", code="protocol")
    try:
        body = await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError):
        return None
    return msgpack.unpackb(body, raw=False)


class PushEndpoint:
    """Server side: serves one AsyncEngine per endpoint path on a TCP port
    (reference ingress/push_endpoint.rs:21,36). One server instance can host
    many endpoints (the reference's NetworkManager role)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._engines: Dict[str, AsyncEngine] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._active: Dict[str, Context] = {}
        self._conns: set = set()  # open connection writers (for shutdown)
        self._draining = False

    def add_endpoint(self, path: str, engine: AsyncEngine) -> None:
        self._engines[path] = engine

    def remove_endpoint(self, path: str) -> None:
        self._engines.pop(path, None)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def active_requests(self) -> int:
        return len(self._active)

    async def start(self) -> str:
        self._server = await asyncio.start_server(self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.address

    async def stop(self, drain_timeout: float = 30.0) -> None:
        """Graceful shutdown: refuse new requests, wait for in-flight to
        drain, then kill stragglers (reference graceful_shutdown.rs)."""
        self._draining = True
        if self._server is not None:
            self._server.close()
        deadline = asyncio.get_event_loop().time() + drain_timeout
        while self._active and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.05)
        for ctx in list(self._active.values()):
            ctx.kill()
        # close lingering (e.g. idle pooled) connections, else wait_closed()
        # blocks on parked connection handlers (py>=3.12.1 semantics)
        for w in list(self._conns):
            w.close()
        if self._server is not None:
            await self._server.wait_closed()

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        """Single reader loop per connection: `req` frames spawn response
        tasks; `cancel`/`kill` frames route to the matching in-flight context
        (avoids two tasks racing on one reader)."""
        conn_ctxs: Dict[str, Context] = {}
        tasks: set = set()
        wlock = asyncio.Lock()
        self._conns.add(writer)
        try:
            while True:
                frame = await _recv_frame(reader)
                if frame is None:
                    return
                t = frame.get("t")
                if t == "req":
                    task = asyncio.create_task(
                        self._handle_request(frame, writer, wlock, conn_ctxs)
                    )
                    tasks.add(task)
                    task.add_done_callback(tasks.discard)
                elif t == "cancel":
                    ctx = conn_ctxs.get(frame.get("id"))
                    if ctx is not None:
                        ctx.stop_generating()
                elif t == "kill":
                    ctx = conn_ctxs.get(frame.get("id"))
                    if ctx is not None:
                        ctx.kill()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            self._conns.discard(writer)
            for ctx in conn_ctxs.values():
                ctx.kill()  # client went away
            for task in tasks:
                task.cancel()
            writer.close()

    async def _handle_request(
        self,
        frame: Dict[str, Any],
        writer: asyncio.StreamWriter,
        wlock: asyncio.Lock,
        conn_ctxs: Dict[str, Context],
    ) -> None:
        rid = frame["id"]
        path = frame["endpoint"]

        async def send(obj: Dict[str, Any]) -> None:
            async with wlock:
                await _send_frame(writer, obj)

        engine = self._engines.get(path)
        if engine is None or self._draining:
            code = "draining" if self._draining else "no_endpoint"
            await send({"t": "err", "id": rid, "msg": f"{code}: {path}", "code": code})
            return
        ctx = Context.from_headers(frame.get("headers") or {})
        self._active[rid] = ctx
        conn_ctxs[rid] = ctx
        # server-hop span: continues the trace the caller's metadata carries
        # (reference: span per ingress hop, logging.rs:76-105) and re-points
        # the metadata so the engine's own egress calls nest under this hop
        from dynamo_tpu.runtime import tracing

        attrs = {"rpc.endpoint": path, "request.id": rid}
        try:
            # metadata is raw wire input — a malformed value must not crash
            # the handler before the err-frame machinery is armed
            attrs["migration.attempt"] = int(ctx.metadata["migration_attempt"])
        except (KeyError, TypeError, ValueError):
            pass
        span_cm = tracing.span(
            f"rpc {path}", parent=ctx.metadata.get("traceparent"),
            kind=2, attributes=attrs,
        )
        try:
            with span_cm as sp:
                tracing.child_traceparent(ctx.metadata, sp)
                async for item in engine.generate(frame.get("payload"), ctx):
                    if ctx.is_killed:
                        raise CancellationError(rid)
                    await send({"t": "item", "id": rid, "data": item})
            await send({"t": "done", "id": rid})
        except CancellationError:
            try:
                await send({"t": "err", "id": rid, "msg": "killed", "code": "cancelled"})
            except (ConnectionResetError, BrokenPipeError):
                pass
        except (ConnectionResetError, BrokenPipeError):
            ctx.kill()
        except Exception as e:  # engine fault → error frame
            log.exception("engine error on %s", path)
            # preserve a handler-supplied error code (e.g. a remote router
            # service re-raising cannot_connect): flattening everything to
            # "engine" would break the caller's migration / affinity-
            # failover classification across a service hop
            code = getattr(e, "code", None) or "engine"
            try:
                await send({"t": "err", "id": rid, "msg": str(e), "code": code})
            except (ConnectionResetError, BrokenPipeError):
                pass
        finally:
            self._active.pop(rid, None)
            conn_ctxs.pop(rid, None)


class _ConnPool:
    """Per-address pool of idle TCP connections."""

    def __init__(self, max_idle: int = 8, connect_timeout: float = 5.0):
        self._idle: Dict[str, list] = {}
        self.max_idle = max_idle
        self.connect_timeout = connect_timeout

    async def acquire(
        self, address: str, fresh: bool = False
    ) -> Tuple[asyncio.StreamReader, asyncio.StreamWriter, bool]:
        """Returns (reader, writer, pooled). `fresh=True` bypasses the pool
        (used to retry after a pooled connection turned out stale)."""
        pool = self._idle.get(address)
        while pool and not fresh:
            reader, writer = pool.pop()
            if not writer.is_closing():
                return reader, writer, True
        host, port = address.rsplit(":", 1)
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, int(port)), self.connect_timeout
            )
            return reader, writer, False
        except (OSError, asyncio.TimeoutError) as e:
            raise RequestPlaneError(f"cannot connect to {address}: {e}", code="cannot_connect")

    def release(self, address: str, conn: Tuple[asyncio.StreamReader, asyncio.StreamWriter]) -> None:
        reader, writer = conn
        pool = self._idle.setdefault(address, [])
        if writer.is_closing() or len(pool) >= self.max_idle:
            writer.close()
        else:
            pool.append(conn)

    def close(self) -> None:
        for pool in self._idle.values():
            for _, writer in pool:
                writer.close()
        self._idle.clear()


class RemoteEngine:
    """Client side: an AsyncEngine whose generate() pushes the request to a
    remote instance over TCP and yields the streamed response items."""

    def __init__(self, pool: _ConnPool, address: str, endpoint_path: str):
        self._pool = pool
        self.address = address
        self.endpoint_path = endpoint_path

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        """Stream the remote response. If a *pooled* connection turns out
        stale (server restarted since it was pooled) and nothing has been
        yielded yet, retry once on a fresh connection."""
        reader, writer, pooled = await self._pool.acquire(self.address)
        yielded = False
        while True:
            try:
                async for item in self._stream_once(reader, writer, request, context):
                    yielded = True
                    yield item
                return
            except RequestPlaneError as e:
                if pooled and not yielded and e.code == "disconnected":
                    reader, writer, pooled = await self._pool.acquire(self.address, fresh=True)
                    continue
                raise

    async def _stream_once(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        request: Any,
        context: Context,
    ) -> AsyncIterator[Any]:
        clean = False
        canceller: Optional[asyncio.Task] = None
        try:
            await _send_frame(
                writer,
                {
                    "t": "req",
                    "id": context.id,
                    "endpoint": self.endpoint_path,
                    "headers": context.to_headers(),
                    "payload": request,
                },
            )
            # propagate stop/kill to the server even while blocked on recv
            async def _forward_cancel():
                await context.wait_stopped()
                try:
                    kind = "kill" if context.is_killed else "cancel"
                    await _send_frame(writer, {"t": kind, "id": context.id})
                except (ConnectionResetError, BrokenPipeError, OSError):
                    pass

            canceller = asyncio.create_task(_forward_cancel())
            while True:
                frame = await _recv_frame(reader)
                if frame is None:
                    raise RequestPlaneError(
                        f"disconnected from {self.address}", code="disconnected"
                    )
                t = frame.get("t")
                if t == "item":
                    yield frame["data"]
                elif t == "done":
                    clean = True
                    return
                elif t == "err":
                    code = frame.get("code", "engine")
                    if code in ("draining", "no_endpoint", "cancelled"):
                        clean = True
                    raise RequestPlaneError(frame.get("msg", "remote error"), code=code)
        except (ConnectionResetError, BrokenPipeError, OSError) as e:
            raise RequestPlaneError(f"connection lost to {self.address}: {e}", code="disconnected")
        finally:
            if canceller is not None:
                canceller.cancel()
            # a connection mid-stream is poisoned; only clean completions are pooled
            if clean:
                self._pool.release(self.address, (reader, writer))
            else:
                writer.close()


class RouterMode:
    ROUND_ROBIN = "round_robin"
    RANDOM = "random"
    DIRECT = "direct"
    KV = "kv"  # handled one level up by KvPushRouter


class PushRouter:
    """Client-side fan-out over the live instance set of an endpoint
    (reference egress/push_router.rs:184-194). Instance set is maintained by
    a discovery watch; routing modes: round_robin / random / direct."""

    def __init__(self, endpoint_path: str, mode: str = RouterMode.ROUND_ROBIN):
        self.endpoint_path = endpoint_path
        self.mode = mode
        self._pool = _ConnPool()
        self._instances: Dict[int, str] = {}  # instance_id -> address
        self._rr = 0

    def update_instance(self, instance_id: int, address: Optional[str]) -> None:
        if address is None:
            self._instances.pop(instance_id, None)
        else:
            self._instances[instance_id] = address

    @property
    def instance_ids(self) -> list:
        return list(self._instances)

    def _pick(self, instance_id: Optional[int] = None) -> Tuple[int, str]:
        if not self._instances:
            raise RequestPlaneError(
                f"no instances for {self.endpoint_path}", code="no_instances"
            )
        if instance_id is not None:
            addr = self._instances.get(instance_id)
            if addr is None:
                raise RequestPlaneError(
                    f"instance {instance_id:x} not found", code="cannot_connect"
                )
            return instance_id, addr
        if self.mode == RouterMode.DIRECT:
            raise RequestPlaneError(
                "direct routing mode requires a target instance_id", code="no_target"
            )
        ids = sorted(self._instances)
        if self.mode == RouterMode.RANDOM:
            iid = random.choice(ids)
        else:  # round robin default
            iid = ids[self._rr % len(ids)]
            self._rr += 1
        return iid, self._instances[iid]

    def engine_for(self, instance_id: Optional[int] = None) -> RemoteEngine:
        _, addr = self._pick(instance_id)
        return RemoteEngine(self._pool, addr, self.endpoint_path)

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        iid, addr = self._pick(context.metadata.get("target_instance"))
        # report the choice so wrappers (session affinity) can pin to it
        context.metadata["routed_instance"] = iid
        engine = RemoteEngine(self._pool, addr, self.endpoint_path)
        async for item in engine.generate(request, context):
            yield item

    def close(self) -> None:
        self._pool.close()
