"""Runtime sanitizer: cheap always-on invariant checks for serving.

The static side (dynlint's interprocedural pass) proves what it can see
in the AST; this module is the dynamic complement, armed with
``DYN_SAN=1`` (or ``--sanitize`` on the worker / mocker CLIs):

- **transfer guard** — wraps the steady-state decode / spec-verify
  dispatches in ``jax.transfer_guard("disallow")`` once the engine is
  warm, so any *implicit* device↔host sync that creeps into the step
  loop fails loudly at the offending line instead of silently serializing
  the pipeline. Known sync points (input staging, the one bulk token
  readback, embed readback) run inside named :meth:`Sanitizer.allow_transfer`
  scopes checked against an explicit allowlist — an unnamed scope is
  itself a violation, so the allowlist IS the documentation of every
  sanctioned transfer (see docs/static_analysis.md).
- **recompile tripwire** — after ``warmup_steps`` engine iterations the
  compiled-family variant counts (`ModelRunner._families`) must be
  frozen; any new family or variant afterwards is a compile-cache leak
  (shape churn) and fires a violation.
- **lock-order recorder** — :meth:`wrap_lock` proxies a lock and records
  the held-before graph per acquisition; an edge that closes a cycle
  reports the full path with acquisition sites (the dynamic twin of
  dynlint DYN-R007, which proves the static subset).
- **asyncio watchdog** — samples event-loop lag (a gauge, never fatal)
  and audits the `spawn_tracked` registry for still-running fire-and-
  forget tasks at shutdown.
- **page-pool audit** — free/ref/cached must partition the pool
  (fork_table refcounts included); with no live sequences, `ref` must be
  empty or pages leaked.

Violations raise :class:`SanitizerViolation` when ``strict`` (unit
tests), or accumulate on :attr:`Sanitizer.violations` for a report block
(fleet-sim chaos runs assert the list is empty at teardown). Everything
here is allocation-light; the measured steady-state overhead is in
docs/perf_notes.md.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import sys
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

log = logging.getLogger("dynamo_tpu.runtime.sanitizer")


class SanitizerViolation(RuntimeError):
    """An invariant the sanitizer enforces was broken (strict mode)."""


#: Every sanctioned implicit-transfer site, by label. Adding a label here
#: is a reviewed act: the docs table in docs/static_analysis.md must gain
#: the matching row explaining WHY the sync is at a request/iteration
#: boundary rather than inside the steady-state loop.
DEFAULT_ALLOWLIST = frozenset({
    "decode_staging",    # per-dispatch int pack + token h2d (model_runner)
    "spec_staging",      # draft-loop tok/pos/table staging
    "verify_staging",    # ragged verify flat-token + metadata staging
    "sampling_staging",  # SamplingParams host->device rows
    "token_readback",    # the ONE bulk d2h sync per fused dispatch
    "draft_readback",    # device n-gram ring proposal d2h (one per spec
                         # iteration, replacing the host history scan)
    "embed_readback",    # request-boundary embedding .tolist
    "kv_tier_io",        # G2/G3 onboarding / offload block copies
    "weight_reload",     # RL weight swap (paused engine, not steady state)
})

#: Compile families that grow at the ADMISSION boundary, not in the warm
#: decode loop: a new prompt-length bucket (first request of that size, or
#: a preempted sequence re-prefilling past its old bucket) legitimately
#: compiles a new prefill variant long after warmup. Growth here is
#: counted and logged once per family, never a violation — mirroring the
#: transfer-guard policy that leaves prefill/mixed dispatch unguarded
#: (docs/static_analysis.md). Steady-state families (decode_loop, mixed,
#: ragged, draft) stay frozen.
ADMISSION_FAMILIES = frozenset({"forward"})


def env_enabled() -> bool:
    return os.environ.get("DYN_SAN", "").lower() in ("1", "true", "on", "yes")


def from_env(**kwargs) -> Optional["Sanitizer"]:
    """Build a Sanitizer iff DYN_SAN is set (the worker/mocker default)."""
    return Sanitizer(**kwargs) if env_enabled() else None


class _TrackedLock:
    """Lock proxy recording acquisition order into the owning Sanitizer.

    Supports the context-manager protocol plus acquire/release/locked so
    it drops in for `threading.Lock` at every engine call site. Non-
    blocking and timeout acquires record only on success.
    """

    __slots__ = ("_lock", "name", "_san")

    def __init__(self, lock, name: str, san: "Sanitizer"):
        self._lock = lock
        self.name = name
        self._san = san

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._san._note_acquire(self.name)
        return got

    def release(self) -> None:
        self._san._note_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class Sanitizer:
    def __init__(
        self,
        *,
        strict: bool = True,
        allowlist: Iterable[str] = DEFAULT_ALLOWLIST,
        transfer_guard: bool = True,
        warmup_steps: int = 16,
        watchdog_interval_s: float = 0.05,
        watchdog_lag_s: float = 0.25,
    ):
        self.strict = strict
        self.allowlist = frozenset(allowlist)
        self.transfer_guard = transfer_guard
        self.warmup_steps = warmup_steps
        self.watchdog_interval_s = watchdog_interval_s
        self.watchdog_lag_s = watchdog_lag_s
        self.violations: List[Dict[str, Any]] = []
        self._vlock = threading.Lock()  # guards violations (multi-thread)
        # recompile tripwire
        self._steps = 0
        self._warm = False
        self._warm_variants: Dict[str, int] = {}
        # lock-order recorder: name -> {successor: (lock_a_site,)} edges;
        # held stacks are per-thread (the engine step thread and asyncio
        # callbacks both take guided locks)
        self._edges: Dict[str, Dict[str, str]] = {}
        self._held = threading.local()
        self._graph_lock = threading.Lock()
        # watchdog
        self._watchdog_task: Optional[asyncio.Task] = None
        self.loop_lag_max_s = 0.0
        self.counters: Dict[str, int] = {
            "steps": 0, "allowed_transfers": 0, "lock_acquires": 0,
        }

    # -- violations --------------------------------------------------------
    def _violation(self, kind: str, message: str) -> None:
        with self._vlock:
            self.violations.append({"kind": kind, "message": message})
        if self.strict:
            raise SanitizerViolation(f"[{kind}] {message}")
        log.warning("sanitizer violation [%s]: %s", kind, message)

    def ok(self) -> bool:
        return not self.violations

    def report(self) -> Dict[str, Any]:
        return {
            "ok": self.ok(),
            "violations": list(self.violations),
            "steps": self._steps,
            "warm": self._warm,
            "loop_lag_max_ms": round(self.loop_lag_max_s * 1e3, 3),
            "counters": dict(self.counters),
        }

    # -- transfer guard ----------------------------------------------------
    @contextlib.contextmanager
    def transfer_scope(self, where: str = "step"):
        """Disallow implicit transfers for the duration (warm engine only
        — warmup iterations compile and stage freely). The violation is
        recorded AND the original error re-raised: the dispatch it broke
        cannot be completed, and the engine's per-step error handling
        owns failing the affected sequences."""
        jax = sys.modules.get("jax")
        if jax is None or not (self.transfer_guard and self._warm):
            # never import jax ourselves: mocker processes run the whole
            # engine jax-free and the sanitizer must not change that
            yield
            return
        try:
            with jax.transfer_guard("disallow"):
                yield
        except SanitizerViolation:
            raise
        except Exception as e:
            if "transfer" in str(e).lower():
                with self._vlock:
                    self.violations.append({
                        "kind": "transfer",
                        "message": f"implicit transfer in {where}: {e}",
                    })
                log.error("sanitizer: implicit transfer in %s: %s", where, e)
            raise

    @contextlib.contextmanager
    def allow_transfer(self, label: str):
        """Named escape hatch for a known sync point. Labels outside the
        allowlist are violations — the allowlist is the reviewed budget
        of sanctioned transfers, not a convenience."""
        if label not in self.allowlist:
            self._violation(
                "allowlist",
                f"transfer scope {label!r} is not in the sanitizer "
                f"allowlist; add it to DEFAULT_ALLOWLIST *and* the docs "
                "table, or remove the sync",
            )
            yield  # non-strict: record, then let it run
            return
        self.counters["allowed_transfers"] += 1
        jax = sys.modules.get("jax")
        if jax is None or not (self.transfer_guard and self._warm):
            yield
            return
        with jax.transfer_guard("allow"):
            yield

    # -- layout guard ------------------------------------------------------
    def check_layouts(self, runner: Any) -> int:
        """Diff live ``jax.Array.sharding`` for every row of the runner's
        statically-derived layout table (ModelRunner.layout_table —
        ShardingPolicy over parallel/mesh.py's canonical spec tables)
        against the declared NamedSharding. Any inequivalence is a HARD
        violation carrying both specs: the array was silently re-placed,
        which means an implicit reshard/all-gather is hiding in the path
        that produced it — the dynamic twin of dynlint DYN-S001/S003.
        Runs once at warm-path entry (note_step), when params and pools
        are in their steady-state placement. Runners without a
        layout_table (mocker SimRunner — the whole fleet sim runs
        jax-free) no-op. Returns the number of rows checked."""
        # no jax gate needed: the guard only reads attributes the arrays
        # already carry, and jax-free runners (SimRunner) simply have no
        # layout_table
        table_fn = getattr(runner, "layout_table", None)
        if table_fn is None:
            return 0
        checked = 0
        for name, arr, want in table_fn():
            live = getattr(arr, "sharding", None)
            if live is None:
                continue
            checked += 1
            try:
                same = live.is_equivalent_to(want, arr.ndim)
            except Exception:
                same = live == want
            if not same:
                self._violation(
                    "layout",
                    f"{name}: live sharding {live} diverges from the "
                    f"declared spec {want.spec} on mesh "
                    f"{dict(want.mesh.shape)} — the array was silently "
                    "re-placed (implicit reshard/all-gather) after the "
                    "policy applied the canonical table",
                )
        self.counters["layout_checked"] = checked
        return checked

    # -- recompile tripwire ------------------------------------------------
    def mark_warm(self) -> None:
        self._warm = True

    def note_step(self, runner: Any = None) -> None:
        """Called once per engine iteration (step thread). Arms the
        transfer guard and freezes the compiled-family baseline after
        `warmup_steps`; any later growth is a compile-cache leak."""
        self._steps += 1
        self.counters["steps"] = self._steps
        fams = getattr(runner, "_families", None)
        variants = (
            {name: fam.variants for name, fam in fams.items()} if fams else {}
        )
        if not self._warm:
            if self._steps >= self.warmup_steps:
                self.mark_warm()
                self._warm_variants = variants
                # warm-path entry: params/pools are in steady-state
                # placement — snapshot and diff their live layouts once
                if runner is not None:
                    self.check_layouts(runner)
            return
        for name, n in variants.items():
            base = self._warm_variants.get(name)
            # update the baseline BEFORE reporting so a non-strict run
            # logs each leak once instead of every subsequent step
            self._warm_variants[name] = n
            if name in ADMISSION_FAMILIES:
                if base is not None and n > base:
                    self.counters["admission_recompiles"] = (
                        self.counters.get("admission_recompiles", 0) + 1
                    )
                    log.info(
                        "admission-boundary family %r grew %d->%d variants "
                        "(step %d) — new prompt bucket, not a warm-loop leak",
                        name, base, n, self._steps,
                    )
                continue
            if base is None:
                self._violation(
                    "recompile",
                    f"new compiled family {name!r} appeared after warmup "
                    f"(step {self._steps})",
                )
            elif n > base:
                self._violation(
                    "recompile",
                    f"compiled family {name!r} grew {base}->{n} variants "
                    f"after warmup (step {self._steps}) — shape churn in "
                    "the steady-state loop",
                )

    # -- lock-order recorder -----------------------------------------------
    def wrap_lock(self, lock, name: str) -> _TrackedLock:
        return _TrackedLock(lock, name, self)

    def _held_stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def _note_acquire(self, name: str) -> None:
        self.counters["lock_acquires"] += 1
        st = self._held_stack()
        if st:
            outer = st[-1]
            if outer != name:
                with self._graph_lock:
                    fresh = name not in self._edges.setdefault(outer, {})
                    if fresh:
                        self._edges[outer][name] = (
                            threading.current_thread().name
                        )
                        cycle = self._find_cycle(name, outer)
                    else:
                        cycle = None
                if fresh and cycle:
                    self._violation(
                        "lock_order",
                        "lock acquisition order cycle: "
                        + " -> ".join(cycle)
                        + f" (edge {outer!r} -> {name!r} closed it on "
                        f"thread {threading.current_thread().name!r})",
                    )
        st.append(name)

    def _note_release(self, name: str) -> None:
        st = self._held_stack()
        # out-of-order release is legal (threading allows it); drop the
        # newest matching entry
        for i in range(len(st) - 1, -1, -1):
            if st[i] == name:
                del st[i]
                break

    def _find_cycle(self, start: str, target: str) -> Optional[List[str]]:
        """Path start ->* target in the held-before graph (caller holds
        _graph_lock); with the new target->start edge that is a cycle."""
        path: List[str] = []
        seen = set()

        def dfs(node: str) -> bool:
            if node == target:
                path.append(node)
                return True
            if node in seen:
                return False
            seen.add(node)
            for nxt in self._edges.get(node, {}):
                if dfs(nxt):
                    path.append(node)
                    return True
            return False

        if dfs(start):
            path.reverse()  # start ... target; closing edge returns to start
            return path + [start]
        return None

    # -- asyncio watchdog --------------------------------------------------
    def start_watchdog(self) -> asyncio.Task:
        """Start the event-loop lag sampler (call from the serving loop).
        Plain create_task retained on self — deliberately NOT
        spawn_tracked, so audit_tasks never reports the watchdog
        itself."""
        if self._watchdog_task is None or self._watchdog_task.done():
            self._watchdog_task = asyncio.get_running_loop().create_task(
                self._watch(), name="dyn-san-watchdog"
            )
        return self._watchdog_task

    async def stop_watchdog(self) -> None:
        t = self._watchdog_task
        if t is not None and not t.done():
            t.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await t
        self._watchdog_task = None

    async def _watch(self) -> None:
        loop = asyncio.get_running_loop()
        interval = self.watchdog_interval_s
        while True:
            t0 = loop.time()
            await asyncio.sleep(interval)
            lag = loop.time() - t0 - interval
            if lag > self.loop_lag_max_s:
                self.loop_lag_max_s = lag
            if lag > self.watchdog_lag_s:
                # a gauge, not a failure: lag has benign causes (cold
                # imports, CI noise) — record without raising even in
                # strict mode
                with self._vlock:
                    self.violations.append({
                        "kind": "loop_lag",
                        "message": f"event loop stalled {lag*1e3:.0f} ms "
                                   f"(threshold {self.watchdog_lag_s*1e3:.0f} ms)",
                    })
                log.warning("sanitizer: event loop stalled %.0f ms", lag * 1e3)

    def audit_tasks(self) -> List[str]:
        """Leaked fire-and-forget audit (shutdown): every spawn_tracked
        task should be done once its owner stopped. Returns the leaked
        task names (and files a violation if any)."""
        from dynamo_tpu.runtime import tasks as _tasks

        leaked = sorted(
            t.get_name() for t in _tasks._TRACKED if not t.done()
        )
        if leaked:
            self._violation(
                "leaked_task",
                f"{len(leaked)} tracked task(s) still running at audit: "
                + ", ".join(leaked[:8]),
            )
        return leaked

    # -- page-pool audit ---------------------------------------------------
    def audit_pool(self, pool, live_seqs: int = 0) -> None:
        """PagePool partition/refcount invariants at request teardown or
        engine stop. fork_table-aware: forked pages legitimately carry
        ref > 1; what must never happen is a page in two states at once,
        a non-positive refcount, or allocated pages with no live
        sequence to own them."""
        free = set(pool.free)
        refd = set(pool.ref)
        cached = set(pool.cached)
        overlap = (free & refd) | (free & cached) | (refd & cached)
        if overlap:
            self._violation(
                "pool",
                f"pages in two states at once: {sorted(overlap)[:8]}",
            )
        missing = set(range(pool.num_pages)) - free - refd - cached
        if missing:
            self._violation(
                "pool",
                f"pages lost from the pool (not free/ref/cached): "
                f"{sorted(missing)[:8]}",
            )
        bad_ref = {p: c for p, c in pool.ref.items() if c <= 0}
        if bad_ref:
            self._violation(
                "pool", f"non-positive refcounts: {bad_ref}"
            )
        if live_seqs == 0 and refd:
            self._violation(
                "pool",
                f"{len(refd)} page(s) still referenced with no live "
                f"sequences — leaked at teardown: {sorted(refd)[:8]}",
            )
        for h, p in pool.by_hash.items():
            if pool.hash_of.get(p) != h:
                self._violation(
                    "pool",
                    f"hash index desync: by_hash[{h}]={p} but "
                    f"hash_of[{p}]={pool.hash_of.get(p)}",
                )
        stray_pins = set(pool.pinned) - set(pool.by_hash)
        if stray_pins:
            self._violation(
                "pool",
                f"pinned hashes with no registered page: "
                f"{sorted(stray_pins)[:8]}",
            )


def selftest() -> bool:
    """Cheap jax-free self-check used by scripts/check_tier1.py to report
    `sanitizer_ok`: lock-cycle detection, allowlist rejection, and the
    violation plumbing must all work in-process."""
    san = Sanitizer(strict=False, transfer_guard=False)
    a = san.wrap_lock(threading.Lock(), "A")
    b = san.wrap_lock(threading.Lock(), "B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert any(v["kind"] == "lock_order" for v in san.violations), \
        "lock cycle not detected"
    n = len(san.violations)
    with san.allow_transfer("not_a_real_label"):
        pass
    assert any(v["kind"] == "allowlist" for v in san.violations[n:]), \
        "allowlist breach not detected"
    strict = Sanitizer(strict=True)
    try:
        strict._violation("selftest", "must raise")
    except SanitizerViolation:
        pass
    else:
        raise AssertionError("strict mode did not raise")

    # layout guard plumbing, still jax-free: a runner WITHOUT a
    # layout_table must no-op (the fleet sim's SimRunner path), and a
    # mismatched table row must fire a "layout" violation with both
    # sides in the message
    class _Placement:
        def __init__(self, tag):
            self.tag = tag
            self.spec = tag
            self.mesh = type("M", (), {"shape": {}})()

        def is_equivalent_to(self, other, ndim):
            return self.tag == other.tag

        def __str__(self):
            return self.tag

    class _Arr:
        ndim = 2

        def __init__(self, tag):
            self.sharding = _Placement(tag)

    class _Runner:
        def layout_table(self):
            return [("params/good", _Arr("P('model')"),
                     _Placement("P('model')")),
                    ("params/drifted", _Arr("P()"),
                     _Placement("P('model')"))]

    lay = Sanitizer(strict=False, transfer_guard=False)
    assert lay.check_layouts(object()) == 0, "table-less runner must no-op"
    assert lay.check_layouts(_Runner()) == 2
    bad = [v for v in lay.violations if v["kind"] == "layout"]
    assert len(bad) == 1 and "params/drifted" in bad[0]["message"], \
        "layout drift not detected"
    return True
