"""DistributedRuntime — top-level runtime handle.

Analog of reference lib/runtime/src/distributed.rs:46-180: owns the
discovery client, the request-plane server (one TCP listener hosting all
endpoints served by this process), the event plane, and the metrics root.
Offers the Namespace→Component→Endpoint builder used by workers
(`endpoint.serve(engine)`) and clients (`endpoint.client()`).
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
from typing import Any, Dict, List, Optional

from dynamo_tpu.runtime.component import (
    EndpointAddress,
    Instance,
    TransportKind,
    new_instance_id,
)
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.discovery import DiscoveryBackend, make_discovery
from dynamo_tpu.runtime.engine import AsyncEngine, as_engine
from dynamo_tpu.runtime.event_plane import (
    EventPublisher,
    EventSubscriber,
    make_publisher,
    make_subscriber,
)
from dynamo_tpu.runtime.metrics import make_metrics
from dynamo_tpu.runtime.request_plane import PushEndpoint, PushRouter, RouterMode

log = logging.getLogger("dynamo_tpu.runtime")


class DistributedRuntime:
    def __init__(
        self,
        discovery: Optional[DiscoveryBackend] = None,
        discovery_backend: Optional[str] = None,
        event_transport: Optional[str] = None,
        host: Optional[str] = None,
        request_plane: Optional[str] = None,  # "tcp" (default) | "nats" | "inproc"
        **discovery_kw,
    ):
        self.discovery = discovery or make_discovery(discovery_backend, **discovery_kw)
        self.event_transport = event_transport or os.environ.get("DYN_EVENT_PLANE", "zmq")
        self.host = host or os.environ.get("DYN_TCP_HOST", "127.0.0.1")
        self.metrics = make_metrics()
        # RequestPlaneMode{Tcp,Nats} (reference distributed.rs:773-779):
        # the server advertises a self-describing address, so clients need
        # no mode flag — PushRouter dials TCP or the broker per address
        self.request_plane = (
            request_plane or os.environ.get("DYN_REQUEST_PLANE", "tcp")
        ).lower()
        if self.request_plane == "nats":
            from dynamo_tpu.runtime.request_plane import NatsPushEndpoint

            self.server = NatsPushEndpoint()
        elif self.request_plane == "inproc":
            # one-process fleets (fleet simulator): registry-keyed
            # endpoint, no listener socket — see request_plane.py
            from dynamo_tpu.runtime.request_plane import InprocPushEndpoint

            self.server = InprocPushEndpoint()
        elif self.request_plane == "tcp":
            self.server = PushEndpoint(host=self.host)
        else:
            raise ValueError(
                f"unknown request plane {self.request_plane!r} "
                "(expected tcp, nats, or inproc)"
            )
        self._server_started = False
        self._served: List[Instance] = []
        self._event_publisher: Optional[EventPublisher] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._closed = False
        self.root_context = Context(request_id="runtime")

    # -- builders ---------------------------------------------------------
    def namespace(self, name: str) -> "Namespace":
        return Namespace(self, name)

    def endpoint(self, path: str) -> "Endpoint":
        addr = EndpointAddress.parse(path)
        return Namespace(self, addr.namespace).component(addr.component).endpoint(addr.endpoint)

    # -- event plane ------------------------------------------------------
    def event_publisher(self) -> EventPublisher:
        """Lazily create this process's PUB socket; address is advertised in
        instance metadata (event-plane.md brokerless topology)."""
        if self._event_publisher is None:
            self._event_publisher = make_publisher(self.event_transport)
        return self._event_publisher

    def event_subscriber(self, subjects: Optional[List[str]] = None) -> EventSubscriber:
        return make_subscriber(self.event_transport, subjects)

    # -- serving ----------------------------------------------------------
    async def _ensure_server(self) -> None:
        if not self._server_started:
            # flag BEFORE the await (rolled back on failure): a second
            # caller arriving during start() must not double-start
            self._server_started = True
            try:
                await self.server.start()
            except BaseException:
                self._server_started = False
                raise
        if self._hb_task is None:
            self._hb_task = asyncio.create_task(self._heartbeat_loop())

    async def _heartbeat_loop(self) -> None:
        while not self._closed:
            try:
                await self.discovery.heartbeat()
            except Exception:  # pragma: no cover
                log.exception("discovery heartbeat failed")
            await asyncio.sleep(2.0)

    async def serve_endpoint(
        self,
        path: str,
        handler: Any,
        metadata: Optional[Dict[str, Any]] = None,
        instance_id: Optional[int] = None,
    ) -> Instance:
        """Serve `handler` (AsyncEngine or async fn) at `ns/comp/ep`,
        registering an Instance in discovery (reference
        Endpoint.serve_endpoint, bindings _core.pyi:150)."""
        await self._ensure_server()
        engine = as_engine(handler)
        addr = EndpointAddress.parse(path)
        self.server.add_endpoint(path, engine)
        inst = Instance(
            namespace=addr.namespace,
            component=addr.component,
            endpoint=addr.endpoint,
            instance_id=instance_id if instance_id is not None else new_instance_id(),
            transport=TransportKind.TCP,
            address=self.server.address,
            metadata=metadata or {},
        )
        await self.discovery.register(inst)
        self._served.append(inst)
        log.info("serving %s as instance %x at %s", path, inst.instance_id, inst.address)
        return inst

    async def update_instance_metadata(self, inst: Instance, metadata: Dict[str, Any]) -> None:
        inst.metadata.update(metadata)
        await self.discovery.register(inst)

    # -- clients ----------------------------------------------------------
    def client(self, path: str, mode: str = RouterMode.ROUND_ROBIN) -> "EndpointClient":
        return EndpointClient(self, path, mode)

    # -- shutdown ---------------------------------------------------------
    async def shutdown(self, drain_timeout: float = 30.0) -> None:
        self._closed = True
        self.root_context.kill()
        for inst in self._served:
            try:
                await self.discovery.unregister(inst)
            except Exception:  # pragma: no cover
                log.debug("unregister %x failed during shutdown (lease "
                          "expiry will reclaim it)", inst.instance_id,
                          exc_info=True)
        self._served.clear()
        if self._hb_task is not None:
            self._hb_task.cancel()
        if self._server_started:
            await self.server.stop(drain_timeout)
        if self._event_publisher is not None:
            await self._event_publisher.close()
        await self.discovery.close()
        # drain the span batch queue (bounded) so a short-lived worker's
        # tail spans reach the collector before the process exits
        from dynamo_tpu.runtime import tracing

        await asyncio.get_running_loop().run_in_executor(
            None, tracing.flush_tracing, 5.0)


class Namespace:
    def __init__(self, runtime: DistributedRuntime, name: str):
        self.runtime = runtime
        self.name = name
        self.metrics = runtime.metrics.child(dynamo_namespace=name)

    def component(self, name: str) -> "Component":
        return Component(self, name)


class Component:
    def __init__(self, namespace: Namespace, name: str):
        self.namespace = namespace
        self.name = name
        self.metrics = namespace.metrics.child(dynamo_component=name)

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self, name)


class Endpoint:
    def __init__(self, component: Component, name: str):
        self.component = component
        self.name = name
        self.metrics = component.metrics.child(dynamo_endpoint=name)

    @property
    def path(self) -> str:
        return f"{self.component.namespace.name}/{self.component.name}/{self.name}"

    @property
    def runtime(self) -> DistributedRuntime:
        return self.component.namespace.runtime

    async def serve(
        self,
        handler: Any,
        metadata: Optional[Dict[str, Any]] = None,
        instance_id: Optional[int] = None,
    ) -> Instance:
        return await self.runtime.serve_endpoint(
            self.path, handler, metadata=metadata, instance_id=instance_id
        )

    def client(self, mode: str = RouterMode.ROUND_ROBIN) -> "EndpointClient":
        return self.runtime.client(self.path, mode)


class EndpointClient:
    """Client handle for one endpoint: watches discovery, keeps the
    PushRouter's instance set current, exposes generate()/direct().

    Mirrors the reference Client (lib/runtime/src/component/client.rs):
    instance set shrinks on lease expiry / unregister, grows on discovery.
    """

    def __init__(self, runtime: DistributedRuntime, path: str, mode: str = RouterMode.ROUND_ROBIN):
        self.runtime = runtime
        self.path = path
        addr = EndpointAddress.parse(path)
        self._prefix = f"services/{addr.namespace}/{addr.component}/{addr.endpoint}/"
        self.router = PushRouter(path, mode)
        self._watch_task: Optional[asyncio.Task] = None
        self._ready = asyncio.Event()
        self.instances: Dict[int, Instance] = {}
        self._change_cbs: list = []  # cb(kind: "put"|"delete", Instance)

    async def start(self) -> "EndpointClient":
        if self._watch_task is None:
            self._watch_task = asyncio.create_task(self._watch())
        return self

    def on_instance_change(self, cb) -> None:
        """cb(kind: "put"|"delete", Instance); put also fires on metadata
        updates (discovery emits puts for changed records)."""
        self._change_cbs.append(cb)

    async def _watch(self) -> None:
        try:
            async for ev in self.runtime.discovery.watch(self._prefix):
                inst = ev.instance
                if ev.kind == "put":
                    self.instances[inst.instance_id] = inst
                    self.router.update_instance(inst.instance_id, inst.address)
                    self.router.update_weight(
                        inst.instance_id,
                        (inst.metadata or {}).get("device_weight"),
                    )
                    self._ready.set()
                else:
                    self.instances.pop(inst.instance_id, None)
                    self.router.update_instance(inst.instance_id, None)
                for cb in self._change_cbs:
                    try:
                        res = cb(ev.kind, inst)
                        if asyncio.iscoroutine(res):
                            await res
                    except Exception:  # pragma: no cover
                        log.exception("instance-change callback failed")
        except asyncio.CancelledError:
            pass

    async def wait_ready(self, timeout: float = 10.0) -> None:
        await self.start()
        await asyncio.wait_for(self._ready.wait(), timeout)

    async def generate(self, request: Any, context: Optional[Context] = None):
        """Push to an instance chosen by the router mode; async iterator of
        response items."""
        context = context or Context()
        async for item in self.router.generate(request, context):
            yield item

    async def direct(self, request: Any, instance_id: int, context: Optional[Context] = None):
        """Push to a specific instance (reference RouterMode::Direct)."""
        context = context or Context()
        engine = self.router.engine_for(instance_id)
        async for item in engine.generate(request, context):
            yield item

    async def close(self) -> None:
        if self._watch_task is not None:
            self._watch_task.cancel()
        self.router.close()


def get_host_ip() -> str:  # pragma: no cover
    """Best-effort routable IP for cross-host deployments."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()
