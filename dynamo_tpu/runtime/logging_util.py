"""Logging setup (analog of reference lib/runtime/src/logging.rs).

Env-driven like the reference's DYN_LOG: `DYN_LOG=debug` or per-module
filters `DYN_LOG=info,dynamo_tpu.router=debug`; `DYN_LOG_JSONL=1` switches
to JSON-lines records (one object per line) for log shippers. OTLP export is
out of scope in this environment (no collector); the JSONL format carries
the same fields.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_CONFIGURED = False


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        for k in ("request_id", "component", "endpoint"):
            v = getattr(record, k, None)
            if v is not None:
                out[k] = v
        return json.dumps(out)


def configure_logging(default_level: str = "info") -> None:
    """Idempotent; call from every entrypoint."""
    global _CONFIGURED
    if _CONFIGURED:
        return
    _CONFIGURED = True

    spec = os.environ.get("DYN_LOG", default_level)
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    root_level = "info"
    module_levels = {}
    for p in parts:
        if "=" in p:
            mod, lvl = p.split("=", 1)
            module_levels[mod] = lvl
        else:
            root_level = p

    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("DYN_LOG_JSONL", "").lower() in ("1", "true", "on", "yes"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S")
        )
    root = logging.getLogger()
    root.addHandler(handler)
    root.setLevel(root_level.upper())
    for mod, lvl in module_levels.items():
        logging.getLogger(mod).setLevel(lvl.upper())
