"""Logging setup (analog of reference lib/runtime/src/logging.rs).

Env-driven like the reference's DYN_LOG: `DYN_LOG=debug` or per-module
filters `DYN_LOG=info,dynamo_tpu.router=debug`; `DYN_LOG_JSONL=1` switches
to JSON-lines records (one object per line) for log shippers.
`DYN_OTLP_ENDPOINT=http://collector:4318` additionally ships records to an
OpenTelemetry collector over OTLP/HTTP JSON (/v1/logs) — plain urllib in a
background thread, no otel SDK dependency (reference: OTLP exporter wired
through tracing-subscriber, logging.rs)."""

from __future__ import annotations

import json
import logging
import os
import sys
import time

_CONFIGURED = False


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "target": record.name,
            "message": record.getMessage(),
        }
        if record.exc_info:
            out["exception"] = self.formatException(record.exc_info)
        for k in ("request_id", "component", "endpoint"):
            v = getattr(record, k, None)
            if v is not None:
                out[k] = v
        return json.dumps(out)


def configure_logging(default_level: str = "info") -> None:
    """Idempotent; call from every entrypoint."""
    global _CONFIGURED
    if _CONFIGURED:
        return
    _CONFIGURED = True

    spec = os.environ.get("DYN_LOG", default_level)
    parts = [p.strip() for p in spec.split(",") if p.strip()]
    root_level = "info"
    module_levels = {}
    for p in parts:
        if "=" in p:
            mod, lvl = p.split("=", 1)
            module_levels[mod] = lvl
        else:
            root_level = p

    handler = logging.StreamHandler(sys.stderr)
    if os.environ.get("DYN_LOG_JSONL", "").lower() in ("1", "true", "on", "yes"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname).1s %(name)s: %(message)s", "%H:%M:%S")
        )
    root = logging.getLogger()
    root.addHandler(handler)
    root.setLevel(root_level.upper())
    for mod, lvl in module_levels.items():
        logging.getLogger(mod).setLevel(lvl.upper())

    otlp = os.environ.get("DYN_OTLP_ENDPOINT")
    if otlp:
        root.addHandler(OtlpLogHandler(otlp))

    # span export rides the same env configuration (reference logging.rs
    # wires logs and traces through one OTLP pipeline)
    from dynamo_tpu.runtime.tracing import configure_tracing

    configure_tracing()


_SEVERITY = {"DEBUG": 5, "INFO": 9, "WARNING": 13, "ERROR": 17, "CRITICAL": 21}


class OtlpLogHandler(logging.Handler):
    """Ship log records to an OTLP/HTTP collector (/v1/logs, JSON
    encoding). Batched and posted from a daemon thread so logging never
    blocks the serving path; drops on collector failure (telemetry is
    best-effort)."""

    def __init__(self, endpoint: str, service_name: str = "dynamo_tpu",
                 flush_interval_s: float = 2.0, max_batch: int = 512):
        super().__init__()
        self.url = endpoint.rstrip("/") + "/v1/logs"
        self.service_name = service_name
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        import queue
        import threading

        self._q: "queue.Queue" = queue.Queue(maxsize=8192)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def emit(self, record: logging.LogRecord) -> None:
        import queue

        # no logging in here: a log call from the log exporter recurses
        # straight back into emit
        try:
            wire = {
                "timeUnixNano": str(int(record.created * 1e9)),
                "severityNumber": _SEVERITY.get(record.levelname, 9),
                "severityText": record.levelname,
                "body": {"stringValue": record.getMessage()},
                "attributes": [
                    {"key": "target",
                     "value": {"stringValue": record.name}},
                ],
            }
        except Exception:
            self.handleError(record)  # bad format args: stderr, not a raise
            return
        try:
            self._q.put_nowait(wire)
        except queue.Full:
            pass  # full queue: drop

    def _loop(self) -> None:
        import queue
        import urllib.request

        while True:
            batch = [self._q.get()]
            deadline = time.monotonic() + self.flush_interval_s
            while len(batch) < self.max_batch:
                try:
                    batch.append(self._q.get(timeout=max(0.01, deadline - time.monotonic())))
                except queue.Empty:
                    break
            payload = json.dumps(
                {
                    "resourceLogs": [
                        {
                            "resource": {"attributes": [
                                {"key": "service.name",
                                 "value": {"stringValue": self.service_name}},
                            ]},
                            "scopeLogs": [{"scope": {}, "logRecords": batch}],
                        }
                    ]
                }
            ).encode()
            try:
                req = urllib.request.Request(
                    self.url, data=payload,
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=5).read()
            except (OSError, ValueError):
                # collector down / bad endpoint: telemetry drops, serving
                # unaffected (no logging here — it would feed back into
                # this exporter's own queue)
                pass
