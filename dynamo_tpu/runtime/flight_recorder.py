"""Always-on engine flight recorder: a fixed-size ring of per-iteration
records plus an EWMA-based anomaly trigger.

The engine step loop appends ONE `IterationRecord` per dispatched
iteration (engine/engine.py `_loop_once`): what the scheduler composed
(decode batch x fused steps, packed prefill chunks and their real vs
charged tokens, ragged vs padded program, fused vs two-dispatch), what it
cost (dispatch + host-sync wall time), and what the world looked like
(admission-queue depth, KV occupancy per tier, prefetch hits,
compile-family cache growth). The ring is the answer to "what was the
engine doing at 14:03:07" without any profiler attached — vLLM's
stat-logger loop and Orca's iteration-level scheduling both treat the
iteration as the unit of observability, and so does this.

Design constraints (enforced by the DYN-R004 dynlint rule):
- `append()` and everything it calls run on the engine STEP thread —
  no blocking I/O, no locks shared with slow consumers, no allocation
  beyond the record itself. The ring is a preallocated list; EWMA math
  is a few floats; anomaly dumps hand a snapshot to a daemon thread via
  `put_nowait` and drop on overflow.
- Readers (`snapshot()`, the /debug/timeline exporter) tolerate torn
  reads: records are immutable once appended, so the worst case is a
  just-overwritten slot appearing once, never a half-written record.

Anomaly trigger: per-kind EWMA of iteration wall time; an iteration
exceeding `ewma * anomaly_k` (after `anomaly_min_samples` warmup) fires
ONCE per excursion — the trigger re-arms only after a sub-threshold
iteration of the same kind, so a sustained stall produces one dump, not
one per iteration. A fired trigger snapshots the last N records to the
dump queue; the daemon thread writes them as JSON under
`anomaly_dump_dir` and (optionally) opens a `jax.profiler` capture
window so the NEXT stall of a recurring pathology lands in a real trace.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional

log = logging.getLogger("dynamo_tpu.flight_recorder")


@dataclass(slots=True)
class IterationRecord:
    """One engine iteration, as the scheduler composed and the runner
    executed it. All counters that read "cumulative" are monotonically
    increasing process totals sampled at append time (deltas between
    consecutive records give per-iteration rates)."""

    seq: int               # engine iteration number (monotonic)
    ts: float              # wall clock (time.time()) at iteration start
    wall_s: float          # dispatch + host-sync wall time
    kind: str              # "prefill" | "decode" | "mixed"
    decode_seqs: int       # decode batch rows this iteration
    decode_steps: int      # fused decode steps (T)
    n_chunks: int          # packed prefill chunks served
    chunk_tokens: int      # real prefill tokens served
    charged_tokens: int    # tokens the dispatch was CHARGED for (padding
    #   and bucket round-up included; == chunk_tokens when unknowable)
    ragged: bool           # ragged flat-token program vs padded fallback
    fused: bool            # one fused dispatch vs decode+prefill halves
    n_waiting: int         # admission queue depth after the step
    n_running: int
    kv_usage: float        # G1 device pool occupancy fraction
    g2_blocks: int         # host-tier resident blocks (0 = tier off)
    g3_blocks: int         # disk-tier resident blocks (0 = tier off)
    prefetch_hits: int     # cumulative prefetched-block claims
    compile_variants: int  # cumulative compiled jit variants (all families)
    compile_calls: int     # cumulative jitted calls (calls - variants
    #   growth = compile-cache hits)
    anomaly: bool = False  # this iteration fired the EWMA trigger
    # speculative decoding: mean tokens emitted per speculating row this
    # iteration (accepted drafts + the verified/bonus token; 0.0 when no
    # row speculated) — the per-step multi-token factor the ITL spine
    # divides by, surfaced in the fleet digest
    accepted_per_step: float = 0.0
    # agentic session-tree serving
    guided_rows: int = 0       # constraint-masked decode rows this iteration
    tree_hit_blocks: int = 0   # cumulative blocks served warm by match_prefix
    forks: int = 0             # cumulative fork-on-branch fan-outs
    # causal tracing: trace ids of the requests this iteration served
    # (bounded by the engine at append time) — joins the per-iteration
    # timeline to the distributed span rings and incident bundles
    trace_ids: List[str] = field(default_factory=list)


@dataclass
class _AnomalyDump:
    """Snapshot handed to the writer thread when the trigger fires."""

    fired_ts: float
    trigger: IterationRecord
    ewma_s: float
    k: float
    records: List[IterationRecord] = field(default_factory=list)


class FlightRecorder:
    """Fixed-size iteration ring + EWMA anomaly trigger.

    `capacity <= 0` builds a disabled recorder: `append()` is a no-op
    and every surface reports empty — the A/B knob for the overhead
    bench and the `--recorder-size 0` worker flag."""

    def __init__(
        self,
        capacity: int = 4096,
        *,
        anomaly_k: float = 4.0,        # fire when wall > ewma * k (0 = off)
        anomaly_min_samples: int = 32,  # per-kind warmup before arming
        anomaly_dump_dir: Optional[str] = None,  # None = count, don't dump
        anomaly_dump_last_n: int = 256,
        anomaly_profile_ms: int = 0,   # >0: jax.profiler window per dump
        ewma_alpha: float = 0.05,
    ):
        self.capacity = max(0, int(capacity))
        self.enabled = self.capacity > 0
        self._ring: List[Optional[IterationRecord]] = [None] * self.capacity
        self._n = 0  # total records ever appended
        self.anomaly_k = float(anomaly_k)
        self.anomaly_min_samples = int(anomaly_min_samples)
        self.anomaly_dump_dir = anomaly_dump_dir
        self.anomaly_dump_last_n = int(anomaly_dump_last_n)
        self.anomaly_profile_ms = int(anomaly_profile_ms)
        self._alpha = float(ewma_alpha)
        self._ewma: Dict[str, float] = {}      # kind -> smoothed wall_s
        self._ewma_n: Dict[str, int] = {}      # kind -> samples folded in
        self._armed: Dict[str, bool] = {}      # kind -> trigger re-armed
        self.anomalies_fired = 0
        self.dumps_written = 0
        self.dumps_dropped = 0   # writer queue full at fire time
        self._dump_q: "queue.Queue[_AnomalyDump]" = queue.Queue(maxsize=4)
        self._dump_thread: Optional[threading.Thread] = None
        # metrics are bind-time optional (worker_common re-homes them onto
        # the status-port hierarchy); None until bound
        self._m_anomalies = None
        # anomaly-fire hooks (incident capture arming): called on the STEP
        # thread with the triggering record — handlers must be hand-off
        # cheap (put_nowait into their own queue), never blocking I/O
        self._anomaly_hooks: List[Any] = []

    def on_anomaly(self, cb) -> None:
        """Register cb(rec: IterationRecord) fired when the EWMA trigger
        trips. Runs on the engine step thread — the handler must hand off
        (DYN-R004 applies to it exactly like it applies to append)."""
        self._anomaly_hooks.append(cb)

    def bind_metrics(self, metrics) -> None:
        """Re-home the fired-dumps counter onto a shared MetricsHierarchy
        (the worker calls this with runtime.metrics at serve time)."""
        node = metrics.child(dynamo_component="flight_recorder")
        self._m_anomalies = node.counter(
            "flight_recorder_anomalies_total",
            "iterations that exceeded the EWMA*k wall-time threshold")

    # -- hot path (engine step thread; DYN-R004: no blocking I/O) ----------
    def append(self, rec: IterationRecord) -> None:
        if not self.enabled:
            return
        self._record_anomaly(rec)
        self._ring[self._n % self.capacity] = rec
        self._n += 1

    def _record_anomaly(self, rec: IterationRecord) -> None:
        """EWMA threshold check + fire-once-per-excursion bookkeeping.
        Runs on the step thread: the dump itself is handed off via
        put_nowait and written elsewhere."""
        if self.anomaly_k <= 0.0:
            return
        kind = rec.kind
        ewma = self._ewma.get(kind)
        n = self._ewma_n.get(kind, 0)
        if (ewma is not None and n >= self.anomaly_min_samples
                and rec.wall_s > ewma * self.anomaly_k):
            if self._armed.get(kind, True):
                self._armed[kind] = False
                rec.anomaly = True
                self.anomalies_fired += 1
                if self._m_anomalies is not None:
                    self._m_anomalies.inc()
                if self.anomaly_dump_dir:
                    dump = _AnomalyDump(
                        fired_ts=rec.ts, trigger=rec, ewma_s=ewma,
                        k=self.anomaly_k,
                        records=self.snapshot(self.anomaly_dump_last_n),
                    )
                    try:
                        self._dump_q.put_nowait(dump)
                    except queue.Full:
                        self.dumps_dropped += 1
                    self._ensure_dump_thread()
                for hook in self._anomaly_hooks:
                    try:
                        hook(rec)
                    except Exception:  # pragma: no cover
                        log.exception("anomaly hook failed")
            # anomalous samples do NOT move the EWMA: the baseline keeps
            # tracking steady state so a sustained stall stays anomalous
            return
        self._armed[kind] = True
        if ewma is None:
            self._ewma[kind] = rec.wall_s
        else:
            self._ewma[kind] = ewma + self._alpha * (rec.wall_s - ewma)
        self._ewma_n[kind] = n + 1

    def _ensure_dump_thread(self) -> None:
        if self._dump_thread is None or not self._dump_thread.is_alive():
            self._dump_thread = threading.Thread(
                target=self._dump_loop, name="flight-recorder-dump",
                daemon=True)
            self._dump_thread.start()

    # -- readers / cold path ------------------------------------------------
    def __len__(self) -> int:
        return min(self._n, self.capacity)

    @property
    def total_appended(self) -> int:
        return self._n

    def snapshot(self, last_n: Optional[int] = None) -> List[IterationRecord]:
        """Oldest-to-newest copy of the ring (or its last `last_n`
        records). Tolerates concurrent appends: a record overwritten
        mid-read is simply the newer one."""
        if not self.enabled:
            return []
        n = self._n
        count = min(n, self.capacity)
        if last_n is not None:
            count = min(count, max(0, int(last_n)))
        out: List[IterationRecord] = []
        for i in range(n - count, n):
            rec = self._ring[i % self.capacity]
            if rec is not None:
                out.append(rec)
        return out

    def to_chrome_trace(self, last_n: Optional[int] = None,
                        pid: int = 0) -> Dict[str, Any]:
        return to_chrome_trace(self.snapshot(last_n), pid=pid)

    def stats(self) -> Dict[str, Any]:
        """One-line counters for goodput extras / status surfaces."""
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "appended": self._n,
            "anomalies_fired": self.anomalies_fired,
            "dumps_written": self.dumps_written,
            "dumps_dropped": self.dumps_dropped,
            "ewma_s": {k: round(v, 6) for k, v in self._ewma.items()},
        }

    # -- dump plane (daemon thread: blocking I/O is fine here) --------------
    def _dump_loop(self) -> None:
        while True:
            try:
                dump = self._dump_q.get(timeout=30.0)
            except queue.Empty:
                return  # idle: let the thread die; refired on next anomaly
            try:
                self._write_dump(dump)
                self.dumps_written += 1
            except OSError:
                log.warning("anomaly dump write failed", exc_info=True)
            if self.anomaly_profile_ms > 0:
                self._profile_window()

    def _write_dump(self, dump: _AnomalyDump) -> str:
        os.makedirs(self.anomaly_dump_dir, exist_ok=True)
        path = os.path.join(
            self.anomaly_dump_dir,
            f"flight_dump_{dump.trigger.seq:08d}.json")
        payload = {
            "fired_ts": dump.fired_ts,
            "ewma_s": dump.ewma_s,
            "k": dump.k,
            "trigger_seq": dump.trigger.seq,
            # the trigger record itself: the ring snapshot was taken
            # before the trigger was appended, so it rides separately
            "trigger": asdict(dump.trigger),
            "records": [asdict(r) for r in dump.records],
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f)
        os.replace(tmp, path)
        return path

    def _profile_window(self) -> None:
        """Best-effort jax.profiler capture window after a dump: the
        recurring pathology's NEXT occurrence lands in a real device
        trace. Off unless anomaly_profile_ms > 0; harmless in mocker
        processes where jax is absent."""
        try:
            import jax

            prof_dir = os.path.join(self.anomaly_dump_dir or ".",
                                    "anomaly_profile")
            jax.profiler.start_trace(prof_dir)
            time.sleep(self.anomaly_profile_ms / 1000.0)
            jax.profiler.stop_trace()
        except Exception:
            log.debug("anomaly profiler window unavailable", exc_info=True)


# -- Perfetto / Chrome-trace export -----------------------------------------

# track (tid) layout inside the engine process
_TID_SCHED = 0
_TID_DISPATCH = 1
_TID_SAMPLE = 2
_TID_KV = 3


def to_chrome_trace(records: List[IterationRecord],
                    pid: int = 0) -> Dict[str, Any]:
    """Render iteration records as Chrome-trace JSON (chrome://tracing /
    Perfetto "Open trace file"). Tracks: scheduler (queue counters),
    dispatch (one X slice per iteration), sample (emitted-token counter +
    anomaly instants), kv (tier occupancy counters). Every event carries
    the required ph/ts/pid/name keys; timestamps are wall-clock
    microseconds."""
    events: List[Dict[str, Any]] = [
        {"ph": "M", "ts": 0, "pid": pid, "name": "process_name",
         "args": {"name": "dynamo_tpu engine"}},
    ]
    for tid, tname in ((_TID_SCHED, "scheduler"), (_TID_DISPATCH, "dispatch"),
                       (_TID_SAMPLE, "sample"), (_TID_KV, "kv tiers")):
        events.append({"ph": "M", "ts": 0, "pid": pid, "tid": tid,
                       "name": "thread_name", "args": {"name": tname}})
    for rec in records:
        ts_us = rec.ts * 1e6
        events.append({
            "ph": "X", "ts": ts_us, "dur": max(0.0, rec.wall_s) * 1e6,
            "pid": pid, "tid": _TID_DISPATCH, "name": rec.kind,
            "args": {
                "seq": rec.seq,
                "decode_seqs": rec.decode_seqs,
                "decode_steps": rec.decode_steps,
                "n_chunks": rec.n_chunks,
                "chunk_tokens": rec.chunk_tokens,
                "charged_tokens": rec.charged_tokens,
                "ragged": rec.ragged,
                "fused": rec.fused,
                "compile_variants": rec.compile_variants,
                "compile_calls": rec.compile_calls,
                "trace_ids": list(getattr(rec, "trace_ids", []) or []),
            },
        })
        events.append({
            "ph": "C", "ts": ts_us, "pid": pid, "tid": _TID_SCHED,
            "name": "queue",
            "args": {"waiting": rec.n_waiting, "running": rec.n_running},
        })
        events.append({
            "ph": "C", "ts": ts_us, "pid": pid, "tid": _TID_SAMPLE,
            "name": "scheduled_tokens",
            "args": {"tokens": rec.decode_seqs * rec.decode_steps
                     + rec.chunk_tokens},
        })
        events.append({
            "ph": "C", "ts": ts_us, "pid": pid, "tid": _TID_KV,
            "name": "kv",
            "args": {"g1_usage": rec.kv_usage, "g2_blocks": rec.g2_blocks,
                     "g3_blocks": rec.g3_blocks,
                     "prefetch_hits": rec.prefetch_hits},
        })
        if rec.anomaly:
            events.append({
                "ph": "i", "ts": ts_us, "pid": pid, "tid": _TID_SAMPLE,
                "name": "anomaly", "s": "p",
                "args": {"wall_s": rec.wall_s, "kind": rec.kind},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
