"""Generic pipeline graph: declarative operator chains over AsyncEngines.

Analog of the reference's Source/Operator/Sink pipeline nodes
(lib/runtime/src/pipeline.rs:8-29 and the linking at
entrypoint/input/common.rs:498-519). In this framework every pipeline
stage is an AsyncEngine wrapping an inner AsyncEngine, so a chain is
fully described by an ordered list of *stage specs*: (name, condition,
factory). `build_chain` folds them right-to-left onto a sink engine and
returns a `Chain` that serves from the head, exposes the built stages by
name (the frontend needs e.g. the PrefillRouter to activate/deactivate
it on discovery events), and tears them down in build order.

This replaces hand-splicing each new operator into the frontend's chain
assembly: a new operator is one list entry with its enabling condition,
and per-model variation (vision → encoder stage, affinity configured →
affinity stage) is data, not control flow.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Dict, List, Optional

from dynamo_tpu.runtime.engine import AsyncEngine


@dataclass
class StageSpec:
    """One prospective operator in a chain.

    factory(inner, ctx) -> AsyncEngine — wraps the downstream engine.
    enabled(ctx) -> bool — stage is skipped entirely when False.
    teardown(built) -> Optional[awaitable-factory] — how to close the
    built stage; default looks for `.stop`/`.close` on the instance.
    """

    name: str
    factory: Callable[[AsyncEngine, Any], AsyncEngine]
    enabled: Callable[[Any], bool] = lambda ctx: True


class Chain(AsyncEngine):
    """A built operator chain. `generate` enters at the head (first
    enabled stage); `stages` maps name → built engine for the operators
    that were enabled."""

    def __init__(self, head: AsyncEngine, stages: Dict[str, AsyncEngine],
                 order: List[str], extra_teardown: Any = None,
                 sink: Optional[AsyncEngine] = None):
        self.head = head
        self.stages = stages
        self.order = order  # head-first stage names (diagnostics)
        self.sink = sink  # the egress engine the specs folded onto
        self._extra_teardown = extra_teardown

    async def generate(self, request: Any, context: Any) -> AsyncIterator[Any]:
        async for item in self.head.generate(request, context):
            yield item

    def get(self, name: str) -> Optional[AsyncEngine]:
        return self.stages.get(name)

    async def teardown(self) -> None:
        """Close stages head-first (upstream stops feeding downstream),
        then the sink's teardown. A stage participates by exposing
        `stop` or `close` (async)."""
        for name in self.order:
            stage = self.stages[name]
            closer = getattr(stage, "stop", None) or getattr(stage, "close", None)
            if closer is not None:
                await closer()
        if self._extra_teardown is not None:
            await self._extra_teardown()


def build_chain(specs: List[StageSpec], sink: AsyncEngine, ctx: Any,
                sink_teardown: Any = None) -> Chain:
    """Fold stage specs (listed head-first) onto `sink`.

    specs[0] is the outermost operator (sees requests first); `sink` is
    the egress (typically the router/push engine); `sink_teardown` is an
    async callable closing sink-owned resources, run last."""
    built: Dict[str, AsyncEngine] = {}
    order: List[str] = []
    inner = sink
    for spec in reversed(specs):
        if not spec.enabled(ctx):
            continue
        inner = spec.factory(inner, ctx)
        built[spec.name] = inner
        order.insert(0, spec.name)
    return Chain(inner, built, order, extra_teardown=sink_teardown, sink=sink)
