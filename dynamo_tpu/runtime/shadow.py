"""Shadow (active/passive) engine failover.

TPU-native analog of the reference's Shadow Engine Failover
(docs/kubernetes/shadow-engine-failover.md): a standby worker pays the
expensive startup — weight load (orbax fast-restart snapshot), jit
compilation, KV-pool allocation — up front, then waits WITHOUT serving.
When the active instance's discovery record disappears (lease expiry on
crash, delete on shutdown), the shadow promotes itself by registering the
already-warm engine, so recovery skips the model (re)load exactly like the
reference's GMS-attached standby skips it on GPU.

The reference gates promotion on GPU Memory Service + DRA (same-node
weight residency); on TPU the warm state is the shadow's own HBM, so the
shadow is a full process and promotion is a discovery-record flip.

Standbys register a `standby/...` record (lease-bound) for observability:
operators and the planner can see a shadow exists without it taking
traffic.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, Optional

from dynamo_tpu.runtime.component import EndpointAddress, Instance, new_instance_id

log = logging.getLogger("dynamo_tpu.runtime.shadow")


class ShadowServer:
    """Holds a warm engine; serves `path` only once no active instance
    remains. `start()` returns immediately; `promoted` resolves when the
    shadow went live (tests/await points)."""

    def __init__(
        self,
        runtime,
        path: str,
        handler: Any = None,
        metadata: Optional[Dict[str, Any]] = None,
        poll_s: float = 0.25,
        activate=None,  # async callable run on promotion instead of
        #   serve_endpoint(handler) — lets a full worker (multiple
        #   endpoints, publishers) arm itself as one shadow unit
    ):
        self.runtime = runtime
        self.path = path
        self.handler = handler
        self.activate = activate
        self.metadata = metadata or {}
        self.poll_s = poll_s
        self.promoted: asyncio.Future = asyncio.get_event_loop().create_future()
        self.instance: Optional[Instance] = None
        self._standby: Optional[Instance] = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> None:
        addr = EndpointAddress.parse(self.path)
        # lease-bound standby record: visible, never routed (different
        # discovery prefix than services/)
        self._standby = Instance(
            namespace=addr.namespace,
            component=addr.component,
            endpoint=addr.endpoint,
            instance_id=new_instance_id(),
            metadata={**self.metadata, "role": "shadow"},
        )
        # Instance.path is a property pinned to services/, so register a
        # shallow proxy whose key lives under standby/ instead.
        standby = _StandbyRecord(self._standby)
        await self.runtime.discovery.register(standby)
        self._task = asyncio.create_task(self._watch_loop(standby))

    async def _watch_loop(self, standby) -> None:
        """Track live actives via the discovery watch (push-style DELETE on
        lease expiry — no poll load, failover latency = event latency).
        Promotion requires having SEEN an active first: a shadow that wins
        the startup race against its active must not grab the slot (that
        would yield two actives and no standby). Transient discovery errors
        retry with backoff — a shadow that silently stops watching is a
        fleet with no failover."""
        prefix = f"services/{self.path}/"
        seen_active = False  # persists across watch retries: an active
        # that dies while the stream is broken must still trigger promotion
        while True:
            alive: set = set()
            try:
                async for ev in self.runtime.discovery.watch(prefix):
                    if ev.kind == "put":
                        seen_active = True
                        alive.add(ev.instance.instance_id)
                    else:
                        alive.discard(ev.instance.instance_id)
                    if seen_active and not alive:
                        if await self._try_promote(standby):
                            return
                        break  # another shadow won: re-arm on a new watch
                # watch stream ended without promotion: resync and retry
            except asyncio.CancelledError:
                raise
            except Exception as e:
                if self.promoted.done():
                    return  # promotion already happened/failed terminally
                log.warning(
                    "shadow watch for %s errored (%s); retrying", self.path, e
                )
            await asyncio.sleep(self.poll_s)
            if seen_active:
                # the death may have happened during the outage — the new
                # watch's replay of an empty prefix yields no events, so
                # check explicitly before re-arming
                try:
                    if not await self.runtime.discovery.list_instances(prefix):
                        if await self._try_promote(standby):
                            return
                except asyncio.CancelledError:
                    raise
                except Exception:
                    if self.promoted.done():
                        return  # promotion failed terminally
                    # else discovery still down; next retry

    async def _try_promote(self, standby) -> bool:
        """Promotion election without a CAS primitive (mem/file backends
        have none): shadows order themselves by their standby records'
        instance ids — rank 0 promotes immediately, rank k waits k
        stagger periods and stands down if an active appeared. Best-effort
        (a brief dual-active under partition converges when the loser's
        next watch sees the winner), same class of window the reference
        lock acquisition documents."""
        rank = 0
        try:
            sbs = await self.runtime.discovery.list_instances(
                f"standby/{self.path}/"
            )
            ids = sorted(i.instance_id for i in sbs)
            me = self._standby.instance_id
            rank = ids.index(me) if me in ids else len(ids)
        except Exception:
            log.debug("standby rank probe failed; assuming rank 0",
                      exc_info=True)
        if rank > 0:
            # wait for the lower-ranked shadow to win: promotion serves the
            # endpoint BEFORE dropping the standby record (see _promote), so
            # while the winner is mid-promotion we still see its standby
            # entry — there is no instant where a live winner is invisible.
            # We promote early only if we BECOME rank 0 (dead peers' standby
            # leases expire and reap their records); a long deadline remains
            # as an availability fallback for a live-but-wedged peer (brief
            # dual-active converges, the documented best-effort semantics).
            import time as _time

            deadline = (
                _time.monotonic() + rank * max(2 * self.poll_s, 0.5) + 10.0
            )
            me = self._standby.instance_id
            while _time.monotonic() < deadline:
                try:
                    # standby BEFORE services: _promote serves first and
                    # drops the standby record second, so a winner absent
                    # from standby has necessarily already registered its
                    # service — a services check issued AFTER the standby
                    # read must see it. The reverse order had a TOCTOU:
                    # winner completes both steps between our two reads
                    # and we'd see empty-services + rank-0 → dual-active.
                    sbs = await self.runtime.discovery.list_instances(
                        f"standby/{self.path}/"
                    )
                    ids = sorted(i.instance_id for i in sbs)
                    if await self.runtime.discovery.list_instances(
                        f"services/{self.path}/"
                    ):
                        return False  # a lower-ranked shadow promoted
                    if me in ids and ids.index(me) == 0:
                        break  # lower-ranked peers are gone: my turn
                except Exception:
                    return False  # can't verify; don't double-promote
                await asyncio.sleep(max(self.poll_s, 0.1))
        await self._promote(standby)
        return True

    async def _promote(self, standby) -> None:
        log.warning("shadow promoting for %s (active gone)", self.path)
        # serve FIRST, drop the standby record SECOND: higher-ranked
        # shadows must never observe a live winner as absent from BOTH
        # lists (that gap is a double-promotion window); a moment of
        # active+standby overlap is harmless, and on serve failure the
        # standby record survives so this shadow stays armed
        try:
            if self.activate is not None:
                self.instance = await self.activate()
            else:
                self.instance = await self.runtime.serve_endpoint(
                    self.path, self.handler, metadata=self.metadata
                )
        except Exception as e:
            log.exception("shadow promotion for %s FAILED", self.path)
            if not self.promoted.done():
                self.promoted.set_exception(e)
            raise
        for attempt in range(3):  # a stale standby record misleads the
            # planner/operators, so retry the unregister briefly; the
            # lease bound to it still reaps it if all retries fail
            try:
                await self.runtime.discovery.unregister(standby)
                break
            except Exception:
                await asyncio.sleep(0.2 * (attempt + 1))
        if not self.promoted.done():
            self.promoted.set_result(self.instance)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            except Exception:
                log.debug("shadow watch task exited with error",
                          exc_info=True)


class _StandbyRecord:
    """Instance proxy whose discovery key lives under standby/ instead of
    services/, so clients and routers never select it."""

    def __init__(self, inst: Instance):
        self._inst = inst

    @property
    def path(self) -> str:
        i = self._inst
        return (
            f"standby/{i.namespace}/{i.component}/{i.endpoint}/{i.instance_id:x}"
        )

    def to_dict(self) -> Dict[str, Any]:
        return self._inst.to_dict()

    def __getattr__(self, name):
        return getattr(self._inst, name)


async def serve_shadow(
    runtime,
    path: str,
    handler: Any,
    metadata: Optional[Dict[str, Any]] = None,
    poll_s: float = 0.25,
) -> ShadowServer:
    """Arm a shadow for `path`: engine stays warm, promotion happens when
    the last active instance disappears from discovery."""
    s = ShadowServer(runtime, path, handler, metadata, poll_s)
    await s.start()
    return s
