"""Distributed runtime core (analog of reference lib/runtime, Rust).

Provides the DistributedRuntime handle, the Namespace→Component→Endpoint
addressing model, pluggable discovery, the TCP/msgpack request plane, the
ZMQ event plane, streaming engines with cancellation, and metrics.
"""

from dynamo_tpu.runtime.context import Context, CancellationError
from dynamo_tpu.runtime.engine import AsyncEngine, EngineStream
from dynamo_tpu.runtime.component import (
    Instance,
    EndpointAddress,
    TransportKind,
)
from dynamo_tpu.runtime.discovery import (
    DiscoveryBackend,
    MemDiscovery,
    FileDiscovery,
    DiscoveryEvent,
    make_discovery,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.tasks import spawn_tracked, tracked_count

__all__ = [
    "Context",
    "CancellationError",
    "AsyncEngine",
    "EngineStream",
    "Instance",
    "EndpointAddress",
    "TransportKind",
    "DiscoveryBackend",
    "MemDiscovery",
    "FileDiscovery",
    "DiscoveryEvent",
    "make_discovery",
    "DistributedRuntime",
    "spawn_tracked",
    "tracked_count",
]
