"""Shared Kubernetes REST bootstrap.

One implementation of the in-cluster client conventions used by every
control-plane piece (operator, planner KubernetesConnector, KubeDiscovery):
service-account token + CA bundle, api-base resolution from the in-cluster
env, and a lazily created aiohttp session with bearer auth. The reference
operator gets this from client-go; here it is the plain REST equivalent.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class KubeApiClient:
    def __init__(
        self,
        api_base: Optional[str] = None,
        token: Optional[str] = None,
        ca_verify: bool = True,
    ):
        if api_base is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in a cluster (KUBERNETES_SERVICE_HOST unset) and no "
                    "api_base given"
                )
            api_base = f"https://{host}:{port}"
        if token is None and os.path.exists(f"{SA_DIR}/token"):
            token = Path(f"{SA_DIR}/token").read_text().strip()
        self.api_base = api_base.rstrip("/")
        self.token = token
        # in-cluster apiserver certs are signed by the cluster CA, not the
        # system trust store — verify against the mounted bundle
        self._ssl = True if ca_verify else False
        if ca_verify and os.path.exists(f"{SA_DIR}/ca.crt"):
            import ssl as _ssl

            self._ssl = _ssl.create_default_context(cafile=f"{SA_DIR}/ca.crt")
        self._session = None

    async def http(self):
        if self._session is None:
            import aiohttp

            headers = {}
            if self.token:
                headers["Authorization"] = f"Bearer {self.token}"
            self._session = aiohttp.ClientSession(
                headers=headers,
                connector=aiohttp.TCPConnector(ssl=self._ssl),
            )
        return self._session

    async def close(self) -> None:
        # claim before the await: concurrent close() double-closing the
        # session is the DYN-A007 check-then-act hazard
        session, self._session = self._session, None
        if session is not None:
            await session.close()
