"""CPU compute offload pool (analog of reference lib/runtime/src/compute/:
pool + timing/validation macros).

The asyncio event loop is the request plane: every frame, SSE chunk and
discovery event flows through it. CPU-bound work — chat-template
rendering, tokenizing a 100k-char prompt, detokenization bursts — stalls
every in-flight stream while it runs inline. The ComputePool pushes such
work onto a bounded thread pool with per-call wall-time metrics, and only
when it is worth it: small inputs stay inline (a thread hop costs more
than tokenizing a tweet).
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import logging
import os
import time
from typing import Any, Callable, Optional

log = logging.getLogger("dynamo_tpu.runtime.compute")

# inputs smaller than this run inline: the pool exists to keep the event
# loop responsive under BIG payloads, not to tax every call with a hop
DEFAULT_OFFLOAD_THRESHOLD = 4096


class ComputePool:
    def __init__(
        self,
        max_workers: Optional[int] = None,
        metrics=None,
        offload_threshold: int = DEFAULT_OFFLOAD_THRESHOLD,
    ):
        workers = max_workers or int(
            os.environ.get("DYN_COMPUTE_WORKERS", min(4, os.cpu_count() or 1))
        )
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="dyn-compute"
        )
        self.metrics = metrics
        self.offload_threshold = offload_threshold
        self.stats = {"offloaded": 0, "inline": 0}

    async def run(
        self, fn: Callable, *args: Any, size_hint: Optional[int] = None, **kw: Any
    ) -> Any:
        """Run fn(*args, **kw): inline when the size hint says it's cheap,
        on the pool otherwise. Exceptions propagate unchanged either way."""
        if size_hint is not None and size_hint < self.offload_threshold:
            self.stats["inline"] += 1
            return fn(*args, **kw)
        self.stats["offloaded"] += 1
        t0 = time.monotonic()
        loop = asyncio.get_running_loop()
        try:
            return await loop.run_in_executor(
                self._pool, lambda: fn(*args, **kw)
            )
        finally:
            if self.metrics is not None:
                self.metrics.histogram(
                    "compute_offload_seconds", "offloaded compute wall time",
                    op=getattr(fn, "__name__", "fn"),
                ).observe(time.monotonic() - t0)

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)
