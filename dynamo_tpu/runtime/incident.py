"""Black-box incident forensics: an armed capturer that snapshots a
correlated evidence bundle the moment the fleet goes wrong.

Production incidents die of evidence loss: by the time a human looks,
the flight-recorder ring has rotated, the span ring has evicted the
breaching window, and the routing audit no longer remembers who sent
the victim requests where. The `IncidentCapturer` inverts that: it is
armed up front with *sources* — zero-cost callables that snapshot live
state (SLO view, span ring, recorder rings, routing audits, actuator
journal, KV-link EWMAs, fleet digest window) — and a *trigger* that any
watchdog may pull (SLO BREACH transition, sanitizer hard violation,
flight-recorder anomaly excursion). On trigger it writes one versioned
JSONL bundle joining all of it, rate-limited and disk-bounded.

Threading contract (DYN-R004): `trigger()` is safe from ANY thread —
the engine step thread's anomaly hook, the event loop's SLO watch — and
never blocks: the rate-limit check is a lock-guarded clock compare and
the hand-off is a `queue.put_nowait`. Gathering and writing happen on
one daemon writer thread; sources therefore must be snapshot-style reads
(ring copies, dict reads — GIL-atomic), never loop-affine awaits.

Bundle format (`dynamo_tpu.incident/v1`), one JSONL file per incident:

    line 1   header {"v": 1, "schema", "reason", "ts", "seq",
                     "detail", "sections": [names...]}
    line 2+  one line per section {"section": name, "data": ...}
             (a failing source records {"section": name, "error": ...}
             instead — one bad source never voids the bundle)

`read_bundle` is the inverse; `scripts/dyn_incident.py` inspects and
replays bundles through a calibrated FleetSim fork.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import time
from dataclasses import asdict, is_dataclass
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger("dynamo_tpu.incident")

BUNDLE_VERSION = 1
BUNDLE_SCHEMA = "dynamo_tpu.incident/v1"
BUNDLE_PREFIX = "incident-"
BUNDLE_SUFFIX = ".jsonl"


def _key(k: Any) -> str:
    """JSON object keys: Worker tuples become 'iid.endpoint' strings —
    the same join key /debug/fleet uses."""
    if isinstance(k, str):
        return k
    if isinstance(k, tuple):
        return ".".join(str(p) for p in k)
    return str(k)


def jsonable(obj: Any) -> Any:
    """Recursively coerce live snapshot objects (dataclasses, tuple-keyed
    dicts, sets) into plain JSON values. Unknown leaves degrade to repr —
    a bundle must never fail to serialize."""
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if is_dataclass(obj) and not isinstance(obj, type):
        return jsonable(asdict(obj))
    if isinstance(obj, dict):
        return {_key(k): jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [jsonable(v) for v in obj]
    return repr(obj)


class IncidentCapturer:
    """Armed bundle writer: `register()` evidence sources once, then any
    watchdog `trigger()`s. Rate-limited (`min_interval_s` between
    accepted triggers), disk-bounded (`max_bundles` newest kept)."""

    def __init__(self, out_dir: str, *, min_interval_s: float = 5.0,
                 max_bundles: int = 16):
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.min_interval_s = float(min_interval_s)
        self.max_bundles = max(1, int(max_bundles))
        self._sources: Dict[str, Callable[[], Any]] = {}
        self._lock = threading.Lock()  # guards clock/seq/counters
        self._last_ts: Optional[float] = None  # monotonic, last ACCEPTED
        self._seq = 0
        self._closed = False
        self.captured = 0    # bundles fully written
        self.suppressed = 0  # triggers dropped by rate limit / full queue
        self.errors = 0      # source or serialization failures (non-fatal)
        self._q: "queue.Queue" = queue.Queue(maxsize=8)
        self._thread = threading.Thread(
            target=self._run, name="dyn-incident-writer", daemon=True)
        self._thread.start()

    # -- arming ------------------------------------------------------------
    def register(self, name: str, fn: Callable[[], Any]) -> None:
        """Attach an evidence source. Registration order = bundle section
        order. Sources run on the writer thread: snapshot reads only."""
        self._sources[str(name)] = fn

    # -- the trigger (any thread, never blocks) ----------------------------
    def trigger(self, reason: str, detail: Optional[Dict[str, Any]] = None
                ) -> bool:
        """Pull the capture cord. Returns True if a bundle was enqueued,
        False if suppressed (rate limit, closed, or writer backlog)."""
        now = time.monotonic()
        with self._lock:
            if self._closed:
                return False
            if (self._last_ts is not None
                    and now - self._last_ts < self.min_interval_s):
                self.suppressed += 1
                return False
            self._last_ts = now
            self._seq += 1
            seq = self._seq
        try:
            self._q.put_nowait((seq, str(reason), dict(detail or {}),
                                time.time()))
        except queue.Full:
            with self._lock:
                self.suppressed += 1
                # the slot was not used — give it back so the next
                # trigger after the backlog drains is not rate-limited
                self._last_ts = None
            return False
        return True

    # -- writer thread -----------------------------------------------------
    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self._write_bundle(*item)
            except Exception:
                with self._lock:
                    self.errors += 1
                log.exception("incident bundle write failed")

    def _write_bundle(self, seq: int, reason: str,
                      detail: Dict[str, Any], ts: float) -> None:
        lines: List[str] = []
        names: List[str] = []
        for name, fn in list(self._sources.items()):
            try:
                data = jsonable(fn())
                line = json.dumps({"section": name, "data": data})
            except Exception as e:
                with self._lock:
                    self.errors += 1
                log.warning("incident source %r failed: %r", name, e)
                line = json.dumps({"section": name, "error": repr(e)})
            lines.append(line)
            names.append(name)
        header = {
            "v": BUNDLE_VERSION,
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "ts": ts,
            "seq": seq,
            "detail": jsonable(detail),
            "sections": names,
        }
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(ts))
        fname = f"{BUNDLE_PREFIX}{stamp}-{seq:04d}-{reason}{BUNDLE_SUFFIX}"
        path = os.path.join(self.out_dir, fname)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for line in lines:
                f.write(line + "\n")
        os.replace(tmp, path)  # readers never see a half bundle
        with self._lock:
            self.captured += 1
        log.warning("incident bundle captured: %s (reason=%s, %d sections)",
                    path, reason, len(names))
        self._prune()

    def _prune(self) -> None:
        names = sorted(
            n for n in os.listdir(self.out_dir)
            if n.startswith(BUNDLE_PREFIX) and n.endswith(BUNDLE_SUFFIX))
        for n in names[:max(0, len(names) - self.max_bundles)]:
            try:
                os.unlink(os.path.join(self.out_dir, n))
            except OSError:
                log.debug("bundle prune failed: %s", n, exc_info=True)

    # -- lifecycle / views -------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "captured": self.captured,
                "suppressed": self.suppressed,
                "errors": self.errors,
                "pending": self._q.qsize(),
                "min_interval_s": self.min_interval_s,
                "max_bundles": self.max_bundles,
                "dir": self.out_dir,
            }

    def close(self, timeout_s: float = 5.0) -> None:
        """Drain the writer (in-flight bundles finish) and stop. Stats
        stay readable after close; triggers are refused."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._q.put(None)
        self._thread.join(timeout=timeout_s)


# -- bundle reading ---------------------------------------------------------
def list_bundles(out_dir: str) -> List[str]:
    """Bundle paths in `out_dir`, oldest first."""
    try:
        names = sorted(
            n for n in os.listdir(out_dir)
            if n.startswith(BUNDLE_PREFIX) and n.endswith(BUNDLE_SUFFIX))
    except FileNotFoundError:
        return []
    return [os.path.join(out_dir, n) for n in names]


def read_bundle(path: str) -> Dict[str, Any]:
    """Inverse of the writer: {"header": {...}, "sections": {name: data}}.
    A section that failed at capture time maps to {"error": "..."}."""
    header: Optional[Dict[str, Any]] = None
    sections: Dict[str, Any] = {}
    with open(path) as f:
        for raw in f:
            raw = raw.strip()
            if not raw:
                continue
            obj = json.loads(raw)
            if header is None:
                if obj.get("schema") != BUNDLE_SCHEMA:
                    raise ValueError(
                        f"{path}: not an incident bundle "
                        f"(schema={obj.get('schema')!r})")
                if int(obj.get("v", 0)) > BUNDLE_VERSION:
                    raise ValueError(
                        f"{path}: bundle v{obj['v']} is newer than this "
                        f"reader (v{BUNDLE_VERSION})")
                header = obj
                continue
            name = obj.get("section")
            if not name:
                continue
            sections[name] = (obj["data"] if "data" in obj
                              else {"error": obj.get("error")})
    if header is None:
        raise ValueError(f"{path}: empty bundle")
    return {"header": header, "sections": sections}
