"""Namespace → Component → Endpoint → Instance addressing model.

Analog of reference lib/runtime/src/component.rs:4-28,107-115: every
servable unit is addressed `namespace/component/endpoint`, and each live
server of that endpoint is an Instance with a unique instance_id plus the
transport address where its request-plane server listens.
"""

from __future__ import annotations

import secrets
from dataclasses import dataclass, field, asdict
from enum import Enum
from typing import Any, Dict, Optional


class TransportKind(str, Enum):
    """Request-plane transport for an instance (reference TransportType,
    component.rs:73-79 — Nats or Tcp; we add InProc for tests)."""

    TCP = "tcp"
    INPROC = "inproc"


@dataclass(frozen=True)
class EndpointAddress:
    """Logical address of an endpoint: `ns/component/endpoint`."""

    namespace: str
    component: str
    endpoint: str

    @property
    def path(self) -> str:
        return f"{self.namespace}/{self.component}/{self.endpoint}"

    @classmethod
    def parse(cls, path: str) -> "EndpointAddress":
        ns, comp, ep = path.split("/", 2)
        return cls(ns, comp, ep)

    def __str__(self) -> str:
        return self.path


def new_instance_id() -> int:
    """Random 63-bit instance id (reference uses etcd lease ids)."""
    return secrets.randbits(63)


@dataclass
class Instance:
    """A live server of an endpoint (reference Instance, component.rs:107-115)."""

    namespace: str
    component: str
    endpoint: str
    instance_id: int
    transport: TransportKind = TransportKind.TCP
    # host:port of the instance's request-plane server (TCP) or in-proc key
    address: str = ""
    # arbitrary worker metadata: model card, dp_size, kv event endpoint, ...
    metadata: Dict[str, Any] = field(default_factory=dict)

    @property
    def endpoint_address(self) -> EndpointAddress:
        return EndpointAddress(self.namespace, self.component, self.endpoint)

    @property
    def path(self) -> str:
        """Discovery key: services/{ns}/{component}/{endpoint}/{instance_id}
        (the reference uses `{endpoint}-{lease_id}`,
        docs/design-docs/distributed-runtime.md:62; we use a `/` delimiter so
        an endpoint name that prefixes another never collides in watches)."""
        return f"services/{self.namespace}/{self.component}/{self.endpoint}/{self.instance_id:x}"

    def to_dict(self) -> Dict[str, Any]:
        d = asdict(self)
        d["transport"] = self.transport.value
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Instance":
        d = dict(d)
        d["transport"] = TransportKind(d.get("transport", "tcp"))
        return cls(**d)
