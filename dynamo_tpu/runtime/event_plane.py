"""Event plane: pub/sub for KV events, load metrics (FPM), sequence sync.

Analog of reference lib/runtime/src/transports/event_plane/ with the same
default topology (docs/design-docs/event-plane.md:21-60): **brokerless ZMQ**
— each publisher binds a PUB socket and advertises its address via
discovery; subscribers watch discovery and connect SUB sockets to every
live publisher. An in-proc transport backs single-process tests.

Wire format: two ZMQ frames [subject: utf-8][payload: msgpack].
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, AsyncIterator, Callable, Dict, List, Optional, Set, Tuple

import msgpack

try:
    import zmq
    import zmq.asyncio

    _HAVE_ZMQ = True
except ImportError:  # pragma: no cover
    _HAVE_ZMQ = False

log = logging.getLogger("dynamo_tpu.event_plane")

# well-known subjects (reference lib/kv-router/src/protocols.rs KV_EVENT_SUBJECT)
KV_EVENT_SUBJECT = "kv_events"
FPM_SUBJECT = "fpm"
SEQ_SYNC_SUBJECT = "seq_sync"
# periodic per-worker observability digests (runtime/fleet_observer.py)
FLEET_DIGEST_SUBJECT = "fleet_digest"


class EventPublisher:
    """Publish (subject, payload) events. Implementations: Zmq, InProc."""

    @property
    def address(self) -> str:
        raise NotImplementedError

    async def publish(self, subject: str, payload: Any) -> None:
        raise NotImplementedError

    async def close(self) -> None:
        pass


class EventSubscriber:
    """Subscribe to subjects across a dynamic set of publisher addresses
    (the reference's dynamic_subscriber.rs: publisher set tracks discovery)."""

    def connect(self, address: str) -> None:
        raise NotImplementedError

    def disconnect(self, address: str) -> None:
        raise NotImplementedError

    async def events(self) -> AsyncIterator[Tuple[str, Any]]:
        raise NotImplementedError
        yield  # pragma: no cover

    async def close(self) -> None:
        pass


# --------------------------------------------------------------------------
# ZMQ transport (default, brokerless)
# --------------------------------------------------------------------------


class ZmqEventPublisher(EventPublisher):
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        if not _HAVE_ZMQ:  # pragma: no cover
            raise RuntimeError("pyzmq not available")
        self._ctx = zmq.asyncio.Context.instance()
        self._sock = self._ctx.socket(zmq.PUB)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.setsockopt(zmq.SNDHWM, 100_000)
        if port == 0:
            port = self._sock.bind_to_random_port(f"tcp://{host}")
        else:
            self._sock.bind(f"tcp://{host}:{port}")
        self._address = f"tcp://{host}:{port}"

    @property
    def address(self) -> str:
        return self._address

    async def publish(self, subject: str, payload: Any) -> None:
        await self._sock.send_multipart(
            [subject.encode(), msgpack.packb(payload, use_bin_type=True)]
        )

    async def close(self) -> None:
        self._sock.close(0)


class ZmqEventSubscriber(EventSubscriber):
    def __init__(self, subjects: Optional[List[str]] = None):
        if not _HAVE_ZMQ:  # pragma: no cover
            raise RuntimeError("pyzmq not available")
        self._ctx = zmq.asyncio.Context.instance()
        self._sock = self._ctx.socket(zmq.SUB)
        self._sock.setsockopt(zmq.LINGER, 0)
        self._sock.setsockopt(zmq.RCVHWM, 100_000)
        for s in subjects or [""]:
            self._sock.setsockopt(zmq.SUBSCRIBE, s.encode())
        self._connected: Set[str] = set()

    def connect(self, address: str) -> None:
        if address not in self._connected:
            self._sock.connect(address)
            self._connected.add(address)

    def disconnect(self, address: str) -> None:
        if address in self._connected:
            try:
                self._sock.disconnect(address)
            except zmq.ZMQError:
                pass
            self._connected.discard(address)

    async def events(self) -> AsyncIterator[Tuple[str, Any]]:
        while True:
            subject, payload = await self._sock.recv_multipart()
            yield subject.decode(), msgpack.unpackb(payload, raw=False)

    async def close(self) -> None:
        self._sock.close(0)


# --------------------------------------------------------------------------
# In-proc transport (tests; analog of reference `mem` transports)
# --------------------------------------------------------------------------


class _InProcBus:
    """Process-wide registry of inproc publishers keyed by address."""

    buses: Dict[str, "_InProcBus"] = {}
    _next_id = 0

    def __init__(self):
        self.subscribers: List[Tuple[Optional[Set[str]], asyncio.Queue]] = []

    @classmethod
    def create(cls) -> Tuple[str, "_InProcBus"]:
        cls._next_id += 1
        addr = f"inproc://bus-{cls._next_id}"
        bus = cls()
        cls.buses[addr] = bus
        return addr, bus

    @classmethod
    def reset(cls) -> None:
        cls.buses.clear()


class InProcEventPublisher(EventPublisher):
    def __init__(self):
        self._address, self._bus = _InProcBus.create()

    @property
    def address(self) -> str:
        return self._address

    async def publish(self, subject: str, payload: Any) -> None:
        payload = msgpack.unpackb(msgpack.packb(payload, use_bin_type=True), raw=False)
        for subjects, q in self._bus.subscribers:
            if subjects is None or any(subject.startswith(s) for s in subjects):
                q.put_nowait((subject, payload))

    async def close(self) -> None:
        _InProcBus.buses.pop(self._address, None)


class InProcEventSubscriber(EventSubscriber):
    def __init__(self, subjects: Optional[List[str]] = None):
        self._subjects: Optional[Set[str]] = set(subjects) if subjects else None
        self._queue: asyncio.Queue = asyncio.Queue()
        self._connected: Set[str] = set()

    def connect(self, address: str) -> None:
        bus = _InProcBus.buses.get(address)
        if bus is not None and address not in self._connected:
            bus.subscribers.append((self._subjects, self._queue))
            self._connected.add(address)

    def disconnect(self, address: str) -> None:
        bus = _InProcBus.buses.get(address)
        if bus is not None:
            bus.subscribers = [(s, q) for s, q in bus.subscribers if q is not self._queue]
        self._connected.discard(address)

    async def events(self) -> AsyncIterator[Tuple[str, Any]]:
        while True:
            yield await self._queue.get()


def make_publisher(transport: str = "zmq") -> EventPublisher:
    if transport == "zmq":
        return ZmqEventPublisher()
    if transport == "inproc":
        return InProcEventPublisher()
    if transport == "nats":
        from dynamo_tpu.runtime.nats_plane import NatsEventPublisher

        return NatsEventPublisher()
    raise ValueError(f"unknown event transport {transport!r}")


def make_subscriber(transport: str = "zmq", subjects: Optional[List[str]] = None) -> EventSubscriber:
    if transport == "zmq":
        return ZmqEventSubscriber(subjects)
    if transport == "inproc":
        return InProcEventSubscriber(subjects)
    if transport == "nats":
        from dynamo_tpu.runtime.nats_plane import NatsEventSubscriber

        return NatsEventSubscriber(subjects)
    raise ValueError(f"unknown event transport {transport!r}")
