"""Distributed tracing: W3C trace context + OTLP/HTTP span export.

Analog of the reference's OTel span pipeline (lib/runtime/src/logging.rs:
76-105 — OTLP span exporter, W3C `traceparent` propagation across the
request plane, spans per ingress/egress hop; migration links via
TraceLink, lib/llm/src/migration.rs:33-35). Same implementation stance as
logging_util.OtlpLogHandler: plain urllib + a daemon batch thread, no otel
SDK dependency.

How a trace forms:
- the HTTP frontend starts a root span per inference request (continuing a
  caller's `traceparent` header when present) and writes the new span's
  traceparent into `ctx.metadata["traceparent"]`;
- Context.metadata rides the request-plane frame headers, so every server
  hop (PushEndpoint._handle_request) opens a child span named after its
  endpoint path and re-points the metadata at itself before the engine
  runs — frontend → prefill worker → decode worker → cross-worker KV
  pulls all land in ONE trace;
- Migration stamps `migration.attempt` on retries (the reference's
  TraceLink role) so replayed hops are distinguishable.

Tail-based sampling rides the W3C flags byte: bit 0x02 is the
"tail-keep" mark. Any hop that learns a request is interesting after
the fact — a migration replay, an SLO-threshold excursion — calls
`mark_tail(metadata)`, and because downstream hops child off the same
traceparent string the mark propagates with zero extra plumbing. The
`SpanRing` exporter keeps every span in a bounded ring and applies the
sampling decision at READ time (snapshot/export), so a trace that turns
interesting late is still whole; unmarked traces survive a snapshot
only when a deterministic hash of their trace_id clears `keep_prob` —
every worker computes the same hash, so a sampled trace is kept (or
dropped) fleet-wide with no coordination.

Disabled (no exporter) the only cost is forwarding an existing
traceparent string; span objects are created only when an exporter is
installed.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

log = logging.getLogger("dynamo_tpu.tracing")

# W3C trace flags: bit 0 (0x01) = sampled; we claim bit 1 (0x02) as the
# tail-keep mark (migrated / SLO-breaching requests are always kept by
# the SpanRing regardless of the probabilistic sampling decision)
TAIL_FLAG = 0x02


@dataclass
class SpanContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    flags: str = "01"

    @property
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"

    @property
    def tail(self) -> bool:
        try:
            return bool(int(self.flags, 16) & TAIL_FLAG)
        except ValueError:
            return False


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """W3C trace-context header -> SpanContext (None when absent/invalid)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    if parts[1] == "0" * 32 or parts[2] == "0" * 16:
        return None
    try:
        # non-hex ids would poison the whole OTLP export batch downstream
        # (a collector 400s the entire /v1/traces request on one bad id)
        int(parts[1], 16), int(parts[2], 16), int(parts[3][:2] or "01", 16)
    except ValueError:
        return None
    return SpanContext(trace_id=parts[1].lower(), span_id=parts[2].lower(),
                       flags=parts[3][:2] or "01")


@dataclass
class TraceContext:
    """The compact trace context that rides Context.metadata across every
    hop: trace id, the parent span at this hop, flags (with the tail-keep
    bit). A thin, explicit view over the traceparent string — helpers for
    code that reasons about the trace rather than opening a span."""

    trace_id: str
    span_id: str  # the parent span for anything opened at this hop
    flags: str = "01"

    @property
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"

    @property
    def tail(self) -> bool:
        try:
            return bool(int(self.flags, 16) & TAIL_FLAG)
        except ValueError:
            return False

    @classmethod
    def from_metadata(cls, metadata: Optional[Dict[str, Any]]
                      ) -> Optional["TraceContext"]:
        ctx = parse_traceparent((metadata or {}).get("traceparent"))
        if ctx is None:
            return None
        return cls(ctx.trace_id, ctx.span_id, ctx.flags)

    def with_tail(self) -> "TraceContext":
        try:
            flags = int(self.flags, 16) | TAIL_FLAG
        except ValueError:
            flags = 0x01 | TAIL_FLAG
        return TraceContext(self.trace_id, self.span_id, f"{flags:02x}")

    def apply(self, metadata: Dict[str, Any]) -> None:
        metadata["traceparent"] = self.traceparent


def mark_tail(metadata: Dict[str, Any]) -> Optional[str]:
    """Set the tail-keep bit on the metadata traceparent (and return the
    rewritten value). Called when a request turns interesting after the
    fact — a migration replay, an SLO-threshold excursion — so every
    LATER hop's spans inherit the mark for free. No-op without a valid
    traceparent."""
    tc = TraceContext.from_metadata(metadata)
    if tc is None:
        return None
    tc = tc.with_tail()
    tc.apply(metadata)
    return tc.traceparent


def trace_keep(trace_id: str, keep_prob: float) -> bool:
    """Coordination-free sampling agreement: a deterministic hash of the
    trace_id against `keep_prob`, so every worker in the fleet keeps (or
    drops) the same traces without talking to each other."""
    if keep_prob >= 1.0:
        return True
    if keep_prob <= 0.0:
        return False
    try:
        # FNV-1a over the hex id: cheap, stable across processes (unlike
        # hash()), uniform enough for a sampling decision
        acc = 0x811C9DC5
        for ch in trace_id:
            acc = ((acc ^ ord(ch)) * 0x01000193) & 0xFFFFFFFF
        return (acc / 0xFFFFFFFF) < keep_prob
    except TypeError:
        return False


@dataclass
class Span:
    name: str
    context: SpanContext
    parent_span_id: Optional[str]
    start_ns: int
    end_ns: int = 0
    kind: int = 1  # OTLP SpanKind: 1=internal, 2=server, 3=client
    attributes: Dict[str, Any] = field(default_factory=dict)
    status_error: Optional[str] = None
    events: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def traceparent(self) -> str:
        return self.context.traceparent

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str,
                  attributes: Optional[Dict[str, Any]] = None) -> None:
        """Timestamped point event inside the span (OTLP span events) —
        the latency spine's phase marks ride these."""
        self.events.append({
            "name": name,
            "time_ns": time.time_ns(),
            "attributes": dict(attributes or {}),
        })

    def record_error(self, err: str) -> None:
        self.status_error = err


class _NoopSpan:
    """Returned when tracing is disabled; forwards nothing, costs nothing."""

    traceparent = None

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str,
                  attributes: Optional[Dict[str, Any]] = None) -> None:
        pass

    def record_error(self, err: str) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class MemorySpanExporter:
    """Test exporter: finished spans in a list."""

    def __init__(self):
        self.spans: List[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)


def span_to_dict(s: Span) -> Dict[str, Any]:
    """JSON form for /debug/traces and incident bundles (inverse-friendly:
    dump_timeline --trace consumes exactly this shape)."""
    return {
        "name": s.name,
        "trace_id": s.context.trace_id,
        "span_id": s.context.span_id,
        "parent_span_id": s.parent_span_id,
        "flags": s.context.flags,
        "start_ns": s.start_ns,
        "end_ns": s.end_ns,
        "kind": s.kind,
        "attributes": dict(s.attributes),
        "status_error": s.status_error,
        "events": [dict(e) for e in s.events],
    }


class SpanRing:
    """Bounded in-process span ring with tail-based sampling at READ time.

    Every finished span lands in the ring (O(1) append, deque-bounded —
    the ring is the memory ceiling, natural FIFO eviction). The sampling
    decision happens when someone reads the ring (`snapshot`,
    `/debug/traces`, an incident bundle): a trace survives if ANY of its
    spans carried the tail-keep flag (migrated / SLO-breaching requests)
    or if the deterministic `trace_keep` hash clears `keep_prob`. Late
    marking therefore keeps the WHOLE trace — the early spans are still
    in the ring when the mark arrives. `spans_for` (incident forensics)
    never samples: evidence beats budgets once a trace id is named."""

    def __init__(self, capacity: int = 4096, keep_prob: float = 1.0):
        from collections import deque

        self.capacity = max(16, int(capacity))
        self.keep_prob = float(keep_prob)
        self._ring = deque(maxlen=self.capacity)
        # bounded memory of tail-marked trace ids (survives ring eviction
        # of the marking span; bounded so a long-lived worker can't grow it)
        self._tail: "deque" = deque(maxlen=self.capacity)
        self._tail_set: set = set()
        self.exported = 0

    def export(self, span: Span) -> None:
        self._ring.append(span)
        self.exported += 1
        if span.context.tail and span.context.trace_id not in self._tail_set:
            if len(self._tail) == self._tail.maxlen:
                self._tail_set.discard(self._tail[0])
            self._tail.append(span.context.trace_id)
            self._tail_set.add(span.context.trace_id)

    def __len__(self) -> int:
        return len(self._ring)

    def keeps(self, trace_id: str) -> bool:
        return trace_id in self._tail_set or trace_keep(trace_id,
                                                        self.keep_prob)

    def tail_trace_ids(self) -> List[str]:
        """Tail-marked trace ids still remembered (incident bundles list
        these so forensics knows which traces were kept by policy)."""
        return sorted(self._tail_set)

    def spans_for(self, trace_id: str) -> List[Span]:
        """Every ring span of one trace, oldest first — unsampled (the
        incident path and trace-id queries want ALL the evidence)."""
        return [s for s in self._ring if s.context.trace_id == trace_id]

    def snapshot(self, last_n: int = 0, sampled: bool = True) -> List[Span]:
        spans = list(self._ring)
        if sampled:
            spans = [s for s in spans if self.keeps(s.context.trace_id)]
        if last_n > 0:
            spans = spans[-last_n:]
        return spans

    def payload(self, trace_id: Optional[str] = None,
                last_n: int = 0) -> Dict[str, Any]:
        """The /debug/traces JSON body."""
        if trace_id:
            spans = self.spans_for(trace_id)
        else:
            spans = self.snapshot(last_n=last_n)
        return {
            "n": len(spans),
            "exported": self.exported,
            "capacity": self.capacity,
            "keep_prob": self.keep_prob,
            "tail_traces": len(self._tail_set),
            "spans": [span_to_dict(s) for s in spans],
        }


class MultiExporter:
    """Fan a span out to several exporters (ring + OTLP coexist)."""

    def __init__(self, *exporters):
        self.exporters = [e for e in exporters if e is not None]

    def export(self, span: Span) -> None:
        for e in self.exporters:
            e.export(span)

    def flush(self, timeout_s: float = 5.0) -> bool:
        ok = True
        for e in self.exporters:
            fl = getattr(e, "flush", None)
            if fl is not None:
                ok = bool(fl(timeout_s)) and ok
        return ok


class OtlpSpanExporter:
    """Batch spans to an OTLP/HTTP collector (/v1/traces, JSON encoding)
    from a daemon thread; drops on failure (telemetry is best-effort)."""

    def __init__(self, endpoint: str, service_name: str = "dynamo_tpu",
                 flush_interval_s: float = 2.0, max_batch: int = 256,
                 max_queue: int = 8192):
        import queue

        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        # bounded queue is the memory ceiling; overflow drops (counted)
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self.dropped = 0  # spans dropped on queue overflow
        self._inflight = 0  # spans popped but not yet POSTed (flush waits)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def export(self, span: Span) -> None:
        import queue

        try:
            self._q.put_nowait(span)
        except queue.Full:
            # full queue: drop, but keep the evidence — a short-lived
            # worker seeing dropped>0 at shutdown lost tail spans. The
            # FIRST drop warns (once): silent span loss hides exactly the
            # traces an overloaded process most needs; after that the
            # `dynamo_trace_dropped_spans` gauge carries the count.
            self.dropped += 1
            if self.dropped == 1:
                log.warning(
                    "span queue full (maxsize=%d): dropping spans — the "
                    "collector at %s is slow or down; further drops are "
                    "counted on the dropped_spans gauge, not logged",
                    self._q.maxsize, getattr(self, "url", "?"))

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Bounded drain: wait until the batch thread has consumed AND
        posted everything queued at call time (or the timeout expires).
        Called on runtime shutdown so short-lived workers don't exit with
        their tail spans still queued. Returns True when fully drained."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while self._q.qsize() > 0 or self._inflight > 0:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)
        return True

    @staticmethod
    def _attr(k: str, v: Any) -> Dict[str, Any]:
        if isinstance(v, bool):
            val = {"boolValue": v}
        elif isinstance(v, int):
            val = {"intValue": str(v)}
        elif isinstance(v, float):
            val = {"doubleValue": v}
        else:
            val = {"stringValue": str(v)}
        return {"key": k, "value": val}

    def _wire(self, s: Span) -> Dict[str, Any]:
        out = {
            "traceId": s.context.trace_id,
            "spanId": s.context.span_id,
            "name": s.name,
            "kind": s.kind,  # already the OTLP enum (1=internal, 2=server, 3=client)
            "startTimeUnixNano": str(s.start_ns),
            "endTimeUnixNano": str(s.end_ns),
            "attributes": [self._attr(k, v) for k, v in s.attributes.items()],
        }
        if s.parent_span_id:
            out["parentSpanId"] = s.parent_span_id
        if s.status_error is not None:
            out["status"] = {"code": 2, "message": s.status_error}
        if s.events:
            out["events"] = [
                {
                    "timeUnixNano": str(e["time_ns"]),
                    "name": e["name"],
                    "attributes": [self._attr(k, v)
                                   for k, v in e["attributes"].items()],
                }
                for e in s.events
            ]
        return out

    def _loop(self) -> None:
        import queue
        import urllib.request

        while True:
            batch = [self._q.get()]
            self._inflight = 1
            deadline = time.monotonic() + self.flush_interval_s
            while len(batch) < self.max_batch:
                try:
                    batch.append(
                        self._q.get(timeout=max(0.01, deadline - time.monotonic()))
                    )
                    self._inflight = len(batch)
                except queue.Empty:
                    break
            payload = json.dumps({
                "resourceSpans": [{
                    "resource": {"attributes": [
                        {"key": "service.name",
                         "value": {"stringValue": self.service_name}},
                    ]},
                    "scopeSpans": [{
                        "scope": {"name": "dynamo_tpu"},
                        "spans": [self._wire(s) for s in batch],
                    }],
                }]
            }).encode()
            try:
                req = urllib.request.Request(
                    self.url, data=payload,
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=5).read()
            except (OSError, ValueError):
                pass  # collector down / bad endpoint: drop
            finally:
                self._inflight = 0


_exporter = None
_configured = False


def set_exporter(exporter) -> None:
    """Install a span exporter (tests use MemorySpanExporter; production
    configuration happens via DYN_OTLP_ENDPOINT in configure_tracing)."""
    global _exporter, _configured
    _exporter = exporter
    _configured = True


def configure_tracing(service_name: str = "dynamo_tpu") -> None:
    """Idempotent env-driven setup: DYN_OTLP_ENDPOINT enables span export
    (shared with the OTLP log handler endpoint, like the reference);
    DYN_TRACE_RING=N arms the bounded in-process SpanRing (queryable at
    /debug/traces, merged fleet-wide by dump_timeline --trace) with
    DYN_TRACE_KEEP as the probabilistic keep fraction (default 1.0;
    tail-marked traces are always kept). Both may coexist."""
    global _configured
    if _configured:
        return
    _configured = True
    endpoint = os.environ.get("DYN_OTLP_TRACES_ENDPOINT") \
        or os.environ.get("DYN_OTLP_ENDPOINT")
    exporters = []
    try:
        ring_cap = int(os.environ.get("DYN_TRACE_RING", "0"))
    except ValueError:
        ring_cap = 0
    if ring_cap > 0:
        try:
            keep = float(os.environ.get("DYN_TRACE_KEEP", "1.0"))
        except ValueError:
            keep = 1.0
        exporters.append(SpanRing(capacity=ring_cap, keep_prob=keep))
    if endpoint:
        exporters.append(OtlpSpanExporter(endpoint,
                                          service_name=service_name))
    if len(exporters) == 1:
        set_exporter(exporters[0])
    elif exporters:
        set_exporter(MultiExporter(*exporters))


def enabled() -> bool:
    return _exporter is not None


def span_ring() -> Optional[SpanRing]:
    """The installed SpanRing, if any (directly or inside a
    MultiExporter) — the /debug/traces and incident-bundle source."""
    exp = _exporter
    if isinstance(exp, SpanRing):
        return exp
    for e in getattr(exp, "exporters", ()):
        if isinstance(e, SpanRing):
            return e
    return None


def dropped_spans() -> int:
    """Spans lost to bounded-queue overflow across the installed
    exporter(s) — surfaced as a /metrics gauge by worker_common so
    silent span loss is visible without reading logs."""
    exp = _exporter
    total = int(getattr(exp, "dropped", 0) or 0)
    for e in getattr(exp, "exporters", ()):
        total += int(getattr(e, "dropped", 0) or 0)
    return total


def flush_tracing(timeout_s: float = 5.0) -> bool:
    """Drain the installed exporter's span queue (bounded). No-ops (True)
    when tracing is off or the exporter has no buffering. Wired into
    DistributedRuntime.shutdown so short-lived workers keep tail spans."""
    exp = _exporter
    fl = getattr(exp, "flush", None)
    if fl is None:
        return True
    try:
        return bool(fl(timeout_s))
    except Exception:  # pragma: no cover
        log.exception("span flush failed")
        return False


@contextlib.contextmanager
def span(name: str, parent: Optional[str] = None, kind: int = 1,
         attributes: Optional[Dict[str, Any]] = None):
    """Open a span. `parent` is a traceparent string (e.g. from
    ctx.metadata); the yielded span's `.traceparent` is what downstream
    metadata should carry. No exporter installed -> a shared no-op span
    (callers still forward the incoming parent themselves)."""
    if _exporter is None:
        yield NOOP_SPAN
        return
    pctx = parse_traceparent(parent)
    ctx = SpanContext(
        trace_id=pctx.trace_id if pctx else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
        # inherit flags so a tail-keep mark set upstream rides every
        # child traceparent this hop writes downstream
        flags=pctx.flags if pctx else "01",
    )
    s = Span(
        name=name,
        context=ctx,
        parent_span_id=pctx.span_id if pctx else None,
        start_ns=time.time_ns(),
        kind=kind,
        attributes=dict(attributes or {}),
    )
    try:
        yield s
    except BaseException as e:
        # GeneratorExit is the normal close of a streaming consumer and
        # CancelledError is cooperative shutdown — neither is a span error
        if not isinstance(e, (GeneratorExit, asyncio.CancelledError)):
            s.record_error(f"{type(e).__name__}: {e}")
        raise
    finally:
        s.end_ns = time.time_ns()
        try:
            _exporter.export(s)
        except Exception:
            log.exception("span export failed")


def child_traceparent(metadata: Dict[str, Any], s) -> None:
    """Point request metadata at `s` so downstream hops become children.
    With tracing disabled (no-op span) the existing traceparent is left
    for downstream services that DO trace."""
    tp = getattr(s, "traceparent", None)
    if tp is not None:
        metadata["traceparent"] = tp


def record_span(name: str, start_ns: int, end_ns: int,
                parent: Optional[str] = None, kind: int = 1,
                attributes: Optional[Dict[str, Any]] = None,
                ) -> Optional[Span]:
    """Record an already-measured interval as a finished span.

    The worker's phase spine measures durations on the step thread and
    only knows the full story at request finish; promotions in the KV
    prefetcher span several engine ticks. Both reconstruct their spans
    retroactively from (start_ns, end_ns) instead of holding a live span
    open across threads. Inherits trace id and the tail-keep flag from
    `parent`; no exporter installed -> None, zero allocation beyond the
    parse."""
    if _exporter is None:
        return None
    pctx = parse_traceparent(parent)
    ctx = SpanContext(
        trace_id=pctx.trace_id if pctx else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
        flags=pctx.flags if pctx else "01",
    )
    s = Span(
        name=name,
        context=ctx,
        parent_span_id=pctx.span_id if pctx else None,
        start_ns=int(start_ns),
        end_ns=int(end_ns),
        kind=kind,
        attributes=dict(attributes or {}),
    )
    try:
        _exporter.export(s)
    except Exception:
        log.exception("span export failed")
    return s
