"""Distributed tracing: W3C trace context + OTLP/HTTP span export.

Analog of the reference's OTel span pipeline (lib/runtime/src/logging.rs:
76-105 — OTLP span exporter, W3C `traceparent` propagation across the
request plane, spans per ingress/egress hop; migration links via
TraceLink, lib/llm/src/migration.rs:33-35). Same implementation stance as
logging_util.OtlpLogHandler: plain urllib + a daemon batch thread, no otel
SDK dependency.

How a trace forms:
- the HTTP frontend starts a root span per inference request (continuing a
  caller's `traceparent` header when present) and writes the new span's
  traceparent into `ctx.metadata["traceparent"]`;
- Context.metadata rides the request-plane frame headers, so every server
  hop (PushEndpoint._handle_request) opens a child span named after its
  endpoint path and re-points the metadata at itself before the engine
  runs — frontend → prefill worker → decode worker → cross-worker KV
  pulls all land in ONE trace;
- Migration stamps `migration.attempt` on retries (the reference's
  TraceLink role) so replayed hops are distinguishable.

Disabled (no exporter) the only cost is forwarding an existing
traceparent string; span objects are created only when an exporter is
installed.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

log = logging.getLogger("dynamo_tpu.tracing")


@dataclass
class SpanContext:
    trace_id: str  # 32 hex chars
    span_id: str  # 16 hex chars
    flags: str = "01"

    @property
    def traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags}"


def parse_traceparent(value: Optional[str]) -> Optional[SpanContext]:
    """W3C trace-context header -> SpanContext (None when absent/invalid)."""
    if not value or not isinstance(value, str):
        return None
    parts = value.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    if parts[1] == "0" * 32 or parts[2] == "0" * 16:
        return None
    try:
        # non-hex ids would poison the whole OTLP export batch downstream
        # (a collector 400s the entire /v1/traces request on one bad id)
        int(parts[1], 16), int(parts[2], 16), int(parts[3][:2] or "01", 16)
    except ValueError:
        return None
    return SpanContext(trace_id=parts[1].lower(), span_id=parts[2].lower(),
                       flags=parts[3][:2] or "01")


@dataclass
class Span:
    name: str
    context: SpanContext
    parent_span_id: Optional[str]
    start_ns: int
    end_ns: int = 0
    kind: int = 1  # OTLP SpanKind: 1=internal, 2=server, 3=client
    attributes: Dict[str, Any] = field(default_factory=dict)
    status_error: Optional[str] = None
    events: List[Dict[str, Any]] = field(default_factory=list)

    @property
    def traceparent(self) -> str:
        return self.context.traceparent

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str,
                  attributes: Optional[Dict[str, Any]] = None) -> None:
        """Timestamped point event inside the span (OTLP span events) —
        the latency spine's phase marks ride these."""
        self.events.append({
            "name": name,
            "time_ns": time.time_ns(),
            "attributes": dict(attributes or {}),
        })

    def record_error(self, err: str) -> None:
        self.status_error = err


class _NoopSpan:
    """Returned when tracing is disabled; forwards nothing, costs nothing."""

    traceparent = None

    def set_attribute(self, key: str, value: Any) -> None:
        pass

    def add_event(self, name: str,
                  attributes: Optional[Dict[str, Any]] = None) -> None:
        pass

    def record_error(self, err: str) -> None:
        pass


NOOP_SPAN = _NoopSpan()


class MemorySpanExporter:
    """Test exporter: finished spans in a list."""

    def __init__(self):
        self.spans: List[Span] = []

    def export(self, span: Span) -> None:
        self.spans.append(span)


class OtlpSpanExporter:
    """Batch spans to an OTLP/HTTP collector (/v1/traces, JSON encoding)
    from a daemon thread; drops on failure (telemetry is best-effort)."""

    def __init__(self, endpoint: str, service_name: str = "dynamo_tpu",
                 flush_interval_s: float = 2.0, max_batch: int = 256,
                 max_queue: int = 8192):
        import queue

        self.url = endpoint.rstrip("/") + "/v1/traces"
        self.service_name = service_name
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        # bounded queue is the memory ceiling; overflow drops (counted)
        self._q: "queue.Queue" = queue.Queue(maxsize=max_queue)
        self.dropped = 0  # spans dropped on queue overflow
        self._inflight = 0  # spans popped but not yet POSTed (flush waits)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def export(self, span: Span) -> None:
        import queue

        try:
            self._q.put_nowait(span)
        except queue.Full:
            # full queue: drop, but keep the evidence — a short-lived
            # worker seeing dropped>0 at shutdown lost tail spans
            self.dropped += 1

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Bounded drain: wait until the batch thread has consumed AND
        posted everything queued at call time (or the timeout expires).
        Called on runtime shutdown so short-lived workers don't exit with
        their tail spans still queued. Returns True when fully drained."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while self._q.qsize() > 0 or self._inflight > 0:
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)
        return True

    @staticmethod
    def _attr(k: str, v: Any) -> Dict[str, Any]:
        if isinstance(v, bool):
            val = {"boolValue": v}
        elif isinstance(v, int):
            val = {"intValue": str(v)}
        elif isinstance(v, float):
            val = {"doubleValue": v}
        else:
            val = {"stringValue": str(v)}
        return {"key": k, "value": val}

    def _wire(self, s: Span) -> Dict[str, Any]:
        out = {
            "traceId": s.context.trace_id,
            "spanId": s.context.span_id,
            "name": s.name,
            "kind": s.kind,  # already the OTLP enum (1=internal, 2=server, 3=client)
            "startTimeUnixNano": str(s.start_ns),
            "endTimeUnixNano": str(s.end_ns),
            "attributes": [self._attr(k, v) for k, v in s.attributes.items()],
        }
        if s.parent_span_id:
            out["parentSpanId"] = s.parent_span_id
        if s.status_error is not None:
            out["status"] = {"code": 2, "message": s.status_error}
        if s.events:
            out["events"] = [
                {
                    "timeUnixNano": str(e["time_ns"]),
                    "name": e["name"],
                    "attributes": [self._attr(k, v)
                                   for k, v in e["attributes"].items()],
                }
                for e in s.events
            ]
        return out

    def _loop(self) -> None:
        import queue
        import urllib.request

        while True:
            batch = [self._q.get()]
            self._inflight = 1
            deadline = time.monotonic() + self.flush_interval_s
            while len(batch) < self.max_batch:
                try:
                    batch.append(
                        self._q.get(timeout=max(0.01, deadline - time.monotonic()))
                    )
                    self._inflight = len(batch)
                except queue.Empty:
                    break
            payload = json.dumps({
                "resourceSpans": [{
                    "resource": {"attributes": [
                        {"key": "service.name",
                         "value": {"stringValue": self.service_name}},
                    ]},
                    "scopeSpans": [{
                        "scope": {"name": "dynamo_tpu"},
                        "spans": [self._wire(s) for s in batch],
                    }],
                }]
            }).encode()
            try:
                req = urllib.request.Request(
                    self.url, data=payload,
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=5).read()
            except (OSError, ValueError):
                pass  # collector down / bad endpoint: drop
            finally:
                self._inflight = 0


_exporter = None
_configured = False


def set_exporter(exporter) -> None:
    """Install a span exporter (tests use MemorySpanExporter; production
    configuration happens via DYN_OTLP_ENDPOINT in configure_tracing)."""
    global _exporter, _configured
    _exporter = exporter
    _configured = True


def configure_tracing(service_name: str = "dynamo_tpu") -> None:
    """Idempotent env-driven setup: DYN_OTLP_ENDPOINT enables span export
    (shared with the OTLP log handler endpoint, like the reference)."""
    global _configured
    if _configured:
        return
    _configured = True
    endpoint = os.environ.get("DYN_OTLP_TRACES_ENDPOINT") \
        or os.environ.get("DYN_OTLP_ENDPOINT")
    if endpoint:
        set_exporter(OtlpSpanExporter(endpoint, service_name=service_name))


def enabled() -> bool:
    return _exporter is not None


def flush_tracing(timeout_s: float = 5.0) -> bool:
    """Drain the installed exporter's span queue (bounded). No-ops (True)
    when tracing is off or the exporter has no buffering. Wired into
    DistributedRuntime.shutdown so short-lived workers keep tail spans."""
    exp = _exporter
    fl = getattr(exp, "flush", None)
    if fl is None:
        return True
    try:
        return bool(fl(timeout_s))
    except Exception:  # pragma: no cover
        log.exception("span flush failed")
        return False


@contextlib.contextmanager
def span(name: str, parent: Optional[str] = None, kind: int = 1,
         attributes: Optional[Dict[str, Any]] = None):
    """Open a span. `parent` is a traceparent string (e.g. from
    ctx.metadata); the yielded span's `.traceparent` is what downstream
    metadata should carry. No exporter installed -> a shared no-op span
    (callers still forward the incoming parent themselves)."""
    if _exporter is None:
        yield NOOP_SPAN
        return
    pctx = parse_traceparent(parent)
    ctx = SpanContext(
        trace_id=pctx.trace_id if pctx else secrets.token_hex(16),
        span_id=secrets.token_hex(8),
    )
    s = Span(
        name=name,
        context=ctx,
        parent_span_id=pctx.span_id if pctx else None,
        start_ns=time.time_ns(),
        kind=kind,
        attributes=dict(attributes or {}),
    )
    try:
        yield s
    except BaseException as e:
        # GeneratorExit is the normal close of a streaming consumer and
        # CancelledError is cooperative shutdown — neither is a span error
        if not isinstance(e, (GeneratorExit, asyncio.CancelledError)):
            s.record_error(f"{type(e).__name__}: {e}")
        raise
    finally:
        s.end_ns = time.time_ns()
        try:
            _exporter.export(s)
        except Exception:
            log.exception("span export failed")


def child_traceparent(metadata: Dict[str, Any], s) -> None:
    """Point request metadata at `s` so downstream hops become children.
    With tracing disabled (no-op span) the existing traceparent is left
    for downstream services that DO trace."""
    tp = getattr(s, "traceparent", None)
    if tp is not None:
        metadata["traceparent"] = tp
