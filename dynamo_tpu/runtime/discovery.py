"""Pluggable service discovery (analog of reference lib/runtime/src/discovery/).

Backends (selected like lib/runtime/src/distributed.rs:149-180 via
DYN_DISCOVERY_BACKEND): `mem` (in-process, shared across runtimes in one
process — mirrors discovery/mock.rs / storage `mem`), `file` (shared
directory of JSON records with mtime-heartbeat leases — multi-process on one
host, mirrors the `file` backend), and later `etcd`/`kubernetes`.

The watch contract mirrors the reference's discovery stream feeding
ModelWatcher (lib/llm/src/discovery/watcher.rs:472): subscribers receive
(PUT|DELETE, Instance) events, with an initial PUT replay of existing
instances.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from dataclasses import dataclass
from pathlib import Path
from typing import AsyncIterator, Dict, List, Optional

from dynamo_tpu.runtime.component import Instance


@dataclass
class DiscoveryEvent:
    kind: str  # "put" | "delete"
    instance: Instance


class DiscoveryBackend:
    """Interface: register/unregister instances, list, watch a prefix."""

    async def register(self, instance: Instance) -> None:
        raise NotImplementedError

    async def unregister(self, instance: Instance) -> None:
        raise NotImplementedError

    async def list_instances(self, prefix: str = "") -> List[Instance]:
        raise NotImplementedError

    async def watch(self, prefix: str = "") -> AsyncIterator[DiscoveryEvent]:
        raise NotImplementedError
        yield  # pragma: no cover

    async def close(self) -> None:
        pass

    # liveness: backends with leases refresh them here (no-op for mem)
    async def heartbeat(self) -> None:
        pass


async def poll_diff_watch(scan, poll_interval: float, on_error=None):
    """Shared poll-based watch: diff successive scans into put/delete
    events (used by the file and kubernetes backends). `scan` is an async
    callable returning {path: Instance}."""
    known: Dict[str, dict] = {}
    while True:
        try:
            current = await scan()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            if on_error is not None:
                on_error(e)
            await asyncio.sleep(poll_interval)
            continue
        for path, inst in current.items():
            rec = inst.to_dict()
            if known.get(path) != rec:  # new or changed (metadata/address)
                known[path] = rec
                yield DiscoveryEvent("put", inst)
        for path in list(known):
            if path not in current:
                rec = known.pop(path)
                yield DiscoveryEvent("delete", Instance.from_dict(rec))
        await asyncio.sleep(poll_interval)


class MemDiscovery(DiscoveryBackend):
    """In-process discovery; all MemDiscovery() instances created with the
    same `realm` share one registry, so N workers + a frontend in one process
    (or one pytest) discover each other."""

    _realms: Dict[str, "_MemRealm"] = {}

    def __init__(self, realm: str = "default"):
        self._realm = MemDiscovery._realms.setdefault(realm, _MemRealm())

    async def register(self, instance: Instance) -> None:
        await self._realm.put(instance)

    async def unregister(self, instance: Instance) -> None:
        await self._realm.delete(instance)

    async def list_instances(self, prefix: str = "") -> List[Instance]:
        return [i for p, i in self._realm.store.items() if p.startswith(prefix or "services/")]

    async def watch(self, prefix: str = "") -> AsyncIterator[DiscoveryEvent]:
        queue: asyncio.Queue = asyncio.Queue()
        prefix = prefix or "services/"
        self._realm.watchers.append((prefix, queue))
        try:
            for inst in await self.list_instances(prefix):
                yield DiscoveryEvent("put", inst)
            while True:
                ev = await queue.get()
                yield ev
        finally:
            self._realm.watchers.remove((prefix, queue))

    @classmethod
    def reset(cls, realm: Optional[str] = None) -> None:
        """Test helper: drop realm state."""
        if realm is None:
            cls._realms.clear()
        else:
            cls._realms.pop(realm, None)


class _MemRealm:
    def __init__(self):
        self.store: Dict[str, Instance] = {}
        self.watchers: List[tuple[str, asyncio.Queue]] = []

    async def put(self, instance: Instance) -> None:
        self.store[instance.path] = instance
        self._notify(DiscoveryEvent("put", instance))

    async def delete(self, instance: Instance) -> None:
        self.store.pop(instance.path, None)
        self._notify(DiscoveryEvent("delete", instance))

    def _notify(self, ev: DiscoveryEvent) -> None:
        for prefix, q in self.watchers:
            if ev.instance.path.startswith(prefix):
                q.put_nowait(ev)


class FileDiscovery(DiscoveryBackend):
    """Directory-backed discovery for multi-process single-host topologies.

    Each instance is one JSON file at `{root}/{instance.path}.json`. Liveness
    = file mtime refreshed by `heartbeat()`; records older than `lease_ttl`
    seconds are treated as dead (the file analog of etcd lease expiry,
    docs/design-docs/distributed-runtime.md:55). Watching is poll-based.
    """

    def __init__(self, root: str, lease_ttl: float = 10.0, poll_interval: float = 0.25):
        self.root = Path(root)
        self.lease_ttl = lease_ttl
        self.poll_interval = poll_interval
        self._mine: Dict[str, Instance] = {}

    def _file(self, instance_path: str) -> Path:
        return self.root / (instance_path + ".json")

    async def register(self, instance: Instance) -> None:
        f = self._file(instance.path)
        f.parent.mkdir(parents=True, exist_ok=True)
        tmp = f.with_suffix(".tmp")
        tmp.write_text(json.dumps(instance.to_dict()))
        os.replace(tmp, f)
        self._mine[instance.path] = instance

    async def unregister(self, instance: Instance) -> None:
        self._mine.pop(instance.path, None)
        try:
            self._file(instance.path).unlink()
        except FileNotFoundError:
            pass

    async def heartbeat(self) -> None:
        now = time.time()
        for path in list(self._mine):
            try:
                os.utime(self._file(path), (now, now))
            except FileNotFoundError:
                # lease lost (file removed externally): re-register
                await self.register(self._mine[path])

    def _scan(self, prefix: str) -> Dict[str, Instance]:
        out: Dict[str, Instance] = {}
        base = self.root
        if not base.exists():
            return out
        cutoff = time.time() - self.lease_ttl
        for f in base.rglob("*.json"):
            rel = str(f.relative_to(base))[: -len(".json")]
            if prefix and not rel.startswith(prefix):
                continue
            try:
                if f.stat().st_mtime < cutoff:
                    continue
                out[rel] = Instance.from_dict(json.loads(f.read_text()))
            except (OSError, ValueError):
                continue
        return out

    async def list_instances(self, prefix: str = "") -> List[Instance]:
        return list(self._scan(prefix or "services/").values())

    async def watch(self, prefix: str = "") -> AsyncIterator[DiscoveryEvent]:
        import logging

        prefix = prefix or "services/"
        log = logging.getLogger("dynamo_tpu.runtime.discovery")

        async def scan():
            return self._scan(prefix)

        async for ev in poll_diff_watch(
            scan, self.poll_interval,
            on_error=lambda e: log.warning("file discovery scan failed (%s); retrying", e),
        ):
            yield ev


def make_discovery(backend: Optional[str] = None, **kw) -> DiscoveryBackend:
    """Select a backend, env-first (DYN_DISCOVERY_BACKEND; reference
    lib/runtime/src/distributed.rs:149-180)."""
    backend = backend or os.environ.get("DYN_DISCOVERY_BACKEND", "mem")
    if backend == "mem":
        return MemDiscovery(realm=kw.get("realm", "default"))
    if backend == "file":
        root = kw.get("root") or os.environ.get("DYN_DISCOVERY_FILE_ROOT", "/tmp/dynamo_tpu_discovery")
        return FileDiscovery(root, lease_ttl=float(kw.get("lease_ttl", 10.0)))
    if backend == "etcd":
        from dynamo_tpu.runtime.etcd import EtcdDiscovery

        endpoint = (
            kw.get("endpoint")
            or os.environ.get("DYN_ETCD_ENDPOINT")
            or os.environ.get("ETCD_ENDPOINTS", "http://127.0.0.1:2379").split(",")[0]
        )
        return EtcdDiscovery(endpoint, lease_ttl=int(kw.get("lease_ttl", 10)))
    if backend == "kubernetes":
        from dynamo_tpu.runtime.kube_discovery import KubeDiscovery

        return KubeDiscovery(
            namespace=kw.get("namespace")
            or os.environ.get("DYN_K8S_NAMESPACE", "default"),
            # DYN_K8S_API overrides the in-cluster endpoint (dev/test)
            api_base=kw.get("api_base") or os.environ.get("DYN_K8S_API"),
            # only override the backend's skew-aware default when asked
            **({"lease_ttl": float(kw["lease_ttl"])} if "lease_ttl" in kw else {}),
        )
    raise ValueError(f"unknown discovery backend {backend!r}")
