"""Device-timeline trace annotations — the TPU analog of the reference's
NVTX integration (lib/runtime/Cargo.toml:24-27, src/nvtx.rs: Nsight
ranges, compile-time + `DYN_ENABLE_RUST_NVTX` runtime gated, ~1ns off).

On TPU the profiler is XLA's: `jax.profiler.start_server` exposes the
worker to TensorBoard/xprof capture, and `TraceAnnotation` ranges mark
engine phases (prefill/decode/sample) on the captured host+device
timeline. Gated by `DYN_ENABLE_JAX_TRACE=1`; when off, `annotate` is a
shared no-op context manager (one attribute read per call)."""

from __future__ import annotations

import contextlib
import functools
import logging
import os

log = logging.getLogger("dynamo_tpu.annotations")

_TRUTHY = {"1", "true", "on", "yes"}  # lib/truthy semantics


@functools.lru_cache(maxsize=1)
def _enabled() -> bool:
    # cached: the engine step loop calls annotate() per plan; the env gate
    # is a deployment decision, not a per-request one (tests reset via
    # _enabled.cache_clear())
    return os.environ.get("DYN_ENABLE_JAX_TRACE", "").lower() in _TRUTHY


_NULL = contextlib.nullcontext()


def annotate(name: str, **kwargs):
    """Context manager marking a named range on the profiler timeline.
    kwargs become xprof metadata (e.g. batch size, token counts)."""
    if not _enabled():
        return _NULL
    from jax.profiler import TraceAnnotation

    return TraceAnnotation(name, **kwargs)


def start_profiler_server(port: int) -> bool:
    """Start the XLA profiler server (TensorBoard 'capture profile'
    target). Returns False if unavailable (CPU-only builds)."""
    try:
        import jax

        jax.profiler.start_server(port)
        log.info("jax profiler server on port %d", port)
        return True
    except Exception:  # pragma: no cover
        log.exception("profiler server failed to start")
        return False
