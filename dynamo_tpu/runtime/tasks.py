"""Tracked fire-and-forget task spawning.

`asyncio.create_task` keeps only a weak reference to the task: a
fire-and-forget spawn whose return value is dropped can be
garbage-collected mid-flight, silently cancelling the coroutine, and an
exception it raises is never observed ("Task exception was never
retrieved" at GC time, long after the cause). dynlint flags those call
sites (DYN-A004); `spawn_tracked` is the sanctioned replacement — it
retains a strong reference until the task finishes and logs uncaught
exceptions through the spawning module's logger at done-callback time.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Coroutine, Optional, Set

log = logging.getLogger("dynamo_tpu.runtime.tasks")

# strong refs for tasks nobody else retains; discarded on completion
_TRACKED: Set[asyncio.Task] = set()


def spawn_tracked(
    coro: Coroutine,
    *,
    name: Optional[str] = None,
    logger: Optional[logging.Logger] = None,
) -> asyncio.Task:
    """Spawn `coro` fire-and-forget, safely.

    Retains the task until it completes and logs any uncaught exception
    (CancelledError excluded — cancellation is how owners stop these).
    Losses stay losses: callers that need the result should await the
    returned task instead of dropping it.
    """
    task = asyncio.create_task(coro, name=name)
    _TRACKED.add(task)
    task_log = logger or log

    def _done(t: asyncio.Task) -> None:
        _TRACKED.discard(t)
        if t.cancelled():
            return
        exc = t.exception()
        if exc is not None:
            task_log.warning(
                "background task %s failed: %r",
                t.get_name(), exc, exc_info=exc,
            )

    task.add_done_callback(_done)
    return task


def tracked_count() -> int:
    """Number of live tracked tasks (tests / shutdown diagnostics)."""
    return len(_TRACKED)
