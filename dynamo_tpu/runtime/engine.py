"""Streaming engine protocol (analog of reference AsyncEngine trait,
lib/runtime/src/engine.rs:211).

An engine maps a request to an async stream of response items. Engines are
the universal composition unit: the frontend pipeline (preprocessor →
migration → backend → router → network egress) is a chain of engines, and a
worker's handler is an engine served over the request plane.
"""

from __future__ import annotations

import inspect
from typing import Any, AsyncIterator, Awaitable, Callable, Protocol, runtime_checkable

from dynamo_tpu.runtime.context import Context

EngineStream = AsyncIterator[Any]


@runtime_checkable
class AsyncEngine(Protocol):
    """generate(request, context) -> async iterator of response items."""

    def generate(self, request: Any, context: Context) -> EngineStream:  # pragma: no cover
        ...


class FnEngine:
    """Wrap an async-generator function (request, context) -> stream as an engine."""

    def __init__(self, fn: Callable[[Any, Context], EngineStream]):
        self._fn = fn

    def generate(self, request: Any, context: Context) -> EngineStream:
        return self._fn(request, context)


class UnaryEngine:
    """Wrap an async function returning a single value as a one-item stream."""

    def __init__(self, fn: Callable[[Any, Context], Awaitable[Any]]):
        self._fn = fn

    async def generate(self, request: Any, context: Context) -> EngineStream:
        yield await self._fn(request, context)


def as_engine(obj: Any) -> AsyncEngine:
    """Coerce a handler (engine / async-gen fn / coroutine fn) to AsyncEngine."""
    if hasattr(obj, "generate"):
        return obj
    if inspect.isasyncgenfunction(obj):
        return FnEngine(obj)
    if inspect.iscoroutinefunction(obj):
        return UnaryEngine(obj)
    raise TypeError(f"cannot make AsyncEngine from {obj!r}")


class EchoEngine:
    """Token-echo test engine (mirror of reference lib/llm/src/engines.rs:77):
    streams back each element of request["token_ids"] (or characters of
    request["text"]) one item at a time. Used for frontend/runtime e2e tests
    with no model."""

    async def generate(self, request: Any, context: Context) -> EngineStream:
        if isinstance(request, dict) and "token_ids" in request:
            for t in request["token_ids"]:
                context.raise_if_killed()
                if context.is_stopped:
                    return
                yield {"token_ids": [t]}
        elif isinstance(request, dict) and "text" in request:
            for ch in request["text"]:
                context.raise_if_killed()
                if context.is_stopped:
                    return
                yield {"text": ch}
        else:
            yield request
