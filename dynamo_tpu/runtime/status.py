"""Per-process system status server (analog of reference
system_status_server.rs + system_health.rs): /live, /health, /metrics on a
side port for workers and routers (the HTTP frontend has its own)."""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, Optional

from aiohttp import web

log = logging.getLogger("dynamo_tpu.status")


class StatusServer:
    def __init__(self, runtime, port: int = 0, host: str = "0.0.0.0"):
        self.runtime = runtime
        self.host = host
        self.port = port
        self._checks: Dict[str, Callable[[], bool]] = {}
        self._timeline: Optional[Callable[[int], dict]] = None
        self._debug: Dict[str, Callable[[Dict[str, str]], dict]] = {}
        self._started_at = time.time()
        self._runner: Optional[web.AppRunner] = None

    def add_check(self, name: str, fn: Callable[[], bool]) -> None:
        self._checks[name] = fn

    def add_timeline(self, fn: Callable[[int], dict]) -> None:
        """Install the /debug/timeline source: fn(last_n) -> Chrome-trace
        dict (the worker wires the engine flight recorder's
        to_chrome_trace here; see docs/observability.md)."""
        self._timeline = fn

    def add_debug(self, name: str, fn: Callable[[Dict[str, str]], dict]) -> None:
        """Install a GET /debug/<name> JSON source: fn(query_params) ->
        payload dict. Must be registered before start(). The frontend
        wires /debug/fleet and /debug/routing here
        (docs/observability.md "Fleet view")."""
        self._debug[name] = fn

    async def start(self) -> str:
        app = web.Application()
        app.add_routes(
            [
                web.get("/live", self._live),
                web.get("/health", self._health),
                web.get("/metrics", self._metrics),
                web.get("/debug/timeline", self._debug_timeline),
                web.get("/debug/traces", self._debug_traces),
            ]
            + [
                web.get(f"/debug/{name}", self._make_debug(fn))
                for name, fn in self._debug.items()
            ]
        )
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for sock in site._server.sockets:  # type: ignore[union-attr]
            self.port = sock.getsockname()[1]
            break
        return f"http://{self.host}:{self.port}"

    async def stop(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()

    async def _live(self, request) -> web.Response:
        return web.json_response({"live": True, "uptime_s": time.time() - self._started_at})

    async def _health(self, request) -> web.Response:
        results = {}
        healthy = True
        for name, fn in self._checks.items():
            try:
                ok = bool(fn())
            except Exception:
                ok = False
            results[name] = ok
            healthy = healthy and ok
        return web.json_response(
            {"status": "healthy" if healthy else "unhealthy", "checks": results},
            status=200 if healthy else 503,
        )

    async def _metrics(self, request) -> web.Response:
        from dynamo_tpu.runtime import tracing

        if tracing.enabled():
            # silent span loss must be visible: the bounded exporter
            # queue's cumulative drop count rides every scrape
            self.runtime.metrics.gauge(
                "tracing_dropped_spans",
                "spans dropped by the bounded trace exporter queue/ring",
            ).set(tracing.dropped_spans())
        return web.Response(body=self.runtime.metrics.render(), content_type="text/plain")

    async def _debug_traces(self, request) -> web.Response:
        """Per-process span ring as JSON (`?trace_id=` filters one trace,
        unsampled; `?last_n=N` bounds the span count). The fleet-merge
        exporter (`scripts/dump_timeline.py --trace`) joins these rings
        across workers by trace_id into one Perfetto timeline."""
        from dynamo_tpu.runtime import tracing

        ring = tracing.span_ring()
        if ring is None:
            return web.json_response(
                {"error": "span ring not armed (set DYN_TRACE_RING)"},
                status=404)
        try:
            last_n = int(request.query.get("last_n", 0))
        except ValueError:
            last_n = 0
        payload = ring.payload(
            trace_id=request.query.get("trace_id") or None, last_n=last_n)
        payload["dropped_spans"] = tracing.dropped_spans()
        return web.json_response(payload)

    async def _debug_timeline(self, request) -> web.Response:
        """Flight-recorder ring as Chrome-trace JSON (open in Perfetto /
        chrome://tracing). `?last_n=N` bounds the record count."""
        if self._timeline is None:
            return web.json_response(
                {"error": "no timeline source on this process"}, status=404)
        try:
            last_n = int(request.query.get("last_n", 0)) or None
        except ValueError:
            last_n = None
        trace = self._timeline(last_n)
        return web.json_response(trace)

    def _make_debug(self, fn):
        async def handler(request) -> web.Response:
            try:
                payload = fn(dict(request.query))
            except Exception as e:
                log.warning("debug source failed", exc_info=True)
                return web.json_response({"error": str(e)}, status=500)
            return web.json_response(payload)

        return handler
