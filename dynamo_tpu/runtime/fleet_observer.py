"""Fleet observability plane: worker digests, fleet aggregation, and the
routing decision audit ring.

PR 5 made a single worker legible (flight recorder ring, per-request
phase spine); this module makes the FLEET legible. Three pieces:

1. **Worker digests** (push, not scrape): every worker runs a
   `DigestPublisher` that folds the engine's phase-spine callbacks and
   FPM samples into a compact periodic digest — mergeable phase
   histograms (fixed log-spaced buckets), queue depth, KV tier occupancy
   G1/G2/G3, prefetch hit counters, compile-family counters — and
   publishes it on the existing event plane under ``FLEET_DIGEST_SUBJECT``.
   One small msgpack message every ``period_s`` seconds per worker, so a
   1000-worker fleet costs the observer ~500 msgs/s, not 1000 scrapes.

2. **`FleetObserver`**: the consumer. Connects to every worker's
   publisher (discovery metadata ``digest_publisher``), windows digests
   by *local receive time* (sender clocks are advisory — a worker with a
   skewed clock must not corrupt fleet percentiles), dedups by the
   per-worker monotonic ``seq`` (late and duplicate digests are dropped,
   never double-counted), and merges histograms into per-worker and
   fleet-wide percentile estimates. Consumed by `/debug/fleet`, the SLO
   engine (planner/slo.py), the planner observer, and goodput's report.

3. **`RoutingAudit`**: a bounded ring of per-decision records — the
   candidate set each router considered WITH its scores (overlap blocks,
   load, prefetch hints, staleness), keyed by request id so a decision
   joins to that request's phase spine. Queryable at `/debug/routing`;
   misroutes become diagnosable rather than inferable.

Histogram design: fixed log-spaced bucket bounds shared by every worker,
so summaries merge by elementwise addition and a percentile is a single
cumulative walk with log-linear interpolation inside the bucket. The
same trick Prometheus histograms use, without requiring the workers and
the observer to negotiate anything.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from dynamo_tpu.runtime.event_plane import FLEET_DIGEST_SUBJECT, EventPublisher, EventSubscriber

log = logging.getLogger("dynamo_tpu.fleet_observer")

Worker = Tuple[int, int]

# -- mergeable phase histograms ---------------------------------------------
# log1.1-spaced bounds from 0.25ms to ~1900s: wide enough for ITL at the
# bottom and a wedged e2e at the top. The fine 1.1 factor bounds the
# in-bucket interpolation error of a percentile estimate at <10% worst
# case, typically ~2% (a factor-2 grid can be ~20-50% off inside one
# bucket, blowing the /debug/fleet-vs-goodput agreement budget). Cost:
# 167 small ints per non-empty phase, ~1KB msgpack per digest — still
# two orders below a scrape. 166 bounds -> 167 buckets (last is the
# overflow). Shared constants, never serialized per-message: a digest
# carries only the counts vector.
HIST_BASE_S = 0.00025
HIST_FACTOR = 1.1
HIST_NBOUNDS = 166
HIST_BOUNDS = tuple(HIST_BASE_S * HIST_FACTOR ** i for i in range(HIST_NBOUNDS))


def new_hist() -> List[int]:
    return [0] * (HIST_NBOUNDS + 1)


def hist_observe(counts: List[int], value_s: float) -> None:
    """Bucket a sample. Pure int/float ops — safe on the engine step
    thread (worker_common wires this behind engine.on_phases)."""
    if value_s < 0.0:
        value_s = 0.0
    import math

    if value_s <= HIST_BASE_S:
        counts[0] += 1
        return
    idx = int(math.log(value_s / HIST_BASE_S, HIST_FACTOR)) + 1
    counts[min(idx, HIST_NBOUNDS)] += 1


def merge_hist(into: List[int], other: List[int]) -> List[int]:
    """Elementwise add `other` into `into` (tolerates short/long vectors
    from a version-skewed worker by clamping to the local layout)."""
    for i in range(min(len(into), len(other))):
        into[i] += int(other[i])
    return into


def hist_count(counts: List[int]) -> int:
    return sum(counts)


def hist_quantile(counts: List[int], q: float) -> Optional[float]:
    """Percentile estimate via cumulative walk + log-linear interpolation
    within the bucket. None when empty. The overflow bucket reports its
    lower bound (same convention as Prometheus's +Inf clamp)."""
    total = sum(counts)
    if total <= 0:
        return None
    q = min(1.0, max(0.0, q))
    rank = q * total
    acc = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        if acc + c >= rank:
            frac = (rank - acc) / c
            if i >= HIST_NBOUNDS:
                return HIST_BOUNDS[-1]
            lo = 0.0 if i == 0 else HIST_BOUNDS[i - 1]
            hi = HIST_BOUNDS[i]
            return lo + (hi - lo) * frac
        acc += c
    return HIST_BOUNDS[-1]


def hist_frac_over(counts: List[int], threshold_s: float) -> Optional[float]:
    """Fraction of samples above `threshold_s` (bucket-interpolated).
    The SLO burn-rate input. None when empty."""
    total = sum(counts)
    if total <= 0:
        return None
    over = 0.0
    for i, c in enumerate(counts):
        if c <= 0:
            continue
        lo = 0.0 if i == 0 else HIST_BOUNDS[i - 1]
        hi = HIST_BOUNDS[i] if i < HIST_NBOUNDS else float("inf")
        if lo >= threshold_s:
            over += c
        elif hi > threshold_s and hi != float("inf"):
            over += c * (hi - threshold_s) / (hi - lo)
    return over / total


# phases folded into digest histograms (the latency spine's SLO-relevant
# subset; itl_s is a per-request sample LIST, flattened)
DIGEST_PHASES = ("ttft_s", "itl_s", "e2e_s", "queue_wait_s", "route_s",
                 "kv_onboard_s")


class DigestBuilder:
    """Worker-side accumulator: engine callbacks in, one digest dict out
    per window. `observe_phases` runs on the engine STEP thread — bucket
    increments only, no locks, no I/O (the flight-recorder append-path
    discipline; DYN-R004's spirit). `build()` runs on the event loop and
    swaps the accumulation dicts wholesale, so a torn read costs at most
    one sample landing in the next window."""

    # bounded per-window trace-id reservoir: enough to join a breaching
    # window back to concrete traces, small enough to never bloat a digest
    MAX_TRACE_IDS = 16

    def __init__(self, instance_id: int, dp_rank: int = 0):
        self.worker = [instance_id, dp_rank]
        self.seq = 0
        self._hists: Dict[str, List[int]] = {}
        self._counters = {"requests": 0, "decode_tokens": 0,
                          "prefill_tokens": 0, "decode_iters": 0,
                          "decode_wall_s": 0.0}
        self._last_fpm: Dict[str, Any] = {}
        self._trace_ids: List[str] = []

    # -- engine hooks (step thread) -----------------------------------------
    def observe_phases(self, phases: Dict[str, Any]) -> None:
        hists = self._hists
        self._counters["requests"] += 1
        tid = phases.get("trace_id")
        if (isinstance(tid, str) and len(self._trace_ids) < self.MAX_TRACE_IDS
                and tid not in self._trace_ids):
            # list append only (step thread); the window close swaps it
            self._trace_ids.append(tid)
        for key in DIGEST_PHASES:
            val = phases.get(key)
            if val is None:
                continue
            h = hists.get(key)
            if h is None:
                h = hists[key] = new_hist()
            if isinstance(val, list):
                for s in val:
                    if isinstance(s, (int, float)):
                        hist_observe(h, float(s))
            elif isinstance(val, (int, float)):
                hist_observe(h, float(val))

    def observe_fpm(self, m) -> None:
        kind = getattr(m, "kind", None)
        tokens = int(getattr(m, "scheduled_tokens", 0) or 0)
        c = self._counters
        if kind == "decode":
            c["decode_tokens"] += tokens
            c["decode_iters"] += 1
            c["decode_wall_s"] += float(getattr(m, "wall_time_s", 0.0) or 0.0)
        elif kind in ("prefill", "mixed"):
            c["prefill_tokens"] += tokens
        self._last_fpm = {
            "n_running": int(getattr(m, "n_running", 0) or 0),
            "n_waiting": int(getattr(m, "n_waiting", 0) or 0),
            "kv_usage": float(getattr(m, "kv_usage", 0.0) or 0.0),
        }

    # -- window close (event loop) ------------------------------------------
    def build(self, engine=None, period_s: float = 0.0) -> Dict[str, Any]:
        """Close the window: emit the digest and reset accumulation.
        `engine` (optional) is sampled for KV tier / prefetch / compile
        state — getattr-guarded so mockers and partial engines work."""
        hists, self._hists = self._hists, {}
        trace_ids, self._trace_ids = self._trace_ids, []
        counters = dict(self._counters)
        for k in self._counters:
            self._counters[k] = 0 if isinstance(self._counters[k], int) else 0.0
        self.seq += 1
        digest: Dict[str, Any] = {
            "worker": list(self.worker),
            "seq": self.seq,
            "ts": time.time(),
            "period_s": period_s,
            "phases": {k.removesuffix("_s"): h for k, h in hists.items()},
            "counters": counters,
            "queue": dict(self._last_fpm) or
                     {"n_running": 0, "n_waiting": 0, "kv_usage": 0.0},
        }
        if trace_ids:
            # join key back to the distributed span rings: the traces this
            # window's requests belonged to (bounded reservoir)
            digest["trace_ids"] = trace_ids
        if engine is not None:
            g2 = g3 = 0
            tiers: Dict[str, Any] = {}
            host_pool = getattr(engine, "host_pool", None)
            if host_pool is not None:
                try:
                    g2 = len(host_pool.host)
                    if getattr(host_pool, "disk", None) is not None:
                        g3 = len(host_pool.disk)
                    # per-tier byte/quantization occupancy (int8 tiered
                    # storage): stored_bytes is the ACTUAL footprint at
                    # the stored width, quant_blocks the int8 fraction's
                    # numerator. dynamo_top renders effective-vs-raw
                    # capacity from these; the router's measured-cost
                    # placement reads onboard_ewma below.
                    for name, pool in (("host", getattr(host_pool, "host", None)),
                                       ("disk", getattr(host_pool, "disk", None)),
                                       ("obj", getattr(host_pool, "obj", None))):
                        st = getattr(pool, "stats", None)
                        if not isinstance(st, dict):
                            continue
                        tiers[name] = {
                            "blocks": len(pool) if hasattr(pool, "__len__") else 0,
                            "stored_bytes": int(st.get("stored_bytes", 0)),
                            "quant_blocks": int(st.get("quant_blocks", 0)),
                        }
                        if "dedup_hits" in st:
                            # G4 prefix economy: fleet-shared store, so
                            # dedup hits are bytes the fleet did NOT
                            # store twice (dynamo_top's dedup ratio)
                            tiers[name]["dedup_hits"] = int(
                                st.get("dedup_hits", 0))
                            tiers[name]["dedup_bytes_saved"] = int(
                                st.get("dedup_bytes_saved", 0))
                except Exception:
                    log.debug("host pool size probe failed", exc_info=True)
            digest["kv"] = {
                "g1_usage": digest["queue"].get("kv_usage", 0.0),
                "g2_blocks": g2, "g3_blocks": g3,
            }
            kv_slice = getattr(engine, "slice_id", None)
            if kv_slice is not None:
                digest["kv"]["slice"] = str(kv_slice)
            if tiers:
                digest["kv"]["tiers"] = tiers
            ewma = getattr(engine, "kv_onboard_ewma", None)
            if ewma:
                digest["kv"]["onboard_ewma"] = {
                    t: {"s_per_block": round(float(v.get("s_per_block", 0.0)), 6),
                        "n": int(v.get("n", 0))}
                    for t, v in ewma.items()
                }
            pf = getattr(engine, "prefetch", None)
            if pf is not None:
                digest["prefetch"] = {
                    k: v for k, v in getattr(pf, "stats", {}).items()
                }
            runner = getattr(engine, "runner", None)
            if hasattr(runner, "compile_stats"):
                try:
                    digest["compile"] = {
                        fam: {"variants": st.get("variants", 0),
                              "calls": st.get("calls", 0)}
                        for fam, st in runner.compile_stats().items()
                    }
                except Exception:
                    log.debug("compile stats probe failed", exc_info=True)
            spec = getattr(engine, "spec_stats", None)
            if spec and spec.get("verify_iters", 0) > 0:
                rows = max(1, spec.get("verify_rows", 0))
                digest["spec"] = {
                    "drafted": spec.get("drafted", 0),
                    "accepted": spec.get("accepted", 0),
                    "rejected": spec.get("rejected", 0),
                    "verify_iters": spec.get("verify_iters", 0),
                    "accept_rate": (spec.get("accepted", 0)
                                    / max(1, spec.get("drafted", 0))),
                    "accepted_per_step": spec.get("spec_emitted", 0) / rows,
                    "tree_rows": spec.get("tree_rows", 0),
                    "tree_switches": spec.get("tree_switches", 0),
                }
            pool = getattr(engine, "pool", None)
            if pool is not None and hasattr(pool, "match_hit_blocks"):
                # session-tree reuse: cumulative engine-lifetime counters
                # (like spec above); hit_rate is reused prompt tokens over
                # all admitted prompt tokens
                sched = getattr(engine, "scheduler", None)
                reused = int(getattr(sched, "reused_prefix_tokens", 0) or 0)
                prompts = int(getattr(sched, "prompt_tokens_total", 0) or 0)
                digest["tree"] = {
                    "hit_blocks": int(pool.match_hit_blocks),
                    "forks": int(getattr(pool, "forks", 0)),
                    "reused_prefix_tokens": reused,
                    "prompt_tokens": prompts,
                    "hit_rate": round(reused / prompts, 4) if prompts else 0.0,
                }
            rec = getattr(engine, "recorder", None)
            if rec is not None and getattr(rec, "enabled", False):
                digest["recorder"] = {
                    "appended": rec.total_appended,
                    "anomalies_fired": rec.anomalies_fired,
                }
            # actuation state: the live co-scheduling knob values plus the
            # retune counter, so the planner's fast loop reads CURRENT
            # knobs off the digest plane (planner/actuator.py) and
            # dynamo_top's ACT column shows what the actuator last did
            sched = getattr(engine, "scheduler", None)
            if sched is not None and hasattr(sched, "mixed_prefill_tokens"):
                digest["act"] = {
                    "mixed_prefill_tokens": int(sched.mixed_prefill_tokens),
                    "mixed_prefill_seqs": int(
                        getattr(sched, "mixed_prefill_seqs", 0) or 0),
                    "spec_k": int(getattr(engine, "spec_k", 0) or 0),
                    "retunes": int(getattr(engine, "retunes", 0) or 0),
                }
        return digest


class DigestPublisher:
    """Periodic publish task wrapping a DigestBuilder. Owned by
    worker_common.serve_worker; the publisher is the runtime's shared
    event publisher (same socket FPM rides)."""

    def __init__(self, builder: DigestBuilder, pub: EventPublisher,
                 engine=None, period_s: float = 2.0):
        self.builder = builder
        self.pub = pub
        self.engine = engine
        self.period_s = max(0.1, float(period_s))
        self._task: Optional[asyncio.Task] = None
        self.published = 0

    @property
    def address(self) -> str:
        return self.pub.address

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())

    async def stop(self, flush: bool = True) -> None:
        # claim before the await: a concurrent stop() must see None, not
        # re-await the half-torn-down task (DYN-A007)
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        if flush:
            await self.publish_once()

    async def publish_once(self) -> None:
        digest = self.builder.build(self.engine, period_s=self.period_s)
        try:
            await self.pub.publish(FLEET_DIGEST_SUBJECT, digest)
            self.published += 1
        except Exception:
            # the digest plane is advisory: a transient publish failure
            # must never touch the serving path
            log.debug("digest publish failed", exc_info=True)

    async def _loop(self) -> None:
        try:
            while True:
                await asyncio.sleep(self.period_s)
                await self.publish_once()
        except asyncio.CancelledError:
            raise


class FleetObserver:
    """Aggregate worker digests into per-worker and fleet-wide views.

    Robustness contract (tested under churn in test_fleet_observer.py):
    - digests are windowed by LOCAL receive time, so a worker with a
      skewed wall clock cannot move fleet percentiles;
    - duplicates and out-of-order arrivals are dropped via the per-worker
      monotonic `seq` (a replayed digest never double-counts);
    - a worker that stops publishing ages out after `gone_after_s`
      (default 3x window) — a mid-window death leaves its already-counted
      samples in the window and then disappears, never NaNs.
    """

    def __init__(self, subscriber: Optional[EventSubscriber],
                 window_s: float = 60.0, max_digests_per_worker: int = 512):
        self._sub = subscriber
        self.window_s = float(window_s)
        self.gone_after_s = 3.0 * self.window_s
        self._max = int(max_digests_per_worker)
        # worker -> deque[(recv_mono_s, digest)]
        self._digests: Dict[Worker, Deque[Tuple[float, dict]]] = {}
        self._last_seq: Dict[Worker, int] = {}
        self._task: Optional[asyncio.Task] = None
        self.received = 0
        self.dropped_stale = 0  # duplicate / out-of-order seq

    # -- plumbing -----------------------------------------------------------
    def connect_publisher(self, address: str) -> None:
        if self._sub is not None:
            self._sub.connect(address)

    async def start(self) -> None:
        if self._task is None and self._sub is not None:
            self._task = asyncio.create_task(self._consume())

    async def stop(self) -> None:
        # claim before the await (DYN-A007): see ObserverPublisher.stop
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _consume(self) -> None:
        async for subject, payload in self._sub.events():
            if subject != FLEET_DIGEST_SUBJECT:
                continue
            try:
                self.ingest(payload)
            except Exception:
                log.debug("malformed digest dropped", exc_info=True)

    def ingest(self, payload: dict, now: Optional[float] = None) -> bool:
        """Feed one digest (the subscription task calls this; tests and
        in-process consumers call it directly). `now` is the observer's
        monotonic receive time. Returns False when dropped."""
        worker = tuple(payload.get("worker") or (0, 0))
        seq = int(payload.get("seq") or 0)
        last = self._last_seq.get(worker)
        if last is not None and seq <= last:
            self.dropped_stale += 1
            return False
        self._last_seq[worker] = seq
        q = self._digests.setdefault(worker, deque(maxlen=self._max))
        q.append((now if now is not None else time.monotonic(), payload))
        self.received += 1
        return True

    def forget(self, worker: Worker) -> None:
        self._digests.pop(tuple(worker), None)
        self._last_seq.pop(tuple(worker), None)

    def forget_instance(self, instance_id: int) -> int:
        """Drop every (instance_id, dp_rank) worker immediately — wired
        to discovery DELETE events so a killed worker's already-ingested
        digests stop feeding load aggregates the moment the fleet knows
        it is gone, instead of lingering until the 3x-window age-out. An
        actuator scaling against that ghost load would fight a worker
        that no longer exists. Returns the number of workers dropped."""
        victims = [w for w in self._digests if w[0] == instance_id]
        for w in victims:
            self.forget(w)
        return len(victims)

    # -- aggregation --------------------------------------------------------
    def _window(self, now: Optional[float], window_s: Optional[float]
                ) -> Dict[Worker, List[dict]]:
        now = now if now is not None else time.monotonic()
        win = window_s if window_s is not None else self.window_s
        cutoff = now - win
        out: Dict[Worker, List[dict]] = {}
        for worker, q in list(self._digests.items()):
            recent = [d for t, d in q if t >= cutoff]
            if not recent:
                if q and now - q[-1][0] > self.gone_after_s:
                    self.forget(worker)  # worker gone
                continue
            out[worker] = recent
        return out

    def workers(self, now: Optional[float] = None) -> List[Worker]:
        return sorted(self._window(now, None))

    def window_digests(self, now: Optional[float] = None,
                       window_s: Optional[float] = None
                       ) -> Dict[Worker, List[dict]]:
        """Raw in-window digests per worker (newest last) — the adapter
        surface for consumers doing their own aggregation (planner's
        FleetLoadObserver)."""
        return self._window(now, window_s)

    def phase_hists(self, now: Optional[float] = None,
                    window_s: Optional[float] = None,
                    worker: Optional[Worker] = None,
                    ) -> Dict[str, List[int]]:
        """Merged phase histograms over the window — fleet-wide, or one
        worker's. Keys are spine phase names without the _s suffix."""
        merged: Dict[str, List[int]] = {}
        for w, digests in self._window(now, window_s).items():
            if worker is not None and tuple(worker) != w:
                continue
            for d in digests:
                for phase, counts in (d.get("phases") or {}).items():
                    h = merged.get(phase)
                    if h is None:
                        h = merged[phase] = new_hist()
                    merge_hist(h, counts)
        return merged

    def onboard_costs(self, now: Optional[float] = None,
                      window_s: Optional[float] = None
                      ) -> Dict[Worker, Dict[str, float]]:
        """Per-worker measured onboarding cost: {worker: {tier:
        s_per_block}} from the newest in-window digest that carried an
        EWMA block. The KvRouter's topology-aware placement feeds this to
        WorkerSelector as `tier_costs`; workers that haven't measured a
        tier yet simply omit it (the selector falls back to its
        constant-cost priors — cold-start safe)."""
        out: Dict[Worker, Dict[str, float]] = {}
        for w, digests in self._window(now, window_s).items():
            for d in reversed(digests):
                ewma = (d.get("kv") or {}).get("onboard_ewma")
                if ewma:
                    out[w] = {
                        str(t): float(v.get("s_per_block", 0.0))
                        for t, v in ewma.items()
                        if isinstance(v, dict) and v.get("n", 0) > 0
                    }
                    break
        return out

    @staticmethod
    def _pct_block(hists: Dict[str, List[int]]) -> Dict[str, Any]:
        out = {}
        for phase, h in sorted(hists.items()):
            n = hist_count(h)
            if not n:
                continue
            out[phase] = {
                "n": n,
                "p50_s": round(hist_quantile(h, 0.5), 6),
                "p95_s": round(hist_quantile(h, 0.95), 6),
                "p99_s": round(hist_quantile(h, 0.99), 6),
            }
        return out

    def fleet(self, now: Optional[float] = None,
              window_s: Optional[float] = None) -> Dict[str, Any]:
        """The /debug/fleet payload core: per-worker rows (latest
        instantaneous state + windowed percentiles) and fleet-wide merged
        percentiles. The SLO engine decorates this with states."""
        windowed = self._window(now, window_s)
        workers_out = {}
        for w, digests in sorted(windowed.items()):
            latest = digests[-1]
            hists: Dict[str, List[int]] = {}
            counters = {"requests": 0, "decode_tokens": 0,
                        "prefill_tokens": 0, "decode_iters": 0,
                        "decode_wall_s": 0.0}
            for d in digests:
                for phase, counts in (d.get("phases") or {}).items():
                    merge_hist(hists.setdefault(phase, new_hist()), counts)
                for k, v in (d.get("counters") or {}).items():
                    if k in counters:
                        counters[k] += v
            row = {
                "worker": list(w),
                "digests": len(digests),
                "last_seq": latest.get("seq"),
                "last_ts": latest.get("ts"),
                "queue": latest.get("queue") or {},
                "kv": latest.get("kv") or {},
                "prefetch": latest.get("prefetch") or {},
                "compile": latest.get("compile") or {},
                # spec stats are cumulative on the engine; surface the most
                # recent digest that carried a block (quiet windows omit it)
                "spec": next((d["spec"] for d in reversed(digests)
                              if d.get("spec")), {}),
                "tree": next((d["tree"] for d in reversed(digests)
                              if d.get("tree")), {}),
                "act": next((d["act"] for d in reversed(digests)
                             if d.get("act")), {}),
                "counters": {k: round(v, 6) if isinstance(v, float) else v
                             for k, v in counters.items()},
                "phases": self._pct_block(hists),
            }
            workers_out[f"{w[0]:x}.{w[1]}"] = row
        return {
            "window_s": window_s if window_s is not None else self.window_s,
            "n_workers": len(windowed),
            "received": self.received,
            "dropped_stale": self.dropped_stale,
            "workers": workers_out,
            "fleet": {"phases": self._pct_block(
                self.phase_hists(now, window_s))},
        }


class RoutingAudit:
    """Bounded ring of routing decisions, joinable to the phase spine by
    request id. Append is O(1) on the frontend event loop; query walks
    at most `capacity` entries. Per-router instance — no module-global
    mutable state (DYN-R001)."""

    def __init__(self, capacity: int = 1024):
        self.capacity = max(1, int(capacity))
        self._ring: Deque[dict] = deque(maxlen=self.capacity)
        self.recorded = 0

    def record(self, rid: str, mode: str, chosen, *,
               candidates: Optional[List[dict]] = None,
               **extra: Any) -> None:
        entry = {
            "rid": rid,
            "ts": time.time(),
            "mode": mode,
            "chosen": list(chosen) if isinstance(chosen, (list, tuple))
                      else chosen,
            "candidates": candidates or [],
        }
        entry.update(extra)
        self._ring.append(entry)
        self.recorded += 1

    def query(self, rid: Optional[str] = None,
              last_n: Optional[int] = None) -> List[dict]:
        if rid is not None:
            return [e for e in self._ring if e.get("rid") == rid]
        entries = list(self._ring)
        if last_n is not None and last_n > 0:
            entries = entries[-last_n:]
        return entries

    def __len__(self) -> int:
        return len(self._ring)


def routing_debug_payload(audits: Dict[str, RoutingAudit],
                          rid: Optional[str] = None,
                          last_n: int = 64) -> Dict[str, Any]:
    """The /debug/routing payload: decisions across every router in the
    process (frontends run one PushRouter per endpoint client plus an
    optional KvRouter), newest last. `rid` filters to one request."""
    decisions: List[dict] = []
    for name, audit in sorted(audits.items()):
        for e in audit.query(rid=rid, last_n=None if rid else last_n):
            d = dict(e)
            d["router"] = name
            decisions.append(d)
    decisions.sort(key=lambda e: e.get("ts", 0.0))
    if rid is None and last_n > 0:
        decisions = decisions[-last_n:]
    return {
        "n": len(decisions),
        "recorded": sum(a.recorded for a in audits.values()),
        "decisions": decisions,
    }
