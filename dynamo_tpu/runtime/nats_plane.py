"""NATS-core event transport (the reference's alternative event plane).

Analog of reference lib/runtime/src/transports/event_plane/
nats_transport.rs: where the default ZMQ plane is brokerless (publishers
bind, subscribers track discovery), NATS routes everything through a
broker — operationally simpler subscription management at the cost of a
hop. This module speaks the NATS CORE wire protocol (text verbs:
INFO/CONNECT/PING/PONG/SUB/UNSUB/PUB/MSG) directly over asyncio — no
client library — so it interoperates with a real `nats-server` AND with
the `MiniNatsServer` below (a protocol-faithful broker used by tests and
dev stacks: `python -m dynamo_tpu.runtime.nats_plane --port 4222`).

Select with `DistributedRuntime(event_transport="nats")` +
`DYN_NATS_URL=nats://host:4222`. Payloads stay msgpack, subjects are the
same KV_EVENT/FPM/seq_sync names — only the transport changes.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

import msgpack

from dynamo_tpu.runtime.event_plane import EventPublisher, EventSubscriber

log = logging.getLogger("dynamo_tpu.nats")

DEFAULT_URL = "nats://127.0.0.1:4222"


def _parse_url(url: str) -> Tuple[str, int]:
    body = url.split("://", 1)[-1]
    host, _, port = body.partition(":")
    return host or "127.0.0.1", int(port or 4222)


def subject_matches(pattern: str, subject: str) -> bool:
    """NATS subject matching: '.'-separated tokens, '*' matches one
    token, '>' matches the rest."""
    pt, st = pattern.split("."), subject.split(".")
    for i, p in enumerate(pt):
        if i >= len(st):
            return False
        if p == ">":  # requires at least one remaining token (NATS)
            return True
        if p != "*" and p != st[i]:
            return False
    return len(pt) == len(st)


class NatsClient:
    """Minimal shared core-protocol client (publisher + subscriber)."""

    def __init__(self, url: str):
        self.url = url
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._sid = 0
        self._queue: asyncio.Queue = asyncio.Queue()
        self._reader_task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()
        self._closed = False  # closed by US (no reconnect)
        self._subs: Dict[int, str] = {}  # sid -> pattern (re-SUB on redial)

    async def ensure_connected(self) -> None:
        async with self._lock:
            if self._writer is not None or self._closed:
                return
            # retire the previous connection's reader BEFORE dialing: its
            # cleanup must not clobber the fresh writer, and a stale loop
            # still SUBed would double-deliver every event into the shared
            # queue after a broker restart
            prev = self._reader_task
            if prev is not None and not prev.done():
                prev.cancel()
                try:
                    await prev
                except asyncio.CancelledError:
                    pass
                except Exception:
                    # it died with the old connection — that is why we
                    # are re-dialling
                    log.debug("old NATS reader exited", exc_info=True)
            host, port = _parse_url(self.url)
            self._reader, self._writer = await asyncio.open_connection(host, port)
            info = await self._reader.readline()  # INFO {...}
            if not info.startswith(b"INFO"):
                raise ConnectionError(f"not a NATS server: {info[:40]!r}")
            self._writer.write(
                b'CONNECT {"verbose":false,"protocol":0,'
                b'"name":"dynamo_tpu"}\r\nPING\r\n'
            )
            # re-establish subscriptions after a broker restart (ZMQ
            # reconnects transparently; the brokered transport must too)
            for sid, pattern in self._subs.items():
                self._writer.write(f"SUB {pattern} {sid}\r\n".encode())
            await self._writer.drain()
            self._reader_task = asyncio.create_task(self._read_loop())

    async def _read_loop(self) -> None:
        # operate on THIS connection's streams (not self._reader/_writer):
        # after a reconnect the instance attributes point at the fresh
        # connection, and this loop's cleanup must only retire its own
        reader, writer = self._reader, self._writer
        try:
            while True:
                line = await reader.readline()
                if not line:
                    break
                if line.startswith(b"MSG "):
                    # MSG <subject> <sid> [reply-to] <#bytes>
                    parts = line.decode().strip().split(" ")
                    n = int(parts[-1])
                    # frame body follows its MSG header immediately; the
                    # idle wait is the readline above, and conn death is
                    # surfaced as ConnectionError/IncompleteReadError
                    payload = await reader.readexactly(n + 2)  # +\r\n  # dynlint: disable=DYN-R003
                    await self._queue.put((parts[1], payload[:n]))
                elif line.startswith(b"PING"):
                    writer.write(b"PONG\r\n")
                    await writer.drain()
                # PONG / +OK / INFO updates: ignored
        except (asyncio.CancelledError, ConnectionError, OSError):
            pass
        finally:
            # mark dead so the next ensure_connected() re-dials — but only
            # if we still own the live connection
            if self._writer is writer:
                self._writer = None
                self._reader = None
            try:
                writer.close()
            except OSError:
                pass  # already torn down
            await self._queue.put(None)  # wake consumers on disconnect

    async def publish(self, subject: str, payload: bytes) -> None:
        frame = (
            f"PUB {subject} {len(payload)}\r\n".encode() + payload + b"\r\n"
        )
        for attempt in (0, 1):  # one transparent redial on a dead broker
            await self.ensure_connected()
            if self._writer is None:
                raise ConnectionError("nats client closed")
            try:
                self._writer.write(frame)
                await self._writer.drain()
                return
            except (ConnectionError, OSError):
                self._writer = None
                if attempt:
                    raise

    async def subscribe(self, subject: str) -> int:
        await self.ensure_connected()
        self._sid += 1
        self._subs[self._sid] = subject
        if self._writer is not None:
            self._writer.write(f"SUB {subject} {self._sid}\r\n".encode())
            await self._writer.drain()
        return self._sid

    async def next_msg(self):
        """Next (subject, payload) or None when the connection dropped;
        the caller may loop — ensure_connected() will redial."""
        return await self._queue.get()

    def close_nowait(self) -> None:
        """Synchronous teardown (callers in non-async close paths — the
        request-plane _NatsMuxConn.close — share ONE implementation with
        the async close instead of poking private state)."""
        self._closed = True
        if self._reader_task is not None:
            self._reader_task.cancel()
        if self._writer is not None:
            self._writer.close()
            self._writer = None

    async def close(self) -> None:
        self.close_nowait()


class NatsEventPublisher(EventPublisher):
    def __init__(self, url: Optional[str] = None):
        self.url = url or os.environ.get("DYN_NATS_URL", DEFAULT_URL)
        self._client = NatsClient(self.url)

    @property
    def address(self) -> str:
        # brokered topology: the advertised address IS the broker —
        # subscribers "connecting to a publisher" just join the broker
        return self.url

    async def publish(self, subject: str, payload: Any) -> None:
        await self._client.publish(
            subject, msgpack.packb(payload, use_bin_type=True)
        )

    async def close(self) -> None:
        await self._client.close()


class NatsEventSubscriber(EventSubscriber):
    def __init__(self, subjects: Optional[List[str]] = None,
                 url: Optional[str] = None):
        self.subjects = list(subjects or [">"])
        self.url = url or os.environ.get("DYN_NATS_URL", DEFAULT_URL)
        self._clients: Dict[str, NatsClient] = {}

    def connect(self, address: str) -> None:
        url = address if address.startswith("nats://") else self.url
        if url not in self._clients:
            self._clients[url] = NatsClient(url)

    def disconnect(self, address: str) -> None:
        # brokered: publisher departure needs no action (the broker stays)
        pass

    async def events(self) -> AsyncIterator[Tuple[str, Any]]:
        if not self._clients:
            self.connect(self.url)
        queues = []
        for c in self._clients.values():
            await c.ensure_connected()
            for s in self.subjects:
                # '' (ZMQ subscribe-all) → '>'; other subjects match
                # EXACTLY / by NATS wildcard — NATS cannot express ZMQ's
                # byte-prefix filters (all in-tree subjects are exact)
                await c.subscribe(s if s else ">")
            queues.append(c)
        if len(queues) == 1:
            c = queues[0]
            while True:
                # subscriber loop: waiting forever for the next event IS
                # the contract; broker death yields None via the reader
                item = await c.next_msg()  # dynlint: disable=DYN-R003
                if item is None:
                    if c._closed:
                        return
                    # broker dropped: redial (with backoff) UNTIL it
                    # comes back — only then return to next_msg(), since
                    # nothing refills the queue while disconnected
                    while not c._closed:
                        await asyncio.sleep(0.5)
                        try:
                            await c.ensure_connected()
                            break
                        except (ConnectionError, OSError):
                            continue
                    continue
                subject, raw = item
                yield subject, msgpack.unpackb(raw, raw=False)
        else:  # pragma: no cover - multiple brokers is unusual
            pending = {
                asyncio.create_task(c.next_msg()): c for c in queues
            }
            while pending:
                done, _ = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for t in done:
                    c = pending.pop(t)
                    item = t.result()
                    if item is None:
                        continue
                    subject, raw = item
                    yield subject, msgpack.unpackb(raw, raw=False)
                    pending[asyncio.create_task(c.next_msg())] = c

    async def close(self) -> None:
        for c in self._clients.values():
            await c.close()


# --------------------------------------------------------------------------
# MiniNatsServer: protocol-faithful core broker (tests / dev stacks)
# --------------------------------------------------------------------------


class MiniNatsServer:
    """Asyncio NATS-core broker: INFO/CONNECT/PING/SUB/UNSUB/PUB/MSG with
    '*'/'>' wildcards. Enough protocol for real NATS core clients; no JetStream,
    auth, or clustering (use a real nats-server for those)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None
        # conn id -> (writer, {sid: pattern})
        self._conns: Dict[int, Tuple[asyncio.StreamWriter, Dict[str, str]]] = {}
        self._next = 0

    @property
    def url(self) -> str:
        return f"nats://{self.host}:{self.port}"

    async def start(self) -> str:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        log.info("mini nats broker on %s", self.url)
        return self.url

    async def stop(self) -> None:
        # sever client connections FIRST: Python 3.12's wait_closed()
        # waits for live handlers, which are blocked in readline()
        for wr, _ in list(self._conns.values()):
            try:
                wr.close()
            except OSError:
                pass  # already torn down
        self._conns.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        cid = self._next
        self._next += 1
        subs: Dict[str, str] = {}
        self._conns[cid] = (writer, subs)
        writer.write(
            b'INFO {"server_id":"dynamo-mini","version":"0.0.1",'
            b'"proto":0,"max_payload":16777216}\r\n'
        )
        try:
            await writer.drain()
            while True:
                line = await reader.readline()
                if not line:
                    break
                verb = line.decode(errors="replace").strip()
                up = verb.upper()
                if up.startswith("CONNECT"):
                    continue
                if up.startswith("PING"):
                    writer.write(b"PONG\r\n")
                    await writer.drain()
                elif up.startswith("PONG"):
                    continue
                elif up.startswith("SUB "):
                    parts = verb.split(" ")
                    # SUB <subject> [queue] <sid>
                    subs[parts[-1]] = parts[1]
                elif up.startswith("UNSUB "):
                    subs.pop(verb.split(" ")[1], None)
                elif up.startswith("PUB "):
                    parts = verb.split(" ")
                    subject = parts[1]
                    n = int(parts[-1])
                    # body follows its PUB header; IncompleteReadError on
                    # conn death is handled below
                    payload = await reader.readexactly(n + 2)  # dynlint: disable=DYN-R003
                    await self._fanout(subject, payload[:n])
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass
        finally:
            self._conns.pop(cid, None)
            try:
                writer.close()
            except OSError:
                pass  # already torn down

    async def _fanout(self, subject: str, payload: bytes) -> None:
        # real NATS delivers once PER MATCHING SUBSCRIPTION (sid), not per
        # connection — overlapping patterns must double-deliver here too
        # or tests pass against this broker and double-count in prod
        writers = []
        for cid, (wr, subs) in list(self._conns.items()):
            wrote = False
            for sid, pattern in subs.items():
                if subject_matches(pattern, subject):
                    try:
                        wr.write(
                            f"MSG {subject} {sid} {len(payload)}\r\n".encode()
                            + payload + b"\r\n"
                        )
                        wrote = True
                    except (ConnectionError, OSError):
                        self._conns.pop(cid, None)
                        wrote = False
                        break
            if wrote:
                writers.append((cid, wr))

        async def _drain(cid, wr):
            try:
                # a stalled consumer must not wedge the whole broker: cap
                # the drain and drop the laggard connection instead
                await asyncio.wait_for(wr.drain(), timeout=5.0)
            except (ConnectionError, OSError, asyncio.TimeoutError):
                self._conns.pop(cid, None)
                try:
                    wr.close()
                except OSError:
                    pass  # already torn down

        if writers:
            await asyncio.gather(*[_drain(c, w) for c, w in writers])


def main(argv=None) -> None:  # pragma: no cover - dev helper
    import argparse

    p = argparse.ArgumentParser("dynamo_tpu.runtime.nats_plane")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=4222)
    args = p.parse_args(argv)

    async def run():
        srv = MiniNatsServer(args.host, args.port)
        await srv.start()
        print(f"mini nats broker on {srv.url}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
