"""Distributed read-write lock over etcd transactions.

TPU-native analog of the reference's DistributedRWLock
(lib/runtime/src/transports/etcd/lock.rs:87-230): writer exclusivity via an
atomic version-compare txn on `{prefix}/writer`, shared readers under
`{prefix}/readers/{id}`, every key bound to the holder's lease so a crashed
holder releases automatically when its lease expires. Used by HA control
paths (e.g. single-writer planner execution, router snapshot election).

Semantics match the reference:
- try_write_lock: txn-create writer key if version==0, then verify no
  readers (rollback if any). Non-blocking; returns None on contention.
- write_lock / read_lock: 100ms polling with a deadline.
- read locks exclude the writer atomically (txn: writer version==0 →
  put reader key); multiple readers coexist.
"""

from __future__ import annotations

import asyncio
import base64
import time
import uuid
from typing import Optional

from dynamo_tpu.runtime.etcd import EtcdDiscovery, _b64, _prefix_end

POLL_S = 0.1
DEFAULT_TIMEOUT_S = 5.0


class LockGuard:
    """Releases the held key on __aexit__/release; the lease releases it
    if the holder dies first."""

    def __init__(self, lock: "DistributedRWLock", key: str, token: str):
        self._lock = lock
        self._key = key
        self._token = token
        self._released = False

    async def release(self) -> None:
        if self._released:
            return
        self._released = True
        # guarded delete: only remove the key if it still holds OUR token.
        # A stale ex-holder (lease expired during a pause, key re-acquired
        # by someone else) must not delete the current holder's lock —
        # unconditional delete would hand the mutex to a third party.
        await self._lock._etcd._post(
            "/v3/kv/txn",
            {
                "compare": [
                    {
                        "key": _b64(self._key),
                        "target": "VALUE",
                        "result": "EQUAL",
                        "value": _b64(self._token),
                    }
                ],
                "success": [
                    {"request_delete_range": {"key": _b64(self._key)}}
                ],
                "failure": [],
            },
        )

    async def __aenter__(self) -> "LockGuard":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.release()


class DistributedRWLock:
    def __init__(self, etcd: EtcdDiscovery, prefix: str):
        self._etcd = etcd
        self.prefix = f"locks/{prefix}"
        self.writer_key = f"{self.prefix}/writer"
        self.reader_prefix = f"{self.prefix}/readers/"

    async def _txn_create(self, key: str, value: str) -> bool:
        """Atomically create `key` (only if absent) bound to our lease."""
        lease = await self._etcd._lease()
        out = await self._etcd._post(
            "/v3/kv/txn",
            {
                "compare": [
                    {
                        "key": _b64(key),
                        "target": "VERSION",
                        "result": "EQUAL",
                        "version": "0",
                    }
                ],
                "success": [
                    {
                        "request_put": {
                            "key": _b64(key),
                            "value": _b64(value),
                            "lease": str(lease),
                        }
                    }
                ],
                "failure": [],
            },
        )
        return bool(out.get("succeeded"))

    async def _reader_count(self) -> int:
        out = await self._etcd._post(
            "/v3/kv/range",
            {
                "key": _b64(self.reader_prefix),
                "range_end": _prefix_end(self.reader_prefix),
                "count_only": True,
            },
        )
        return int(out.get("count", len(out.get("kvs") or [])))

    async def try_write_lock(self) -> Optional[LockGuard]:
        """Non-blocking exclusive acquire; None if a writer or readers
        exist. (Same sub-ms create-then-check window as the reference.)"""
        token = f"writing:{uuid.uuid4().hex}"
        if not await self._txn_create(self.writer_key, token):
            return None
        guard = LockGuard(self, self.writer_key, token)
        if await self._reader_count() > 0:
            await guard.release()  # rollback
            return None
        return guard

    async def write_lock(self, timeout: Optional[float] = None) -> LockGuard:
        deadline = time.monotonic() + (timeout or DEFAULT_TIMEOUT_S)
        while True:
            guard = await self.try_write_lock()
            if guard is not None:
                return guard
            if time.monotonic() > deadline:
                raise TimeoutError(f"write lock {self.prefix} not acquired")
            await asyncio.sleep(POLL_S)

    async def read_lock(
        self, reader_id: Optional[str] = None, timeout: Optional[float] = None
    ) -> LockGuard:
        """Shared acquire: atomically excludes the writer, coexists with
        other readers."""
        reader_id = reader_id or uuid.uuid4().hex[:12]
        key = self.reader_prefix + reader_id
        token = f"reading:{uuid.uuid4().hex}"
        deadline = time.monotonic() + (timeout or DEFAULT_TIMEOUT_S)
        while True:
            lease = await self._etcd._lease()
            out = await self._etcd._post(
                "/v3/kv/txn",
                {
                    "compare": [
                        {
                            "key": _b64(self.writer_key),
                            "target": "VERSION",
                            "result": "EQUAL",
                            "version": "0",
                        }
                    ],
                    "success": [
                        {
                            "request_put": {
                                "key": _b64(key),
                                "value": _b64(token),
                                "lease": str(lease),
                            }
                        }
                    ],
                    "failure": [],
                },
            )
            if out.get("succeeded"):
                return LockGuard(self, key, token)
            if time.monotonic() > deadline:
                raise TimeoutError(f"read lock {self.prefix} not acquired")
            await asyncio.sleep(POLL_S)
