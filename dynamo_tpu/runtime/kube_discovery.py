"""Kubernetes discovery backend.

The reference runtime's alternative to etcd discovery in-cluster
(lib/runtime discovery backends): instances live as ConfigMap-backed
registrations (one ConfigMap per instance, labeled for list/watch) in a
namespace, with liveness via a heartbeat timestamp annotation — the same
record/lease semantics as the file backend, expressed as Kubernetes
objects so `kubectl get cm -l app=dynamo-tpu` shows the live topology.

Uses the plain REST API with service-account auth (no kubernetes client
library), matching planner/connector.py's KubernetesConnector. Watching is
poll-based (list with labelSelector) — robust against watch-stream
bookmarks and adequate at control-plane rates.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from pathlib import Path
from typing import AsyncIterator, Dict, List, Optional

from dynamo_tpu.runtime.component import Instance
from dynamo_tpu.runtime.discovery import DiscoveryBackend, DiscoveryEvent

log = logging.getLogger("dynamo_tpu.runtime.kube")

LABEL = "app.kubernetes.io/managed-by=dynamo-tpu-discovery"


class KubeDiscovery(DiscoveryBackend):
    def __init__(
        self,
        namespace: str = "default",
        api_base: Optional[str] = None,
        token: Optional[str] = None,
        lease_ttl: float = 30.0,  # generous: heartbeat annotations compare
        #   WRITER wall clocks against the reader's (same caveat as k8s
        #   leader election) — keep ttl >> worst-case NTP skew
        poll_interval: float = 1.0,
    ):
        from dynamo_tpu.runtime.kube_client import KubeApiClient

        self._client = KubeApiClient(api_base=api_base, token=token)
        self.api_base = self._client.api_base
        self.namespace = namespace
        self.lease_ttl = lease_ttl
        self.poll_interval = poll_interval
        self._mine: Dict[str, Instance] = {}

    # -- REST helpers -------------------------------------------------------
    async def _http(self):
        return await self._client.http()

    def _cm_url(self, name: str = "") -> str:
        base = f"{self.api_base}/api/v1/namespaces/{self.namespace}/configmaps"
        return f"{base}/{name}" if name else base

    @staticmethod
    def _cm_name(instance: Instance) -> str:
        # DNS-1123 slug + content hash of the EXACT path: the slug is lossy
        # ("/", "_" → "-", lowercased), so the hash keeps distinct paths
        # from colliding onto one ConfigMap
        import hashlib

        slug = instance.path.replace("/", "-").replace("_", "-").lower()[:200]
        h = hashlib.blake2b(instance.path.encode(), digest_size=4).hexdigest()
        return f"dyn-{slug}-{h}"

    def _to_cm(self, instance: Instance) -> dict:
        return {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {
                "name": self._cm_name(instance),
                "labels": {LABEL.split("=")[0]: LABEL.split("=")[1]},
                "annotations": {"dynamo-tpu/heartbeat": str(time.time())},
            },
            "data": {
                "path": instance.path,
                "instance": json.dumps(instance.to_dict()),
            },
        }

    # -- DiscoveryBackend ---------------------------------------------------
    async def register(self, instance: Instance) -> None:
        s = await self._http()
        body = self._to_cm(instance)
        async with s.post(self._cm_url(), json=body) as r:
            if r.status == 409:  # exists: replace
                async with s.put(self._cm_url(self._cm_name(instance)), json=body) as r2:
                    r2.raise_for_status()
            else:
                r.raise_for_status()
        self._mine[instance.path] = instance

    async def unregister(self, instance: Instance) -> None:
        self._mine.pop(instance.path, None)
        s = await self._http()
        async with s.delete(self._cm_url(self._cm_name(instance))) as r:
            if r.status not in (200, 404):
                r.raise_for_status()

    async def heartbeat(self) -> None:
        # refresh the heartbeat annotation (re-PUT keeps it one round trip)
        for inst in list(self._mine.values()):
            try:
                s = await self._http()
                async with s.put(
                    self._cm_url(self._cm_name(inst)), json=self._to_cm(inst)
                ) as r:
                    if r.status == 404:  # lost (GC'd): re-create
                        await self.register(inst)
                    else:
                        r.raise_for_status()
            except Exception:
                log.warning("kube heartbeat failed for %s", inst.path, exc_info=True)

    async def _scan(self, prefix: str) -> Dict[str, Instance]:
        s = await self._http()
        out: Dict[str, Instance] = {}
        cutoff = time.time() - self.lease_ttl
        async with s.get(self._cm_url(), params={"labelSelector": LABEL}) as r:
            r.raise_for_status()
            body = await r.json()
        for item in body.get("items", []):
            try:
                hb = float((item["metadata"].get("annotations") or {})
                           .get("dynamo-tpu/heartbeat", 0))
                if hb < cutoff:
                    continue  # lease expired (stale pod)
                inst = Instance.from_dict(json.loads(item["data"]["instance"]))
                if inst.path.startswith(prefix):
                    out[inst.path] = inst
            except (KeyError, ValueError):
                continue
        return out

    async def list_instances(self, prefix: str = "") -> List[Instance]:
        return list((await self._scan(prefix or "services/")).values())

    async def watch(self, prefix: str = "") -> AsyncIterator[DiscoveryEvent]:
        from dynamo_tpu.runtime.discovery import poll_diff_watch

        prefix = prefix or "services/"
        async for ev in poll_diff_watch(
            lambda: self._scan(prefix), self.poll_interval,
            on_error=lambda e: log.warning("kube scan failed (%s); retrying", e),
        ):
            yield ev

    async def close(self) -> None:
        await self._client.close()
