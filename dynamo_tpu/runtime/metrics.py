"""Prometheus metrics with auto-injected hierarchy labels.

Analog of the reference MetricsHierarchy (lib/runtime/src/distributed.rs:93-109):
metrics created through a runtime/component/endpoint handle automatically
carry dynamo_namespace / dynamo_component / dynamo_endpoint labels.

When `prometheus_client` is absent, `make_metrics` degrades to
`SimpleMetrics` — plain dict-backed counters/gauges/histograms with a
minimal text-exposition `render()` — so StatusServer `/metrics` is never
empty. The degradation is logged once at startup.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Iterable, Optional, Tuple

log = logging.getLogger("dynamo_tpu.metrics")

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    _HAVE_PROM = True
except ImportError:  # pragma: no cover
    _HAVE_PROM = False

PREFIX = "dynamo_"
HIERARCHY_LABELS = ("dynamo_namespace", "dynamo_component", "dynamo_endpoint")


class MetricsHierarchy:
    """A node in the namespace/component/endpoint label hierarchy."""

    def __init__(
        self,
        registry: Optional["CollectorRegistry"] = None,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.registry = registry if registry is not None else (CollectorRegistry() if _HAVE_PROM else None)
        self.labels = {k: "" for k in HIERARCHY_LABELS}
        self.labels.update(labels or {})
        self._metrics: Dict[str, object] = {}

    def child(self, **labels: str) -> "MetricsHierarchy":
        merged = dict(self.labels)
        merged.update(labels)
        node = MetricsHierarchy(registry=self.registry, labels=merged)
        node._metrics = self._metrics  # family cache is shared
        return node

    def _family(self, cls, name: str, doc: str, extra_labels: Iterable[str] = ()):
        key = f"{cls.__name__}:{name}"
        fam = self._metrics.get(key)
        if fam is None:
            fam = cls(
                PREFIX + name,
                doc,
                list(HIERARCHY_LABELS) + list(extra_labels),
                registry=self.registry,
            )
            self._metrics[key] = fam
        return fam

    def counter(self, name: str, doc: str = "", **extra: str):
        fam = self._family(Counter, name, doc, extra.keys())
        return fam.labels(**self.labels, **extra)

    def gauge(self, name: str, doc: str = "", **extra: str):
        fam = self._family(Gauge, name, doc, extra.keys())
        return fam.labels(**self.labels, **extra)

    def histogram(self, name: str, doc: str = "", **extra: str):
        fam = self._family(Histogram, name, doc, extra.keys())
        return fam.labels(**self.labels, **extra)

    def render(self) -> bytes:
        """Prometheus exposition format (served at /metrics)."""
        if not _HAVE_PROM or self.registry is None:  # pragma: no cover
            return b""
        return generate_latest(self.registry)


class _SimpleValue:
    """One labeled series in the fallback store. Counter/gauge hold a
    float; histogram keeps count/sum (no buckets — the fallback trades
    quantiles for zero dependencies)."""

    __slots__ = ("value", "count", "lock")

    def __init__(self) -> None:
        self.value = 0.0
        self.count = 0
        self.lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self.lock:
            self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self.lock:
            self.value -= amount

    def set(self, value: float) -> None:
        with self.lock:
            self.value = float(value)

    def observe(self, value: float) -> None:
        with self.lock:
            self.value += float(value)
            self.count += 1


class SimpleMetrics:
    """Dict-backed MetricsHierarchy stand-in when prometheus_client is
    unavailable: same counter/gauge/histogram/child surface, and a
    minimal Prometheus text-exposition `render()` so StatusServer
    /metrics still serves real numbers."""

    _KINDS = {"counter": "counter", "gauge": "gauge",
              "histogram": "histogram"}

    def __init__(self, labels: Optional[Dict[str, str]] = None,
                 store: Optional[Dict] = None):
        self.labels = {k: "" for k in HIERARCHY_LABELS}
        self.labels.update(labels or {})
        # (kind, name, label_items) -> _SimpleValue; shared across children
        self._store: Dict[Tuple[str, str, Tuple], _SimpleValue] = (
            store if store is not None else {})

    def child(self, **labels: str) -> "SimpleMetrics":
        merged = dict(self.labels)
        merged.update(labels)
        return SimpleMetrics(labels=merged, store=self._store)

    def _series(self, kind: str, name: str, extra: Dict[str, str]):
        labels = dict(self.labels)
        labels.update({k: str(v) for k, v in extra.items()})
        key = (kind, name, tuple(sorted(labels.items())))
        val = self._store.get(key)
        if val is None:
            val = self._store.setdefault(key, _SimpleValue())
        return val

    def counter(self, name: str, doc: str = "", **extra: str):
        return self._series("counter", name, extra)

    def gauge(self, name: str, doc: str = "", **extra: str):
        return self._series("gauge", name, extra)

    def histogram(self, name: str, doc: str = "", **extra: str):
        return self._series("histogram", name, extra)

    def render(self) -> bytes:
        """Prometheus text exposition from the dict store. Histograms
        expose only _count and _sum series (no buckets)."""
        by_name: Dict[Tuple[str, str], list] = {}
        for (kind, name, label_items), val in sorted(self._store.items()):
            by_name.setdefault((kind, name), []).append((label_items, val))
        lines = []
        for (kind, name), series in by_name.items():
            full = PREFIX + name
            lines.append(f"# TYPE {full} {self._KINDS[kind]}")
            for label_items, val in series:
                lbl = ",".join(
                    f'{k}="{v}"' for k, v in label_items)
                if kind == "histogram":
                    lines.append(f"{full}_count{{{lbl}}} {val.count}")
                    lines.append(f"{full}_sum{{{lbl}}} {val.value}")
                else:
                    lines.append(f"{full}{{{lbl}}} {val.value}")
        return ("\n".join(lines) + "\n").encode() if lines else b""


# kept for back-compat with external callers; SimpleMetrics is what
# make_metrics now degrades to
class NullMetrics(SimpleMetrics):  # pragma: no cover
    def render(self) -> bytes:
        return b""


_warned_no_prom = False


def make_metrics(namespace: str = "") -> MetricsHierarchy:
    global _warned_no_prom
    if _HAVE_PROM:
        return MetricsHierarchy(labels={"dynamo_namespace": namespace})
    if not _warned_no_prom:  # pragma: no cover
        _warned_no_prom = True
        log.warning(
            "prometheus_client is not installed: /metrics degrades to the "
            "dict-backed text fallback (no histogram buckets)")
    return SimpleMetrics(labels={"dynamo_namespace": namespace})
