"""Prometheus metrics with auto-injected hierarchy labels.

Analog of the reference MetricsHierarchy (lib/runtime/src/distributed.rs:93-109):
metrics created through a runtime/component/endpoint handle automatically
carry dynamo_namespace / dynamo_component / dynamo_endpoint labels.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

try:
    from prometheus_client import (
        CollectorRegistry,
        Counter,
        Gauge,
        Histogram,
        generate_latest,
    )

    _HAVE_PROM = True
except ImportError:  # pragma: no cover
    _HAVE_PROM = False

PREFIX = "dynamo_"
HIERARCHY_LABELS = ("dynamo_namespace", "dynamo_component", "dynamo_endpoint")


class MetricsHierarchy:
    """A node in the namespace/component/endpoint label hierarchy."""

    def __init__(
        self,
        registry: Optional["CollectorRegistry"] = None,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.registry = registry if registry is not None else (CollectorRegistry() if _HAVE_PROM else None)
        self.labels = {k: "" for k in HIERARCHY_LABELS}
        self.labels.update(labels or {})
        self._metrics: Dict[str, object] = {}

    def child(self, **labels: str) -> "MetricsHierarchy":
        merged = dict(self.labels)
        merged.update(labels)
        node = MetricsHierarchy(registry=self.registry, labels=merged)
        node._metrics = self._metrics  # family cache is shared
        return node

    def _family(self, cls, name: str, doc: str, extra_labels: Iterable[str] = ()):
        key = f"{cls.__name__}:{name}"
        fam = self._metrics.get(key)
        if fam is None:
            fam = cls(
                PREFIX + name,
                doc,
                list(HIERARCHY_LABELS) + list(extra_labels),
                registry=self.registry,
            )
            self._metrics[key] = fam
        return fam

    def counter(self, name: str, doc: str = "", **extra: str):
        fam = self._family(Counter, name, doc, extra.keys())
        return fam.labels(**self.labels, **extra)

    def gauge(self, name: str, doc: str = "", **extra: str):
        fam = self._family(Gauge, name, doc, extra.keys())
        return fam.labels(**self.labels, **extra)

    def histogram(self, name: str, doc: str = "", **extra: str):
        fam = self._family(Histogram, name, doc, extra.keys())
        return fam.labels(**self.labels, **extra)

    def render(self) -> bytes:
        """Prometheus exposition format (served at /metrics)."""
        if not _HAVE_PROM or self.registry is None:  # pragma: no cover
            return b""
        return generate_latest(self.registry)


class NullMetrics:
    """No-op stand-in when prometheus_client is unavailable."""  # pragma: no cover

    def child(self, **labels):
        return self

    def _noop(self, *a, **k):
        class _N:
            def inc(self, *a, **k):
                pass

            def dec(self, *a, **k):
                pass

            def set(self, *a, **k):
                pass

            def observe(self, *a, **k):
                pass

        return _N()

    counter = gauge = histogram = _noop

    def render(self) -> bytes:
        return b""


def make_metrics(namespace: str = "") -> MetricsHierarchy:
    if _HAVE_PROM:
        return MetricsHierarchy(labels={"dynamo_namespace": namespace})
    return NullMetrics()  # pragma: no cover
