"""Pallas TPU kernels for the serving hot ops (the role CUDA kernels play
in the reference: kvbm-kernels/cuda/tensor_kernels.cu, block_copy.cu — here
they are paged attention + block copy, TPU-first)."""
