"""Pallas batched KV page copy / layout permute kernels.

TPU-native equivalents of the reference's block-movement CUDA kernels
(lib/llm/src/kernels/block_copy.cu copy_blocks_kernel:40-46 — batched
block copies for transfers — and lib/kvbm-kernels/cuda/
tensor_kernels.cu:33-58 — universal↔NHD/HND layout permutes for
cross-engine adoption).

The transfer/offload path (disagg P→D export, G2 offload, host import)
moves SETS of non-contiguous pages between the paged pool and dense
staging buffers. The jnp path (`pool[idx]` / scatter `.at[idx].set`)
materializes XLA gather/scatter HLOs; these kernels instead stream one
page per grid step with the page list scalar-prefetched — each step is
a single contiguous [PS, Hk, D] DMA, and the permuted variant fuses the
token-major → head-major transpose into the same pass (what the
reference does with a dedicated permute kernel).

All kernels run in interpret mode on CPU for CI; compiled mode is
exercised on hardware. Integration: model_runner's export/import keeps
the jnp path by default and switches here under DYN_KV_COPY_KERNEL=1
(flip after hardware A/B, same policy as attn_impl).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.parallel.mesh import AXIS_MODEL, kv_pool_specs


def _copy_kernel(idx_ref, src_ref, dst_ref):
    dst_ref[...] = src_ref[...]


def _permute_kernel(idx_ref, src_ref, dst_ref):
    # token-major [..., PS, Hk, D] page → head-major [..., Hk, PS, D]
    # (fused into the copy; the reference runs a standalone permute
    # kernel for this)
    dst_ref[...] = jnp.swapaxes(src_ref[...], -3, -2)


@functools.partial(jax.jit, static_argnames=("head_major", "interpret"))
def gather_pages(
    pool: jax.Array,  # [NP, PS, Hk, D] one layer OR [L, NP, PS, Hk, D]
    idx: jax.Array,  # [n] int32 page ids
    *,
    head_major: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Copy pages `idx` out of the pool into a dense buffer:
    [(L,) n, PS, Hk, D] (token-major) or [(L,) n, Hk, PS, D]
    (head_major=True — the cross-layout adoption format). Stacked pools
    add a leading layer grid dim (same page list every layer)."""
    stacked = pool.ndim == 5
    if stacked:
        L, NP, PS, Hk, D = pool.shape
    else:
        NP, PS, Hk, D = pool.shape
        L = 1
        pool = pool[None]
    n = idx.shape[0]
    page = (Hk, PS, D) if head_major else (PS, Hk, D)
    kernel = _permute_kernel if head_major else _copy_kernel
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # idx
        grid=(L, n),
        in_specs=[
            pl.BlockSpec((None, None, PS, Hk, D),
                         lambda l, i, idx: (l, idx[i], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None) + page,
                               lambda l, i, idx: (l, i, 0, 0, 0)),
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((L, n) + page, pool.dtype),
        interpret=interpret,
    )(idx, pool)
    return out if stacked else out[0]


def gather_pages_sharded(
    pool: jax.Array,  # [L, NP, PS, Hk, D], kv-heads sharded over `axis`
    idx: jax.Array,  # [n] int32 page ids, replicated
    mesh,
    axis: str = AXIS_MODEL,
    *,
    head_major: bool = False,
    interpret: bool = False,
) -> jax.Array:
    """Tensor-parallel wrapper (same pattern as
    decode_paged_attention_sharded): page copies are independent per
    kv-head, and the pool shards kv-heads over the model axis
    (ShardingPolicy), so each shard streams its local head slice of every
    page — zero collectives. Output keeps the pool's head sharding."""
    import functools

    from jax.sharding import PartitionSpec as P

    pool_spec = kv_pool_specs(axis)
    out_spec = (P(None, None, axis, None, None) if head_major
                else pool_spec)
    fn = jax.shard_map(
        functools.partial(
            gather_pages, head_major=head_major, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(pool_spec, P(None)),
        out_specs=out_spec,
        check_vma=False,
    )
    return fn(pool, idx)


def scatter_pages_sharded(
    pool: jax.Array,  # [L, NP, PS, Hk, D], kv-heads sharded over `axis`
    idx: jax.Array,  # [n] int32 target page ids, replicated
    pages: jax.Array,  # [L, n, PS, Hk, D] dense pages (head-sharded or
    #   replicated — GSPMD reshards to match)
    mesh,
    axis: str = AXIS_MODEL,
    *,
    interpret: bool = False,
) -> jax.Array:
    import functools

    from jax.sharding import PartitionSpec as P

    spec = kv_pool_specs(axis)
    fn = jax.shard_map(
        functools.partial(scatter_pages, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, P(None), spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(pool, idx, pages)


def _scatter_kernel(idx_ref, pool_in_ref, pages_ref, pool_ref):
    del pool_in_ref  # aliased through to the output; only written blocks move
    pool_ref[...] = pages_ref[...]


def _scatter_layers_kernel(idx_ref, off_ref, pool_in_ref, pages_ref, pool_ref):
    del idx_ref, off_ref, pool_in_ref  # consumed by the index maps
    pool_ref[...] = pages_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def scatter_pages_layers(
    pool: jax.Array,  # [L, NP, PS, Hk, D] (donated: updated in place)
    idx: jax.Array,  # [n] int32 target page ids (unique)
    pages: jax.Array,  # [Lg, n, PS, Hk, D] one layer GROUP of pages
    layer_off: jax.Array,  # [1] int32 first pool layer the group lands in
    *,
    interpret: bool = False,
) -> jax.Array:
    """Layer-streamed import half: write a contiguous layer-group slab
    into pool layers [layer_off, layer_off+Lg) at page slots `idx`. The
    streamed onboard (FlowKV-style) calls this once per group so the
    shallow layers are device-resident — and prefill can start — while
    deeper groups are still crossing host→HBM. Same donation/aliasing
    contract as scatter_pages; both prefetched scalars (page list, layer
    offset) are consumed by the output index map."""
    L, NP, PS, Hk, D = pool.shape
    Lg = pages.shape[0]
    n = idx.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # idx, layer_off
        grid=(Lg, n),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # pool: aliased, unread
            pl.BlockSpec((None, None, PS, Hk, D),
                         lambda l, i, idx, off: (l, i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, PS, Hk, D),
                               lambda l, i, idx, off: (off[0] + l, idx[i], 0, 0, 0)),
    )
    return pl.pallas_call(
        _scatter_layers_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        input_output_aliases={2: 0},  # pool (after idx, layer_off) → out
        interpret=interpret,
    )(idx, layer_off, pool, pages)


@functools.partial(jax.jit, static_argnames=("interpret",), donate_argnums=(0,))
def scatter_pages(
    pool: jax.Array,  # [(L,) NP, PS, Hk, D] (donated: updated in place)
    idx: jax.Array,  # [n] int32 target page ids (unique)
    pages: jax.Array,  # [(L,) n, PS, Hk, D] token-major pages
    *,
    interpret: bool = False,
) -> jax.Array:
    """Write dense pages into pool slots `idx` (the import half of a
    transfer). The pool buffer is donated and aliased to the output, so
    pages the grid never touches stay in place without a copy."""
    stacked = pool.ndim == 5
    if stacked:
        L, NP, PS, Hk, D = pool.shape
    else:
        NP, PS, Hk, D = pool.shape
        L = 1
        pool = pool[None]
        pages = pages[None]
    n = idx.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(L, n),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # pool: aliased, unread
            pl.BlockSpec((None, None, PS, Hk, D),
                         lambda l, i, idx: (l, i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, PS, Hk, D),
                               lambda l, i, idx: (l, idx[i], 0, 0, 0)),
    )
    out = pl.pallas_call(
        _scatter_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(pool.shape, pool.dtype),
        input_output_aliases={1: 0},  # pool (after the prefetched idx) → out
        interpret=interpret,
    )(idx, pool, pages)
    return out if stacked else out[0]
