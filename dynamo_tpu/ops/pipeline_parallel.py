"""Pipeline-parallel transformer forward (GPipe microbatching over a
`pipe` mesh axis).

SURVEY §2.10: the reference delegates PP to its engines (vLLM Ray PP
workers); here it is native. TPU-first shape: layers stay STACKED
[L, ...] and shard over the pipe axis on axis 0 — each stage owns
L/S contiguous layers (params AND its slice of the paged KV pool), and
one `shard_map` runs the classic GPipe schedule: M microbatches flow
through S stages over S+M-1 ticks, activations hopping stage→stage with
a single `ppermute` per tick over ICI. Bubble ticks compute on garbage
and are neutralized by masking (positions = -1 drops their KV writes;
their outputs are never committed), so the whole schedule is one
compiled program with static shapes — no per-stage host orchestration.

Scope: the dense GQA family (no MoE/MLA/LoRA here yet). Engine
integration: ModelRunner dispatches its prefill/decode step functions
through pp_forward / pp_decode_loop when the mesh has a pipe axis
(MeshConfig(pipe=S)); TP+DP cover ≤70B on v5e (SURVEY §2.10), so PP is
for the tail beyond that — the reference delegates the same role to its
engines (components/src/dynamo/vllm/main.py:133-137).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import AXIS_PIPE, SPEC_REPLICATED, pipe_specs
from dynamo_tpu.models.llama import (
    _write_kv,
    paged_attention_jnp,
    rms_norm,
    rope,
)
from dynamo_tpu.models.quant import embed_lookup, mm, tied_logits


def _check(config: ModelConfig) -> None:
    c = config
    if (c.is_moe or c.is_mla or c.attn_bias or c.qk_norm
            or c.act != "silu" or c.post_norms or c.norm_zero_centered
            or c.embed_scale or c.attn_logit_softcap
            or c.final_logit_softcap or c.query_pre_attn_scalar
            or c.sliding_window or not c.pre_norms
            or c.embed_multiplier or c.residual_multiplier != 1.0
            or c.attn_scale or c.logits_divider != 1.0):
        raise NotImplementedError(
            "pipeline-parallel forward currently covers the plain dense "
            "GQA family (llama/mistral-style)"
        )


def pp_forward(
    config: ModelConfig,
    params,
    tokens: jax.Array,  # [B, T]
    positions: jax.Array,  # [B, T] (-1 = padding)
    k_pool: jax.Array,  # [L, NP, PS, Hk, D] sharded over `axis` on dim 0
    v_pool: jax.Array,
    page_table: jax.Array,  # [B, MP]
    kv_lens: jax.Array,  # [B]
    mesh: Mesh,
    axis: str = AXIS_PIPE,
    n_microbatches: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Returns (logits [B, T, V], k_pool, v_pool) — numerically the plain
    llama.forward, computed stage-parallel."""
    _check(config)
    c = config
    S = mesh.shape[axis]
    B, T = tokens.shape
    M = n_microbatches or min(B, S)
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    if c.n_layers % S != 0:
        raise ValueError(f"{c.n_layers} layers not divisible by {S} stages")
    mb = B // M
    hd = c.head_dim
    G = c.n_heads // c.n_kv_heads

    stage_spec = pipe_specs(axis)
    layer_spec = jax.tree.map(lambda _: stage_spec, params["layers"])
    tied = params.get("lm_head") is None

    def body(layers, embed, norm_f, *rest):
        kp, vp, tok, pos, pt, kvl = rest
        sid = lax.axis_index(axis)

        def run_layers(h, pos_mb, pt_mb, kvl_mb, kp, vp):
            def layer(carry, xs):
                h, kp, vp = carry
                lp, l_idx = xs
                x = rms_norm(h, lp["attn_norm"], c.norm_eps)
                q = mm(x, lp["wq"]).reshape(mb, T, c.n_heads, hd)
                k = mm(x, lp["wk"]).reshape(mb, T, c.n_kv_heads, hd)
                v = mm(x, lp["wv"]).reshape(mb, T, c.n_kv_heads, hd)
                safe_pos = jnp.maximum(pos_mb, 0)
                q = rope(q, safe_pos, c.rope_theta, config=c)
                k = rope(k, safe_pos, c.rope_theta, config=c)
                kp = _write_kv(kp, l_idx, k, pt_mb, pos_mb)
                vp = _write_kv(vp, l_idx, v, pt_mb, pos_mb)
                qg = q.reshape(mb, T, c.n_kv_heads, G, hd)
                kp_l = jax.tree.map(lambda a: a[l_idx], kp)  # dict-safe
                vp_l = jax.tree.map(lambda a: a[l_idx], vp)
                attn = paged_attention_jnp(
                    qg, kp_l, vp_l, pt_mb, safe_pos, kvl_mb
                ).reshape(mb, T, c.n_heads * hd)
                h = h + mm(attn, lp["wo"])
                x = rms_norm(h, lp["mlp_norm"], c.norm_eps)
                gate = jax.nn.silu(mm(x, lp["w_gate"]))
                h = h + mm(gate * mm(x, lp["w_up"]), lp["w_down"])
                return (h, kp, vp), None

            L_local = jax.tree.leaves(layers)[0].shape[0]
            (h, kp, vp), _ = lax.scan(
                layer, (h, kp, vp),
                (layers, jnp.arange(L_local, dtype=jnp.int32)),
            )
            return h, kp, vp

        # committed FINAL HIDDEN states, not logits: psum'ing [B, T, dim]
        # and projecting to the vocab ONCE outside the shard_map is
        # ~V/dim cheaper in both lm_head matmuls and ICI all-reduce bytes
        out = jnp.zeros((B, T, c.dim), jnp.float32)
        h = jnp.zeros((mb, T, c.dim), embed.dtype if not isinstance(embed, dict)
                      else jnp.bfloat16)
        for t in range(M + S - 1):  # static schedule, unrolled
            mb_idx = t - sid  # which microbatch this stage sees this tick
            valid = (mb_idx >= 0) & (mb_idx < M)
            safe = jnp.clip(mb_idx, 0, M - 1)
            tok_mb = lax.dynamic_slice(tok, (safe * mb, 0), (mb, T))
            pos_mb = lax.dynamic_slice(pos, (safe * mb, 0), (mb, T))
            pos_mb = jnp.where(valid, pos_mb, -1)  # bubbles write nothing
            pt_mb = lax.dynamic_slice(pt, (safe * mb, 0), (mb, pt.shape[1]))
            kvl_mb = jnp.where(
                valid, lax.dynamic_slice(kvl, (safe * mb,), (mb,)), 0
            )
            x0 = embed_lookup(embed, tok_mb)
            h_in = jnp.where(sid == 0, x0.astype(h.dtype), h)
            h_out, kp, vp = run_layers(h_in, pos_mb, pt_mb, kvl_mb, kp, vp)
            # last stage commits its (valid) microbatch's hidden states
            commit = valid & (sid == S - 1)
            cur = lax.dynamic_slice(out, (safe * mb, 0, 0), (mb, T, c.dim))
            out = lax.dynamic_update_slice(
                out,
                jnp.where(commit, h_out.astype(jnp.float32), cur),
                (safe * mb, 0, 0),
            )
            # activations hop to the next stage (ring; the wrap-around
            # into stage 0 is overwritten by fresh input)
            h = lax.ppermute(h_out, axis, [(i, (i + 1) % S) for i in range(S)])
        # every rank holds only its committed slots; sum replicates the
        # full hidden states (non-last stages contributed zeros)
        return lax.psum(out, axis), kp, vp

    # embed/norm_f ride replicated BY DESIGN: every stage embeds its own
    # microbatch locally (a stage-0-only embed would serialize the ramp)
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(layer_spec, SPEC_REPLICATED, SPEC_REPLICATED,
                  stage_spec, stage_spec, SPEC_REPLICATED, SPEC_REPLICATED,
                  SPEC_REPLICATED, SPEC_REPLICATED),
        out_specs=(SPEC_REPLICATED, stage_spec, stage_spec),
        check_vma=False,
    )
    hidden, kp, vp = fn(
        params["layers"], params["embed"], params["norm_f"],
        k_pool, v_pool, tokens, positions, page_table, kv_lens,
    )
    # final norm + vocab projection once, on the replicated result
    hf = rms_norm(hidden.astype(jnp.bfloat16), params["norm_f"], c.norm_eps)
    logits = (
        tied_logits(hf, params["embed"]) if tied
        else mm(hf, params["lm_head"])
    ).astype(jnp.float32)
    return logits, kp, vp


def pp_decode_loop(
    config: ModelConfig,
    mesh: Mesh,
    axis: str,
    n_steps: int,
    params,
    tokens0: jax.Array,  # [B] current token per seq
    packed: jax.Array,  # int32 [B + B*MP + 1]: positions | page_table | step
    mask,  # None or bool [B, V] guided sampling mask (n_steps=1 dispatches)
    k_pool: jax.Array,
    v_pool: jax.Array,
    sampling,
):
    """Fused multi-step decode through the GPipe schedule: each scan step
    runs one pipelined forward over the whole batch (B microbatched over
    the stages), samples on the replicated logits, and feeds the token
    back — the pipeline-parallel twin of model_runner._decode_loop, same
    packed-ints dispatch contract. Logprobs/penalties/LoRA are not wired
    on the PP path yet (ModelRunner rejects them at construction /
    dispatch). Returns (toks [B, n_steps], last [B], k_pool, v_pool)."""
    from dynamo_tpu.engine.sampling import sample

    B = sampling.temperature.shape[0]
    MP = (packed.shape[0] - 1 - B) // B
    positions0 = packed[:B]
    page_table = packed[B : B + B * MP].reshape(B, MP)
    step0 = packed[-1]

    def body(carry, t):
        tok, kp, vp = carry
        pos = jnp.where(positions0 < 0, -1, positions0 + t)
        kvl = jnp.where(positions0 < 0, 0, positions0 + t + 1)
        logits, kp, vp = pp_forward(
            config, params, tok[:, None], pos[:, None], kp, vp,
            page_table, kvl, mesh, axis,
        )
        s = sample(logits[:, 0, :], sampling, step0 + t, mask=mask)
        return (s, kp, vp), s

    (last, k_pool, v_pool), toks = lax.scan(
        body, (tokens0, k_pool, v_pool), jnp.arange(n_steps, dtype=jnp.int32)
    )
    return toks.T, last, k_pool, v_pool  # [B, n_steps], [B]
