"""Expert-parallel MoE with all-to-all token dispatch over the `expert`
mesh axis.

The wide-EP building block (reference deploys DeepSeek-class wide-EP via
engine backends + recipes, SURVEY.md §2.10; here it is native): tokens are
sharded across expert ranks; each rank routes its local tokens, packs them
into per-destination capacity buffers, exchanges them with one
`all_to_all` over ICI, runs its resident experts, and returns results with
a second all_to_all, combining with router weights.

Capacity model: each (src rank → dst rank) lane carries up to C tokens,
C = ceil(T_local * k / n_ranks * capacity_factor). Overflow tokens are
dropped (contribute zero), standard Switch/GShard semantics — with
capacity_factor ≥ n_experts/k the dispatch is lossless and matches the
dense reference exactly.

Engine integration note: models/llama.py currently computes MoE densely
with expert-sharded weights (GSPMD all-gather EP); this op replaces that
path once engine activations are token-sharded over `expert` (round 2).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dynamo_tpu.parallel.mesh import AXIS_EXPERT, SPEC_REPLICATED, moe_specs


def router_topk(logits: jax.Array, k: int, scoring: str = "softmax",
                norm_topk: bool = True, bias=None, routed_scale: float = 1.0,
                n_groups: int = 0, topk_groups: int = 0):
    """Top-k routing weights from f32 router logits. softmax = Mixtral/
    Qwen (softmax over the selected logits); sigmoid = DeepSeek-V3
    (independent gates, renormalized over the top-k). norm_topk=False
    (HF norm_topk_prob: false, Qwen2-MoE) keeps softmax-over-ALL-experts
    probabilities without renormalizing — the routed sum is deliberately
    < 1. One helper shared by every MoE path so dense, EP, and reference
    all route identically.

    `bias` [n_experts] is DeepSeek-V3's e_score_correction_bias
    (aux-loss-free load balancing): it shifts SELECTION only — the mixing
    weights come from the unbiased gates. `routed_scale` multiplies the
    final weights (HF routed_scaling_factor). `n_groups`/`topk_groups`
    enable V3's group-limited selection: keep the topk_groups expert
    groups whose top-2 member scores sum highest, ban the rest."""
    if scoring == "sigmoid":
        gates = jax.nn.sigmoid(logits)
        sel_scores = (
            gates + bias.astype(gates.dtype) if bias is not None else gates
        )
        if n_groups > 1 and 0 < topk_groups < n_groups:
            *lead, n_exp = sel_scores.shape
            per = n_exp // n_groups
            grouped = sel_scores.reshape(*lead, n_groups, per)
            top2, _ = lax.top_k(grouped, min(2, per))
            group_score = top2.sum(-1)  # [..., n_groups]
            _, keep_g = lax.top_k(group_score, topk_groups)
            keep = jnp.zeros(group_score.shape, bool)
            keep = jnp.put_along_axis(keep, keep_g, True, axis=-1,
                                      inplace=False)
            mask = jnp.repeat(keep, per, axis=-1)
            sel_scores = jnp.where(mask, sel_scores, -jnp.inf)
        if bias is not None or n_groups > 1:
            _, sel = lax.top_k(sel_scores, k)
            weights = jnp.take_along_axis(gates, sel, axis=-1)
        else:
            weights, sel = lax.top_k(gates, k)
        if norm_topk:
            weights = weights / jnp.maximum(
                jnp.sum(weights, axis=-1, keepdims=True), 1e-9
            )
    elif not norm_topk:
        probs = jax.nn.softmax(logits, axis=-1)
        weights, sel = lax.top_k(probs, k)
    else:
        weights, sel = lax.top_k(logits, k)
        weights = jax.nn.softmax(weights, axis=-1)
    if routed_scale != 1.0:
        weights = weights * routed_scale
    return weights, sel


def _local_moe(x, w_router, we_gate, we_up, we_down, k: int, capacity: int, axis: str,
               model_axis=None, scoring: str = "softmax", norm_topk: bool = True,
               router_bias=None, routed_scale: float = 1.0,
               n_groups: int = 0, topk_groups: int = 0):
    """Per-shard body. x: [T, E] local tokens; we_*: [n_local, ...] resident
    experts; router weights replicated. Returns [T, E]."""
    n_ranks = lax.psum(1, axis)
    rank = lax.axis_index(axis)
    T, E = x.shape
    n_local = we_gate.shape[0]
    n_experts = n_local * n_ranks

    logits = (x @ w_router).astype(jnp.float32)  # [T, n_experts]
    weights, sel = router_topk(logits, k, scoring, norm_topk,
                               bias=router_bias, routed_scale=routed_scale,
                               n_groups=n_groups, topk_groups=topk_groups)
    weights = weights.astype(x.dtype)

    # flatten (token, choice) pairs and bucket by destination rank
    flat_sel = sel.reshape(-1)  # [T*k] expert ids
    flat_tok = jnp.repeat(jnp.arange(T), k)  # [T*k]
    flat_w = weights.reshape(-1)
    dest = flat_sel // n_local  # destination rank per pair

    # position of each pair within its (dest rank, capacity) lane: running
    # count of earlier pairs with the same destination
    onehot = jax.nn.one_hot(dest, n_ranks, dtype=jnp.int32)  # [T*k, R]
    pos_in_dest = ((jnp.cumsum(onehot, axis=0) - onehot) * onehot).sum(-1)
    keep = pos_in_dest < capacity

    # dispatch buffers [R, C, E] + bookkeeping [R, C]
    disp_x = jnp.zeros((n_ranks, capacity, E), x.dtype)
    disp_expert = jnp.zeros((n_ranks, capacity), jnp.int32)
    slot_r = jnp.where(keep, dest, n_ranks)  # OOB drop
    slot_c = jnp.where(keep, pos_in_dest, capacity)
    disp_x = disp_x.at[slot_r, slot_c].set(x[flat_tok], mode="drop")
    disp_expert = disp_expert.at[slot_r, slot_c].set(flat_sel % n_local, mode="drop")

    # exchange: [R, C, E] → every rank receives its inbound tokens
    recv_x = lax.all_to_all(disp_x, axis, split_axis=0, concat_axis=0, tiled=False)
    recv_expert = lax.all_to_all(disp_expert, axis, split_axis=0, concat_axis=0, tiled=False)
    # recv_x: [R, C, E] — row r = tokens sent by rank r to us

    rx = recv_x.reshape(n_ranks * capacity, E)
    re_ = recv_expert.reshape(n_ranks * capacity)

    # run resident experts on every received token, select by expert id.
    # With a model axis, each expert's F dim is TP-sharded: the down-proj
    # produces partial sums that one psum over `model` completes (the
    # megatron row-parallel pattern inside the EP shard)
    def expert_fn(wg, wu, wd):
        return (jax.nn.silu(rx @ wg) * (rx @ wu)) @ wd  # [RC, E]

    all_out = jax.vmap(expert_fn)(we_gate, we_up, we_down)  # [n_local, RC, E]
    if model_axis is not None:
        all_out = lax.psum(all_out, model_axis)
    out_tok = jnp.take_along_axis(
        all_out.transpose(1, 0, 2), re_[:, None, None], axis=1
    )[:, 0]  # [RC, E]

    # send results back
    back = lax.all_to_all(
        out_tok.reshape(n_ranks, capacity, E), axis, split_axis=0, concat_axis=0
    )  # [R, C, E] — row r = results for pairs we sent to rank r

    # combine: scatter-add weighted results back to source tokens
    y = jnp.zeros((T, E), jnp.float32)
    gathered = back[slot_r.clip(0, n_ranks - 1), slot_c.clip(0, capacity - 1)]
    gathered = jnp.where(keep[:, None], gathered.astype(jnp.float32), 0.0)
    y = y.at[flat_tok].add(gathered * flat_w[:, None].astype(jnp.float32))
    return y.astype(x.dtype)


def moe_ep(
    x: jax.Array,  # [T, E] tokens, sharded over `axis` on dim 0
    w_router: jax.Array,  # [E, n_experts] replicated
    we_gate: jax.Array,  # [n_experts, E, F] sharded over `axis` on dim 0
    we_up: jax.Array,
    we_down: jax.Array,  # [n_experts, F, E]
    mesh: Mesh,
    n_experts_active: int,
    capacity_factor: float = 2.0,
    axis: str = AXIS_EXPERT,
    model_axis=None,  # set to "model" for EP x TP expert weights
    scoring: str = "softmax",
    norm_topk: bool = True,
    router_bias=None,  # [n_experts] selection bias (DeepSeek-V3)
    routed_scale: float = 1.0,
    n_groups: int = 0,  # group-limited selection (DeepSeek-V3)
    topk_groups: int = 0,
) -> jax.Array:
    """Token-dispatch EP MoE. Returns [T, E] with x's sharding."""
    n_ranks = mesh.shape[axis]
    T_local = x.shape[0] // n_ranks
    n_experts = we_gate.shape[0]
    capacity = int(np.ceil(T_local * n_experts_active / n_ranks * capacity_factor))

    ma = model_axis
    # router_bias rides as an explicit replicated input: a traced array
    # captured in the shard_map closure would be rejected under jit
    has_bias = router_bias is not None

    def body(x, w_router, we_gate, we_up, we_down, *rest):
        return _local_moe(
            x, w_router, we_gate, we_up, we_down, k=n_experts_active,
            capacity=capacity, axis=axis, model_axis=ma, scoring=scoring,
            norm_topk=norm_topk, router_bias=rest[0] if has_bias else None,
            routed_scale=routed_scale, n_groups=n_groups,
            topk_groups=topk_groups,
        )

    tok_spec, gate_up_spec, down_spec = moe_specs(axis, ma)
    in_specs = [
        tok_spec,
        SPEC_REPLICATED,  # w_router [E, n_exp]
        gate_up_spec,  # [n_exp, E, F]: F TP-sharded when ma set
        gate_up_spec,
        down_spec,  # [n_exp, F, E]
    ]
    args = [x, w_router, we_gate, we_up, we_down]
    if has_bias:
        in_specs.append(SPEC_REPLICATED)
        args.append(router_bias)
    fn = jax.shard_map(
        body, mesh=mesh, in_specs=tuple(in_specs), out_specs=tok_spec
    )
    return fn(*args)


def moe_dense_reference(x, w_router, we_gate, we_up, we_down, k: int,
                        scoring: str = "softmax", norm_topk: bool = True):
    """Unsharded dense top-k MoE (same math as models/llama.py _moe_block)."""
    logits = (x @ w_router).astype(jnp.float32)
    weights, sel = router_topk(logits, k, scoring, norm_topk)
    weights = weights.astype(x.dtype)

    def expert_fn(wg, wu, wd):
        return (jax.nn.silu(x @ wg) * (x @ wu)) @ wd

    all_out = jax.vmap(expert_fn)(we_gate, we_up, we_down)  # [n_exp, T, E]
    sel_out = jnp.take_along_axis(
        all_out.transpose(1, 0, 2), sel[..., None], axis=1
    )  # [T, k, E]
    return jnp.sum(sel_out * weights[..., None], axis=1)
