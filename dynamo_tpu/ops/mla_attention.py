"""Pallas TPU decode attention for MLA (DeepSeek latent-cache) models.

The MLA decode hot op in absorbed form: each sequence's single query
token carries per-head absorbed vectors q = [q_absorbed ; q_rope]
([H, d_c + d_rh]) and attends over the sequence's paged LATENT cache
([NP, PS, d_c + d_rh] — one vector per token, no heads). Scores are
q · latent; values are the latent's first d_c columns — so ONE page DMA
feeds both the K and the V side of the computation (the GQA kernel
needs two pools; MLA's cache compression pays again here in bandwidth).

Same streaming structure as ops/paged_attention.py: grid (B, MP), page
index innermost, scalar-prefetched page table driving BlockSpec index
maps with past-the-end pages clamped (repeat block index → Pallas elides
the copy), online-softmax state in VMEM scratch.

Tiling note: the latent dim for DeepSeek-V3 is 576 = 4.5 x 128 lanes;
Pallas pads the last tile. Splitting the score matmul into an aligned
512-wide latent part and a 64-wide rope part would avoid the padding —
measured on hardware before bothering.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.parallel.mesh import AXIS_MODEL, SPEC_MLA_LATENT_POOL

NEG_INF = -1e30


def _mla_kernel_body(
    page_table_ref,  # [B, MP] int32 (SMEM, scalar-prefetched)
    kv_lens_ref,  # [B] int32 (SMEM)
    q_ref,  # [H, Dl] absorbed+rope query for seq b
    lat_ref,  # [PS, Dl] one latent page (single contiguous DMA)
    ls_ref,  # [PS] f32 per-token latent scales (int8 pool) or None
    o_ref,  # [H, dc]
    m_ref,  # [H, 1] f32 running max
    l_ref,  # [H, 1] f32 running denom
    acc_ref,  # [H, dc] f32 running numerator
    *,
    page_size: int,
    scale: float,
    dc: int,
):
    b = pl.program_id(0)
    i = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    kv_len = kv_lens_ref[b]
    n_valid = jnp.clip(kv_len - i * page_size, 0, page_size)

    @pl.when(n_valid > 0)
    def _compute():
        q = q_ref[...].astype(jnp.float32)  # [H, Dl]
        lat = lat_ref[...].astype(jnp.float32)  # [PS, Dl]
        s = lax.dot_general(
            q, lat, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [H, PS]
        if ls_ref is not None:
            # int8 latent: fold the per-token scale into the scores —
            # one [1, PS] multiply instead of dequantizing over Dl
            s = s * ls_ref[...][None, :]
        valid = lax.broadcasted_iota(jnp.int32, s.shape, 1) < n_valid
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[...]  # [H, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)  # [H, PS]
        alpha = jnp.exp(m_prev - m_new)
        l_add = jnp.sum(p, axis=1, keepdims=True)  # raw-probability denom
        if ls_ref is not None:
            # same scale dequantizes the VALUE side (values are the
            # latent's first d_c columns of the same vector)
            p = p * ls_ref[...][None, :]
        pv = lax.dot_general(
            p, lat[:, :dc], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [H, dc]
        acc_ref[...] = acc_ref[...] * alpha + pv
        l_ref[...] = l_ref[...] * alpha + l_add
        m_ref[...] = m_new

    @pl.when(i == n_pages - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _mla_kernel(pt, kl, q, lat, o, m, l, acc, **kw):
    _mla_kernel_body(pt, kl, q, lat, None, o, m, l, acc, **kw)


def _mla_kernel_int8(pt, kl, q, lat, ls, o, m, l, acc, **kw):
    _mla_kernel_body(pt, kl, q, lat, ls, o, m, l, acc, **kw)


@functools.partial(jax.jit, static_argnames=("dc", "scale", "interpret"))
def decode_mla_attention(
    q: jax.Array,  # [B, H, Dl] absorbed+rope queries
    lat_pool_l: jax.Array,  # [NP, PS, 1, Dl] one layer's latent pool
    page_table: jax.Array,  # [B, MP] int32
    kv_lens: jax.Array,  # [B] int32 (context incl. current token)
    *,
    dc: int,  # latent (value) width = kv_lora_rank
    scale: float,  # score scale ((d_nope + d_rh)^-0.5 [* yarn mscale^2])
    interpret: bool = False,
) -> jax.Array:
    """Returns the attended latents [B, H, dc] (the caller lifts them
    through W_UV). The current token's latent must already be written.
    `lat_pool_l` may be the int8 dict ({"q": [NP,PS,1,Dl] int8, "s":
    [NP,PS,1] f32}) — scales fold into scores/values per token."""
    quantized = isinstance(lat_pool_l, dict)
    lq = lat_pool_l["q"] if quantized else lat_pool_l
    B, H, Dl = q.shape
    NP, PS, _, _ = lq.shape
    MP = page_table.shape[1]
    lat = lq.reshape(NP, PS, Dl)

    def lat_index(b, i, pt, kl):
        last = jnp.maximum(kl[b] - 1, 0) // PS
        return (pt[b, jnp.minimum(i, last)], 0, 0)

    def scale_index(b, i, pt, kl):
        return lat_index(b, i, pt, kl)[:2]

    in_specs = [
        pl.BlockSpec((None, H, Dl), lambda b, i, pt, kl: (b, 0, 0)),
        pl.BlockSpec((None, PS, Dl), lat_index),
    ]
    operands = (q, lat)
    kernel = _mla_kernel
    if quantized:
        in_specs.append(pl.BlockSpec((None, PS), scale_index))
        operands = operands + (lat_pool_l["s"].reshape(NP, PS),)
        kernel = _mla_kernel_int8
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, MP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, H, dc), lambda b, i, pt, kl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, dc), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(kernel, page_size=PS, scale=scale, dc=dc),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, dc), q.dtype),
        interpret=interpret,
    )(page_table, kv_lens, *operands)


def _mla_prefill_kernel(
    page_table_ref,  # [B, MP] int32
    q_start_ref,  # [B] int32
    q_len_ref,  # [B] int32
    kv_lens_ref,  # [B] int32
    q_ref,  # [Sq, H, Dl] one query block
    lat_ref,  # [PS, Dl] one latent page
    o_ref,  # [Sq, H, dc]
    m_ref,  # [Sq*H, 1] f32
    l_ref,  # [Sq*H, 1] f32
    acc_ref,  # [Sq*H, dc] f32
    *,
    page_size: int,
    q_block: int,
    scale: float,
    dc: int,
):
    b = pl.program_id(0)
    sb = pl.program_id(1)
    i = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = q_start_ref[b]
    q_len = q_len_ref[b]
    kv_len = kv_lens_ref[b]
    blk_rows = jnp.minimum(q_len - sb * q_block, q_block)
    blk_max_pos = q_start + sb * q_block + blk_rows - 1
    page_first = i * page_size
    needed = (blk_rows > 0) & (page_first <= blk_max_pos) & (page_first < kv_len)

    @pl.when(needed)
    def _compute():
        Sq, H, Dl = q_ref.shape
        q = q_ref[...].astype(jnp.float32).reshape(Sq * H, Dl)
        lat = lat_ref[...].astype(jnp.float32)  # [PS, Dl]
        s = lax.dot_general(
            q, lat, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [Sq*H, PS]
        row = lax.broadcasted_iota(jnp.int32, s.shape, 0) // H
        col = lax.broadcasted_iota(jnp.int32, s.shape, 1)
        q_pos = q_start + sb * q_block + row
        kv_pos = page_first + col
        mask = (row < blk_rows) & (kv_pos <= q_pos) & (kv_pos < kv_len)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_add = jnp.sum(p, axis=1, keepdims=True)
        pv = lax.dot_general(
            p, lat[:, :dc], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Sq*H, dc]
        acc_ref[...] = acc_ref[...] * alpha + pv
        l_ref[...] = l_ref[...] * alpha + l_add
        m_ref[...] = m_new

    @pl.when(i == n_pages - 1)
    def _finalize():
        Sq, H, dcw = o_ref.shape
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / denom).astype(o_ref.dtype).reshape(Sq, H, dcw)


@functools.partial(jax.jit, static_argnames=("dc", "scale", "q_block", "interpret"))
def prefill_mla_attention(
    q: jax.Array,  # [B, S, H, Dl] absorbed+rope queries (chunk)
    lat_pool_l: jax.Array,  # [NP, PS, 1, Dl]
    page_table: jax.Array,  # [B, MP]
    q_start: jax.Array,  # [B] absolute position of query token 0
    q_len: jax.Array,  # [B] valid query tokens
    kv_lens: jax.Array,  # [B] context incl. this chunk
    *,
    dc: int,
    scale: float,
    q_block: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Flash-style MLA prefill over latent pages (one DMA per page feeds
    scores AND values; causally-dead/past-kv pages are clamped in the
    index_map so Pallas elides their copies). Returns the attended
    latents [B, S, H, dc]; padding rows return 0. Same positions
    contract as ops/flash_prefill.py."""
    B, S, H, Dl = q.shape
    NP, PS, _, _ = lat_pool_l.shape
    MP = page_table.shape[1]
    lat = lat_pool_l.reshape(NP, PS, Dl)
    # VMEM budget: the f32 acc scratch is q_block*H x dc — at flagship MLA
    # dims (H=128, dc=512) a 128-row block would need ~34MiB of scratch
    # alone. Cap the block so acc stays ~<=4MiB; tiny test dims keep the
    # requested block.
    q_block = min(q_block, max(8, (4 << 20) // max(H * dc * 4, 1)))
    q_block = min(q_block, S)
    while S % q_block:
        q_block -= 1
    n_sblk = S // q_block

    def lat_index(b, sb, i, pt, qs, ql, kl):
        rows = jnp.minimum(ql[b] - sb * q_block, q_block)
        blk_max_pos = qs[b] + sb * q_block + jnp.maximum(rows, 1) - 1
        last = jnp.minimum(blk_max_pos, jnp.maximum(kl[b] - 1, 0)) // PS
        last = jnp.clip(last, 0, MP - 1)
        return (pt[b, jnp.minimum(i, last)], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, n_sblk, MP),
        in_specs=[
            pl.BlockSpec((None, q_block, H, Dl),
                         lambda b, sb, i, pt, qs, ql, kl: (b, sb, 0, 0)),
            pl.BlockSpec((None, PS, Dl), lat_index),
        ],
        out_specs=pl.BlockSpec(
            (None, q_block, H, dc),
            lambda b, sb, i, pt, qs, ql, kl: (b, sb, 0, 0),
        ),
        scratch_shapes=[
            pltpu.VMEM((q_block * H, 1), jnp.float32),
            pltpu.VMEM((q_block * H, 1), jnp.float32),
            pltpu.VMEM((q_block * H, dc), jnp.float32),
        ],
    )
    return pl.pallas_call(
        functools.partial(
            _mla_prefill_kernel, page_size=PS, q_block=q_block,
            scale=scale, dc=dc,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, S, H, dc), q.dtype),
        interpret=interpret,
    )(page_table, q_start, q_len, kv_lens, q, lat)


def prefill_mla_attention_sharded(
    q: jax.Array,  # [B, S, H, Dl] heads sharded over `axis_name`
    lat_pool_l: jax.Array,  # [NP, PS, 1, Dl] REPLICATED (Hk=1)
    page_table: jax.Array,
    q_start: jax.Array,
    q_len: jax.Array,
    kv_lens: jax.Array,
    mesh,
    axis_name: str = AXIS_MODEL,
    *,
    dc: int,
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """Tensor-parallel wrapper for the flash MLA prefill: per-head
    independence means each shard runs the kernel on its local heads
    against the replicated latent pool — zero collectives (the block
    all-reduce happens in the out-projection as usual; the
    decode_mla_attention_sharded pattern applied to the chunk path, so
    TP meshes no longer fall back to the jnp gather)."""
    from jax.sharding import PartitionSpec as P

    fn = jax.shard_map(
        functools.partial(
            prefill_mla_attention, dc=dc, scale=scale, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(P(None, None, axis_name, None), SPEC_MLA_LATENT_POOL,
                  P(None, None), P(None), P(None), P(None)),
        out_specs=P(None, None, axis_name, None),
        check_vma=False,
    )
    return fn(q, lat_pool_l, page_table, q_start, q_len, kv_lens)


def decode_mla_attention_sharded(
    q: jax.Array,  # [B, H, Dl] heads sharded over `axis_name`
    lat_pool_l: jax.Array,  # [NP, PS, 1, Dl] REPLICATED (Hk=1 — no head
    #   axis to shard; the latent pool is small by design)
    page_table: jax.Array,
    kv_lens: jax.Array,
    mesh,
    axis_name: str = AXIS_MODEL,
    *,
    dc: int,
    scale: float,
    interpret: bool = False,
) -> jax.Array:
    """Tensor-parallel wrapper: per-head independence means each shard
    runs the kernel on its local heads against the replicated latent pool
    — zero collectives (the block all-reduce happens in the
    out-projection as usual)."""
    from jax.sharding import PartitionSpec as P

    fn = jax.shard_map(
        functools.partial(
            decode_mla_attention, dc=dc, scale=scale, interpret=interpret
        ),
        mesh=mesh,
        in_specs=(P(None, axis_name, None), SPEC_MLA_LATENT_POOL,
                  P(None, None), P(None)),
        out_specs=P(None, axis_name, None),
        check_vma=False,
    )
    return fn(q, lat_pool_l, page_table, kv_lens)
