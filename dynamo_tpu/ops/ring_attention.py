"""Ring attention: blockwise causal attention with K/V rotation over the
`seq` mesh axis (sequence/context parallelism).

The reference framework has no in-tree sequence parallelism (SURVEY.md
§2.10: absent from the core; long context is handled by chunked prefill +
disagg + KVBM). The TPU build makes SP native: the sequence is sharded
[B, S/n, ...] across the ring; each step computes the local Q block against
the resident K/V block with a flash-style online softmax, then rotates K/V
to the next ring neighbor with ppermute — n steps see the full context
while ICI carries exactly one K/V shard per hop (the Ring Attention
construction; Pallas fusion of the per-block kernel is a later
optimization — XLA already overlaps the ppermute with compute).

Causality is handled by absolute positions: block (i ← j) contributes only
where q_pos >= kv_pos, so out-of-order ring arrival needs no special-casing.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.parallel.mesh import AXIS_SEQ, ring_specs

NEG_INF = -1e30


def _block_attn_update(q, k, v, q_pos, kv_pos, m, l, acc, scale):
    """One blockwise online-softmax update.
    q [B,s,Hk,G,D]; k/v [B,t,Hk,D]; q_pos [B,s]; kv_pos [B,t];
    m,l [B,s,Hk,G,1]; acc [B,s,Hk,G,D] (all fp32 accumulators)."""
    s = jnp.einsum("bskgd,btkd->bskgt", q, k).astype(jnp.float32) * scale
    mask = (q_pos[:, :, None] >= kv_pos[:, None, :])[:, :, None, None, :]
    s = jnp.where(mask, s, NEG_INF)

    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
    alpha = jnp.exp(m - m_new)
    acc = acc * alpha + jnp.einsum("bskgt,btkd->bskgd", p.astype(v.dtype), v).astype(jnp.float32)
    l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    return m_new, l, acc


def _ring_attention_sharded(q, k, v, q_pos, kv_pos, axis_name: str, scale: float):
    """Runs inside shard_map: local shards, full-context result. Returns
    (out, m, l) — normalized output plus online-softmax stats so callers can
    merge with attention over other context (e.g. prior paged KV)."""
    n = lax.psum(1, axis_name)
    B, s_len, Hk, G, D = q.shape

    # mark accumulators as device-varying along the ring axis (vma typing)
    def _varying(x):
        return lax.pcast(x, (axis_name,), to="varying")

    m = _varying(jnp.full((B, s_len, Hk, G, 1), NEG_INF, jnp.float32))
    l = _varying(jnp.zeros((B, s_len, Hk, G, 1), jnp.float32))
    acc = _varying(jnp.zeros((B, s_len, Hk, G, D), jnp.float32))

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, _):
        k_cur, v_cur, kv_pos_cur, m, l, acc = carry
        m, l, acc = _block_attn_update(q, k_cur, v_cur, q_pos, kv_pos_cur, m, l, acc, scale)
        k_cur = lax.ppermute(k_cur, axis_name, perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm)
        kv_pos_cur = lax.ppermute(kv_pos_cur, axis_name, perm)
        return (k_cur, v_cur, kv_pos_cur, m, l, acc), None

    (k, v, kv_pos, m, l, acc), _ = lax.scan(step, (k, v, kv_pos, m, l, acc), None, length=n)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype), m, l


def ring_attention(
    q: jax.Array,  # [B, S, Hk, G, D] sequence-sharded over `axis_name`
    k: jax.Array,  # [B, S, Hk, D]
    v: jax.Array,
    q_positions: jax.Array,  # [B, S] absolute positions
    kv_positions: jax.Array,  # [B, S] (use a huge sentinel for padding slots
    #         so no query position reaches them)
    mesh: Mesh,
    axis_name: str = AXIS_SEQ,
    return_stats: bool = False,
):
    """Full causal attention over a sequence sharded across `axis_name`.
    Returns [B, S, Hk, G, D] with the same sharding as q; with
    `return_stats`, also the per-row online-softmax (m, l) [B, S, Hk, G, 1]
    fp32 stats for merging with attention over disjoint context."""
    D = q.shape[-1]
    scale = D**-0.5
    spec_q, spec_kv, seq = ring_specs(axis_name)

    fn = jax.shard_map(
        partial(_ring_attention_sharded, axis_name=axis_name, scale=scale),
        mesh=mesh,
        in_specs=(spec_q, spec_kv, spec_kv, seq, seq),
        out_specs=(spec_q, spec_q, spec_q),
    )
    out, m, l = fn(q, k, v, q_positions, kv_positions)
    if return_stats:
        return out, m, l
    return out


def full_attention_reference(q, k, v, q_positions, kv_positions):
    """Unsharded reference for testing."""
    D = q.shape[-1]
    s = jnp.einsum("bskgd,btkd->bskgt", q, k).astype(jnp.float32) * (D**-0.5)
    mask = (q_positions[:, :, None] >= kv_positions[:, None, :])[:, :, None, None, :]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bskgt,btkd->bskgd", p.astype(v.dtype), v)
