"""Pallas TPU ragged paged attention: one grid for decode + packed prefills.

The mixed iteration's hot op. PR 1's token-budget scheduler packs the decode
batch plus several partial-prefill chunks into one fused dispatch, but the
device path pads them into a dense [N, S] batch: a pack of one 512-token
chunk and three 32-token chunks pays 4x512 tokens of attention+MLP, and the
runner compiles a variant per (decode, chunk, pack) bucket triple. This
kernel serves every segment — each decode sequence is a q_len=1 segment,
each prefill chunk a q_len=n segment — from ONE flat [T, Hk, G, D] query
buffer whose length T comes from a small set of token-budget buckets, so
mixed-iteration cost is proportional to real tokens and the compile key is
T alone.

Work-unit grid. The flat token axis is cut into q_block-row blocks; a block
that spans a segment boundary would mix two segments' (page table, kv_len,
positions), so the host emits one WORK UNIT per (block, segment) overlap:

    meta [5, NW] int32 rows:           (scalar-prefetched, SMEM)
      0 seg    segment row into seg_page_table / seg_kv_lens
      1 qblk   flat q block index (block of q_block tokens)
      2 rs     first valid row of this unit within the block
      3 rows   valid row count (0 = padding unit, a no-op)
      4 qpos0  absolute position of row rs

NW and the segment capacity are functions of the T bucket only
(`ragged_work_cap` / `ragged_seg_cap`), so they never add compile keys.
Grid is (NW, MP) with the page index innermost: consecutive units sharing a
block keep the q and out blocks resident (same block index -> Pallas elides
the DMA), and each unit read-modify-writes ONLY its rows of the out block
under a row mask at finalize. Units are emitted in increasing-row order so
a later unit never clobbers an earlier one's rows. K/V pages stream exactly
as in the decode kernel (ops/paged_attention.py): the index_map clamps dead
pages (causal top, kv_len, window low bound) to a repeated index so their
copies are elided, and a `needed` guard skips their compute.

Parity: GQA (G groups per kv head), sliding window (traced scalar, 0 =
global at runtime), logit softcap, and int8-KV per-(token, head) scales all
follow the exact op order of the two kernels this subsumes — scales fold
into scores BEFORE softcap, V scales fold into p AFTER the raw-probability
denominator.

The flat layout itself is the "Ragged Paged Attention" TPU kernel design
(PAPERS.md); the reference framework reaches the same shape through
vLLM's ragged query batch on GPU.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.parallel.mesh import AXIS_MODEL, attention_specs

NEG_INF = -1e30

# decode batch (<=64) + packed chunks (<=32) in one mixed iteration
RAGGED_MAX_SEGS = 96
DEFAULT_Q_BLOCK = 8


def ragged_seg_cap(t_bucket: int, max_segs: int = RAGGED_MAX_SEGS) -> int:
    """Segment-row capacity for a T bucket (+1 for the padding-tail
    segment). A function of the bucket ONLY — it must not add compile
    keys beyond |T buckets|."""
    return min(t_bucket, max_segs) + 1


def ragged_work_cap(
    t_bucket: int,
    q_block: int = DEFAULT_Q_BLOCK,
    max_segs: int = RAGGED_MAX_SEGS,
) -> int:
    """Work-unit capacity: every block yields one unit plus one extra per
    segment that starts mid-block, so blocks + segments bounds it."""
    if t_bucket % q_block:
        raise ValueError(f"t_bucket {t_bucket} not a multiple of {q_block}")
    return t_bucket // q_block + ragged_seg_cap(t_bucket, max_segs)


def build_ragged_metadata(
    q_lens: Sequence[int],  # true (unpadded) query tokens per segment
    q_starts: Sequence[int],  # absolute position of each segment's token 0
    kv_lens: Sequence[int],  # context length per segment (incl. its chunk)
    page_rows: Sequence[Sequence[int]],  # page-table row per segment
    t_bucket: int,
    *,
    q_block: int = DEFAULT_Q_BLOCK,
    max_pages: Optional[int] = None,
    max_segs: int = RAGGED_MAX_SEGS,
) -> Dict[str, np.ndarray]:
    """Host-side (numpy) metadata for one ragged dispatch.

    Segments are laid out back to back in the flat [t_bucket] token axis in
    the given order; the tail [sum(q_lens), t_bucket) is covered by a dummy
    segment with kv_len=0 (no compute, finalize writes zeros). Returns the
    kernel operands (seg_page_table, seg_kv_lens, meta) padded to the
    bucket's static caps, plus per-token arrays for the model's KV writes /
    RoPE / jnp fallback (tok_*) and the per-segment last-token gather
    (last_index). Padding tokens get tok_pos=-1 (KV write drops them) but
    tok_kv_len=1 so the jnp fallback's softmax stays finite.

    Segments are fully independent — each brings its own page-table row
    and kv_len — which is what lets speculative verify treat tree
    branches as ordinary extra segments: a branch rides the dispatch on
    its forked table (trunk pages shared by reference, divergent tail
    copied), and this metadata neither knows nor cares that two
    segments' rows alias the same physical pages.
    """
    n = len(q_lens)
    t_real = int(sum(q_lens))
    if t_real > t_bucket:
        raise ValueError(f"{t_real} tokens exceed bucket {t_bucket}")
    if n > max_segs:
        raise ValueError(f"{n} segments exceed cap {max_segs}")
    seg_cap = ragged_seg_cap(t_bucket, max_segs)
    nw = ragged_work_cap(t_bucket, q_block, max_segs)
    if max_pages is None:
        max_pages = max((len(r) for r in page_rows), default=1)

    seg_pt = np.zeros((seg_cap, max_pages), np.int32)
    seg_kvl = np.zeros((seg_cap,), np.int32)
    for s, row in enumerate(page_rows):
        seg_pt[s, : len(row)] = row
    seg_kvl[:n] = kv_lens

    # flat extents per segment, dummy tail included
    lens_all: List[int] = list(int(x) for x in q_lens)
    if t_real < t_bucket:
        lens_all.append(t_bucket - t_real)
    meta = np.zeros((5, nw), np.int32)
    w = 0
    lo = 0
    for s, ln in enumerate(lens_all):
        hi = lo + ln
        for b in range(lo // q_block, (hi - 1) // q_block + 1):
            blo = max(lo, b * q_block)
            bhi = min(hi, (b + 1) * q_block)
            qp0 = int(q_starts[s]) + (blo - lo) if s < n else 0
            meta[:, w] = (s, b, blo - b * q_block, bhi - blo, qp0)
            w += 1
        lo = hi
    # padding units: rows=0 no-ops pointing at the last real block (its
    # buffers stay resident, so the repeat elides every DMA)
    if w:
        pad_blk = meta[1, w - 1]
    else:
        pad_blk = 0
    pad_seg = min(n, seg_cap - 1)
    for j in range(w, nw):
        meta[:, j] = (pad_seg, pad_blk, 0, 0, 0)

    tok_pt = np.zeros((t_bucket, max_pages), np.int32)
    tok_kvl = np.ones((t_bucket,), np.int32)
    tok_pos = np.full((t_bucket,), -1, np.int32)
    cu = np.zeros((n + 1,), np.int32)
    off = 0
    for s in range(n):
        ln = int(q_lens[s])
        tok_pt[off : off + ln] = seg_pt[s]
        tok_kvl[off : off + ln] = kv_lens[s]
        tok_pos[off : off + ln] = int(q_starts[s]) + np.arange(ln)
        off += ln
        cu[s + 1] = off
    return {
        "seg_page_table": seg_pt,
        "seg_kv_lens": seg_kvl,
        "meta": meta,
        "tok_page_table": tok_pt,
        "tok_kv_lens": tok_kvl,
        "tok_positions": tok_pos,
        "cu_q_lens": cu,
        "last_index": (cu[1:] - 1).astype(np.int32),
        "n_work": np.int32(w),
    }


def _ragged_kernel_body(
    # scalar prefetch
    meta_ref,  # [5, NW] int32 (seg, qblk, rs, rows, qpos0)
    pt_ref,  # [SEG, MP] int32 per-segment page-table rows
    kvl_ref,  # [SEG] int32 per-segment context length
    win_ref,  # [1] int32 sliding window (0 = global) or None
    # blocks
    q_ref,  # [Hk, QB, G, D]
    k_ref,  # [PS, Hk, D] one token-major page
    v_ref,  # [PS, Hk, D]
    ks_ref,  # [PS, Hk] f32 per-vector K scales (int8 KV) or None
    vs_ref,  # [PS, Hk] f32 per-vector V scales or None
    o_ref,  # [Hk, QB, G, D]
    # scratch (persist across the page loop)
    m_ref,  # [Hk, QB*G, 1] f32
    l_ref,  # [Hk, QB*G, 1] f32
    acc_ref,  # [Hk, QB*G, D] f32
    *,
    page_size: int,
    n_groups: int,
    scale: float,
    softcap: float = 0.0,
):
    w = pl.program_id(0)
    i = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    seg = meta_ref[0, w]
    row_start = meta_ref[2, w]
    n_rows = meta_ref[3, w]
    qpos0 = meta_ref[4, w]
    kv_len = kvl_ref[seg]
    # last absolute position any valid row of this work unit can see
    blk_max_pos = qpos0 + n_rows - 1
    page_first = i * page_size
    needed = (n_rows > 0) & (page_first <= blk_max_pos) & (page_first < kv_len)
    if win_ref is not None:
        wv = win_ref[0]
        blk_lo = jnp.where(wv > 0, jnp.maximum(qpos0 - wv + 1, 0), 0)
        needed = needed & (page_first + page_size > blk_lo)

    @pl.when(needed)
    def _compute():
        Hk, QB, G, D = q_ref.shape
        q = q_ref[...].astype(jnp.float32).reshape(Hk, QB * G, D)
        k = k_ref[...].astype(jnp.float32)  # [PS, Hk, D]
        s = lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))), preferred_element_type=jnp.float32
        ) * scale  # [Hk, QB*G, PS]
        if ks_ref is not None:
            s = s * ks_ref[...].T[:, None, :]
        if softcap:
            # the TRUE score (post any int8 fold), matching the jnp path
            s = softcap * jnp.tanh(s / softcap)

        row = lax.broadcasted_iota(jnp.int32, s.shape, 1) // n_groups
        col = lax.broadcasted_iota(jnp.int32, s.shape, 2)
        q_pos = qpos0 + row - row_start  # valid only inside the row band
        kv_pos = page_first + col
        mask = (
            (row >= row_start)
            & (row < row_start + n_rows)
            & (kv_pos <= q_pos)
            & (kv_pos < kv_len)
        )
        if win_ref is not None:
            wv = win_ref[0]
            mask = mask & ((wv <= 0) | (kv_pos > q_pos - wv))
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)

        l_add = jnp.sum(p, axis=2, keepdims=True)  # raw-probability denom
        if vs_ref is not None:
            p = p * vs_ref[...].T[:, None, :]
        v = v_ref[...].astype(jnp.float32)
        pv = lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        l_ref[...] = l_ref[...] * alpha + l_add
        m_ref[...] = m_new

    @pl.when(i == n_pages - 1)
    def _finalize():
        # read-modify-write ONLY this unit's row band: units sharing the
        # block run back to back on the same resident out buffer, each
        # masking in its own rows (increasing-row emission order)
        Hk, QB, G, D = o_ref.shape
        denom = jnp.maximum(l_ref[...], 1e-30)
        res = acc_ref[...] / denom  # [Hk, QB*G, D]
        row = lax.broadcasted_iota(jnp.int32, res.shape, 1) // n_groups
        keep = (row >= row_start) & (row < row_start + n_rows)
        prev = o_ref[...].astype(jnp.float32).reshape(Hk, QB * G, D)
        o_ref[...] = (
            jnp.where(keep, res, prev).astype(o_ref.dtype).reshape(Hk, QB, G, D)
        )


def _ragged_kernel(meta, pt, kl, q, k, v, o, m, l, acc, **kw):
    _ragged_kernel_body(meta, pt, kl, None, q, k, v, None, None,
                        o, m, l, acc, **kw)


def _ragged_kernel_win(meta, pt, kl, win, q, k, v, o, m, l, acc, **kw):
    _ragged_kernel_body(meta, pt, kl, win, q, k, v, None, None,
                        o, m, l, acc, **kw)


def _ragged_kernel_int8(meta, pt, kl, q, k, ks, v, vs, o, m, l, acc, **kw):
    _ragged_kernel_body(meta, pt, kl, None, q, k, v, ks, vs,
                        o, m, l, acc, **kw)


def _ragged_kernel_int8_win(meta, pt, kl, win, q, k, ks, v, vs, o, m, l,
                            acc, **kw):
    _ragged_kernel_body(meta, pt, kl, win, q, k, v, ks, vs,
                        o, m, l, acc, **kw)


def ragged_attention_reference(
    q: jax.Array,  # [T, Hk, G, D]
    k_pool_l,
    v_pool_l,
    tok_page_table: jax.Array,  # [T, MP]
    tok_positions: jax.Array,  # [T] (-1 = padding)
    tok_kv_lens: jax.Array,  # [T]
    *,
    scale=None,
    softcap: float = 0.0,
    window=None,
) -> jax.Array:
    """jnp reference (and CPU fallback): each flat token is a B=T, S=1 row
    of the canonical paged_attention_jnp — per-token page table / kv_len /
    position make arbitrary segment layouts exactly correct."""
    from ..models.toolkit import paged_attention_jnp

    out = paged_attention_jnp(
        q[:, None],
        k_pool_l,
        v_pool_l,
        tok_page_table,
        jnp.maximum(tok_positions, 0)[:, None],
        tok_kv_lens,
        scale=scale,
        softcap=softcap,
        window=window,
    )
    return out[:, 0]


def ragged_paged_attention_sharded(
    q: jax.Array,  # [T, Hk, G, D] heads sharded over `axis_name`
    k_pool_l,
    v_pool_l,
    seg_page_table: jax.Array,
    seg_kv_lens: jax.Array,
    meta: jax.Array,
    mesh,
    axis_name: str = AXIS_MODEL,
    window=None,
    *,
    q_block: int = DEFAULT_Q_BLOCK,
    scale=None,
    softcap: float = 0.0,
    interpret: bool = False,
) -> jax.Array:
    """Tensor-parallel wrapper (see decode_paged_attention_sharded): each
    model-axis shard runs the kernel over its local kv-heads."""
    from jax.sharding import PartitionSpec as P

    heads, pool, scales = attention_specs(axis_name)
    if isinstance(k_pool_l, dict):  # int8 KV: scales [NP, PS, Hk]
        pool = {"q": pool, "s": scales}
    part = functools.partial(
        ragged_paged_attention, q_block=q_block, scale=scale,
        softcap=softcap, interpret=interpret,
    )
    base_specs = (heads, pool, pool, P(None, None), P(None), P(None, None))
    extra = (
        () if window is None
        else (jnp.asarray(window, jnp.int32).reshape(1),)
    )
    fn = jax.shard_map(
        part, mesh=mesh,
        in_specs=base_specs + ((P(),) if extra else ()),
        out_specs=heads, check_vma=False,
    )
    return fn(q, k_pool_l, v_pool_l, seg_page_table, seg_kv_lens, meta,
              *extra)


@functools.partial(
    jax.jit, static_argnames=("q_block", "interpret", "scale", "softcap")
)
def ragged_paged_attention(
    q: jax.Array,  # [T, Hk, G, D] flat query tokens (all segments)
    k_pool_l,  # [NP, PS, Hk, D] token-major (or int8 {"q","s"} dict)
    v_pool_l,
    seg_page_table: jax.Array,  # [SEG, MP] int32
    seg_kv_lens: jax.Array,  # [SEG] int32
    meta: jax.Array,  # [5, NW] int32 work units (build_ragged_metadata)
    window=None,  # None = no-window compile; else traced int32 scalar
    *,
    q_block: int = DEFAULT_Q_BLOCK,
    scale=None,
    softcap: float = 0.0,
    interpret: bool = False,
) -> jax.Array:
    """Returns [T, Hk, G, D]; rows covered by no real segment return 0.
    Every segment's K/V (including its own chunk) must already be written
    to the pool. The compile key is (T, NW, SEG, q_block) — all functions
    of the T bucket, so variants stay at |T buckets|."""
    T, Hk, G, D = q.shape
    quantized = isinstance(k_pool_l, dict)
    kq = k_pool_l["q"] if quantized else k_pool_l
    NP, PS, _, _ = kq.shape
    MP = seg_page_table.shape[1]
    if T % q_block:
        raise ValueError(f"T {T} not a multiple of q_block {q_block}")
    NW = meta.shape[1]
    if scale is None:
        scale = D**-0.5
    windowed = window is not None
    n_prefetch = 4 if windowed else 3

    qt = q.transpose(1, 0, 2, 3)  # [Hk, T, G, D]

    def _clamp(w, i, mt, pt, kl, *rest):
        # clamp dead pages (causal top, kv_len, window low bound) to a
        # repeated index so Pallas elides their DMA — flash-prefill trick,
        # per work unit instead of per (b, sb)
        seg = mt[0, w]
        rows = mt[3, w]
        qpos0 = mt[4, w]
        blk_max_pos = qpos0 + jnp.maximum(rows, 1) - 1
        last = jnp.minimum(blk_max_pos, jnp.maximum(kl[seg] - 1, 0)) // PS
        last = jnp.clip(last, 0, MP - 1)
        i_eff = jnp.minimum(i, last)
        if rest:
            (win,) = rest
            wv = win[0]
            lo = jnp.where(wv > 0, jnp.maximum(qpos0 - wv + 1, 0), 0)
            i_eff = jnp.maximum(i_eff, jnp.minimum(lo // PS, last))
        return seg, i_eff

    def kv_index(w, i, mt, pt, kl, *rest):
        seg, i_eff = _clamp(w, i, mt, pt, kl, *rest)
        return (pt[seg, i_eff], 0, 0, 0)

    def scale_index(w, i, mt, pt, kl, *rest):
        return kv_index(w, i, mt, pt, kl, *rest)[:3]

    def q_index(w, i, mt, pt, kl, *rest):
        return (0, mt[1, w], 0, 0)

    q_spec = pl.BlockSpec((Hk, q_block, G, D), q_index)
    # one token-major page = one contiguous PS*Hk*D slab (single DMA)
    kv_spec = pl.BlockSpec((None, PS, Hk, D), kv_index)
    kw = dict(page_size=PS, n_groups=G, scale=scale, softcap=softcap)
    if quantized:
        kernel = functools.partial(
            _ragged_kernel_int8_win if windowed else _ragged_kernel_int8,
            **kw,
        )
        s_spec = pl.BlockSpec((None, PS, Hk), scale_index)
        in_specs = [q_spec, kv_spec, s_spec, kv_spec, s_spec]
        operands = (qt, kq, k_pool_l["s"], v_pool_l["q"], v_pool_l["s"])
    else:
        kernel = functools.partial(
            _ragged_kernel_win if windowed else _ragged_kernel, **kw
        )
        in_specs = [q_spec, kv_spec, kv_spec]
        operands = (qt, kq, v_pool_l)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # meta, seg_pt, seg_kvl (+ window)
        grid=(NW, MP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((Hk, q_block, G, D), q_index),
        scratch_shapes=[
            pltpu.VMEM((Hk, q_block * G, 1), jnp.float32),
            pltpu.VMEM((Hk, q_block * G, 1), jnp.float32),
            pltpu.VMEM((Hk, q_block * G, D), jnp.float32),
        ],
    )

    prefetch = (meta, seg_page_table, seg_kv_lens)
    if windowed:
        prefetch = prefetch + (
            jnp.asarray(window, jnp.int32).reshape(1),
        )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Hk, T, G, D), q.dtype),
        interpret=interpret,
    )(*prefetch, *operands)
    return out.transpose(1, 0, 2, 3)  # [T, Hk, G, D]
