"""Pallas TPU prefill flash attention over the paged KV pool.

The prefill hot op: a chunk of S new query tokens per sequence attends over
the full paged context (prior prefix-cache/chunk pages + this chunk's own
pages, already written to the pool). The jnp path materializes
[B, Hk, G, S, C] fp32 scores in HBM — O(S·C) traffic that dominates long
prompts. This kernel streams K/V pages HBM→VMEM once per (q-block, page)
pair with flash online softmax in VMEM scratch, and skips both the DMA and
the compute for pages that are entirely masked:

- pages at/after the q-block's last causal position, and pages past
  kv_len, are clamped in the index_map to the last needed page, so the
  block index repeats and Pallas elides the copy (same trick as the decode
  kernel). A causal chunk therefore costs ~half the rectangular DMA.

Layout: q arrives [B, Hk, S, G, D] (wrapper transposes from the model's
[B, S, Hk, G, D]) so a block is [Hk, Sq, G, D] and the matmul runs as one
Hk-batched [Sq*G, D] x [D, PS] — MXU-shaped at Sq=128.

Positions contract (same as models/llama.py paged_attention_jnp): flat
context index c IS absolute position c; query token s of sequence b sits at
absolute position q_start[b] + s for s < q_len[b], padding after.

The reference delegates prefill attention to vLLM/TRT-LLM FlashAttention
CUDA kernels (SURVEY.md: engine tier); this is the TPU-native equivalent.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.parallel.mesh import AXIS_MODEL, prefill_attention_specs

NEG_INF = -1e30


def _prefill_kernel_body(
    # scalar prefetch
    page_table_ref,  # [B, MP] int32
    q_start_ref,  # [B] int32 absolute position of query token 0
    q_len_ref,  # [B] int32 number of valid query tokens
    kv_lens_ref,  # [B] int32 context length (incl. this chunk)
    win_ref,  # [1] int32 sliding window (0 = global) or None (no-window
    #   compile) — Gemma-2 alternates per layer with a traced scalar
    # blocks
    q_ref,  # [Hk, Sq, G, D]
    k_ref,  # [PS, Hk, D] one token-major page (one contiguous DMA)
    v_ref,  # [PS, Hk, D]
    ks_ref,  # [PS, Hk] f32 per-vector K scales (int8 KV) or None
    vs_ref,  # [PS, Hk] f32 per-vector V scales or None
    o_ref,  # [Hk, Sq, G, D]
    # scratch (persist across the page loop)
    m_ref,  # [Hk, Sq*G, 1] f32
    l_ref,  # [Hk, Sq*G, 1] f32
    acc_ref,  # [Hk, Sq*G, D] f32
    *,
    page_size: int,
    q_block: int,
    n_groups: int,
    scale: float,
    softcap: float = 0.0,
):
    b = pl.program_id(0)
    sb = pl.program_id(1)
    i = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q_start = q_start_ref[b]
    q_len = q_len_ref[b]
    kv_len = kv_lens_ref[b]
    # last absolute position any valid query row in this block can see
    blk_rows = jnp.minimum(q_len - sb * q_block, q_block)  # valid rows here
    blk_max_pos = q_start + sb * q_block + blk_rows - 1
    page_first = i * page_size
    needed = (blk_rows > 0) & (page_first <= blk_max_pos) & (page_first < kv_len)
    if win_ref is not None:
        # sliding window: the EARLIEST position any row here can see is
        # first_row_pos - w + 1; pages wholly before that are dead (their
        # DMA is already elided by the index_map's low clamp)
        w = win_ref[0]
        blk_lo = jnp.where(
            w > 0, jnp.maximum(q_start + sb * q_block - w + 1, 0), 0
        )
        needed = needed & (page_first + page_size > blk_lo)

    @pl.when(needed)
    def _compute():
        Hk, Sq, G, D = q_ref.shape
        q = q_ref[...].astype(jnp.float32).reshape(Hk, Sq * G, D)
        k = k_ref[...].astype(jnp.float32)  # [PS, Hk, D]
        s = lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))), preferred_element_type=jnp.float32
        ) * scale  # [Hk, Sq*G, PS]
        if ks_ref is not None:
            # int8 KV: fold per-(token, head) K scales into the scores
            # ((PS, Hk) block transposed in-register — 2 KiB)
            s = s * ks_ref[...].T[:, None, :]
        if softcap:
            # the TRUE score (post any int8 fold), matching the jnp path
            s = softcap * jnp.tanh(s / softcap)

        row = lax.broadcasted_iota(jnp.int32, s.shape, 1) // n_groups  # sq idx
        col = lax.broadcasted_iota(jnp.int32, s.shape, 2)  # slot in page
        q_pos = q_start + sb * q_block + row
        kv_pos = page_first + col
        mask = (row < blk_rows) & (kv_pos <= q_pos) & (kv_pos < kv_len)
        if win_ref is not None:
            w = win_ref[0]
            mask = mask & ((w <= 0) | (kv_pos > q_pos - w))
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)

        l_add = jnp.sum(p, axis=2, keepdims=True)  # raw-probability denom
        if vs_ref is not None:
            p = p * vs_ref[...].T[:, None, :]  # fold V scales into p
        v = v_ref[...].astype(jnp.float32)  # [PS, Hk, D]
        pv = lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [Hk, Sq*G, D]
        acc_ref[...] = acc_ref[...] * alpha + pv
        l_ref[...] = l_ref[...] * alpha + l_add
        m_ref[...] = m_new

    @pl.when(i == n_pages - 1)
    def _finalize():
        Hk, Sq, G, D = o_ref.shape
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / denom).astype(o_ref.dtype).reshape(Hk, Sq, G, D)


def _prefill_kernel(pt, qs, ql, kl, q, k, v, o, m, l, acc, **kw):
    _prefill_kernel_body(pt, qs, ql, kl, None, q, k, v, None, None,
                         o, m, l, acc, **kw)


def _prefill_kernel_win(pt, qs, ql, kl, win, q, k, v, o, m, l, acc, **kw):
    _prefill_kernel_body(pt, qs, ql, kl, win, q, k, v, None, None,
                         o, m, l, acc, **kw)


def _prefill_kernel_int8(pt, qs, ql, kl, q, k, ks, v, vs, o, m, l, acc, **kw):
    _prefill_kernel_body(pt, qs, ql, kl, None, q, k, v, ks, vs,
                         o, m, l, acc, **kw)


def _prefill_kernel_int8_win(pt, qs, ql, kl, win, q, k, ks, v, vs, o, m, l,
                             acc, **kw):
    _prefill_kernel_body(pt, qs, ql, kl, win, q, k, v, ks, vs,
                         o, m, l, acc, **kw)


def prefill_paged_attention_sharded(
    q: jax.Array,  # [B, S, Hk, G, D] heads sharded over `axis_name`
    k_pool_l: jax.Array,  # [NP, PS, Hk, D] (token-major)
    v_pool_l: jax.Array,
    page_table: jax.Array,
    q_start: jax.Array,
    q_len: jax.Array,
    kv_lens: jax.Array,
    mesh,
    axis_name: str = AXIS_MODEL,
    window=None,  # traced int32 scalar (see prefill_paged_attention)
    *,
    q_block: int = 128,
    scale=None,
    softcap: float = 0.0,
    interpret: bool = False,
) -> jax.Array:
    """Tensor-parallel wrapper (see decode_paged_attention_sharded): each
    model-axis shard runs the kernel over its local kv-heads."""
    from jax.sharding import PartitionSpec as P

    heads, pool, scales = prefill_attention_specs(axis_name)
    if isinstance(k_pool_l, dict):  # int8 KV: scales [NP, PS, Hk] shard
        # the same head axis
        pool = {"q": pool, "s": scales}
    part = functools.partial(
        prefill_paged_attention, q_block=q_block, scale=scale,
        softcap=softcap, interpret=interpret,
    )
    base_specs = (heads, pool, pool, P(None, None), P(None), P(None), P(None))
    extra = (
        () if window is None
        else (jnp.asarray(window, jnp.int32).reshape(1),)
    )
    fn = jax.shard_map(
        part, mesh=mesh,
        in_specs=base_specs + ((P(),) if extra else ()),
        out_specs=heads, check_vma=False,
    )
    return fn(q, k_pool_l, v_pool_l, page_table, q_start, q_len, kv_lens,
              *extra)


@functools.partial(
    jax.jit, static_argnames=("q_block", "interpret", "scale", "softcap")
)
def prefill_paged_attention(
    q: jax.Array,  # [B, S, Hk, G, D]
    k_pool_l: jax.Array,  # [NP, PS, Hk, D] (token-major)
    v_pool_l: jax.Array,
    page_table: jax.Array,  # [B, MP] int32
    q_start: jax.Array,  # [B] int32 absolute position of query token 0
    q_len: jax.Array,  # [B] int32 valid query tokens (rest are padding)
    kv_lens: jax.Array,  # [B] int32 context length incl. this chunk
    window=None,  # None = no-window compile; else traced int32 scalar
    #   (0 = global at runtime) — see decode_paged_attention
    *,
    q_block: int = 128,
    scale=None,  # static score-scale override (query_pre_attn_scalar)
    softcap: float = 0.0,  # Gemma-2 logit soft capping (static; 0 = off)
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, S, Hk, G, D]; padding rows (s >= q_len[b]) return 0.
    The chunk's own K/V must already be written to the pool."""
    B, S, Hk, G, D = q.shape
    quantized = isinstance(k_pool_l, dict)
    kq = k_pool_l["q"] if quantized else k_pool_l
    NP, PS, _, _ = kq.shape
    MP = page_table.shape[1]
    q_block = min(q_block, S)
    while S % q_block:  # largest divisor of S at most the requested block
        q_block -= 1
    n_sblk = S // q_block
    if scale is None:
        scale = D**-0.5
    windowed = window is not None
    n_prefetch = 5 if windowed else 4

    qt = q.transpose(0, 2, 1, 3, 4)  # [B, Hk, S, G, D]

    def _clamp(b, sb, i, pt, qs, ql, kl, *rest):
        # clamp to the page range this q-block can actually see (causal
        # top, kv_len, and — with a window — the sliding low bound):
        # repeated indices across grid steps → Pallas skips the DMA
        rows = jnp.minimum(ql[b] - sb * q_block, q_block)
        blk_max_pos = qs[b] + sb * q_block + jnp.maximum(rows, 1) - 1
        last = jnp.minimum(blk_max_pos, jnp.maximum(kl[b] - 1, 0)) // PS
        last = jnp.clip(last, 0, MP - 1)
        i_eff = jnp.minimum(i, last)
        if rest:
            (win,) = rest
            w = win[0]
            lo = jnp.where(
                w > 0, jnp.maximum(qs[b] + sb * q_block - w + 1, 0), 0
            )
            i_eff = jnp.maximum(i_eff, jnp.minimum(lo // PS, last))
        return i_eff

    def kv_index(b, sb, i, pt, qs, ql, kl, *rest):
        return (pt[b, _clamp(b, sb, i, pt, qs, ql, kl, *rest)], 0, 0, 0)

    def scale_index(b, sb, i, pt, qs, ql, kl, *rest):
        return kv_index(b, sb, i, pt, qs, ql, kl, *rest)[:3]

    def q_index(b, sb, i, pt, qs, ql, kl, *rest):
        return (b, 0, sb, 0, 0)

    q_spec = pl.BlockSpec((None, Hk, q_block, G, D), q_index)
    # one token-major page = one contiguous PS*Hk*D slab (single DMA)
    kv_spec = pl.BlockSpec((None, PS, Hk, D), kv_index)
    kw = dict(page_size=PS, q_block=q_block, n_groups=G, scale=scale,
              softcap=softcap)
    if quantized:
        kernel = functools.partial(
            _prefill_kernel_int8_win if windowed else _prefill_kernel_int8,
            **kw,
        )
        # (None, PS, Hk): minor dims are full array dims — legal tile
        s_spec = pl.BlockSpec((None, PS, Hk), scale_index)
        in_specs = [q_spec, kv_spec, s_spec, kv_spec, s_spec]
        operands = (qt, kq, k_pool_l["s"], v_pool_l["q"], v_pool_l["s"])
    else:
        kernel = functools.partial(
            _prefill_kernel_win if windowed else _prefill_kernel, **kw
        )
        in_specs = [q_spec, kv_spec, kv_spec]
        operands = (qt, kq, v_pool_l)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # pt, q_start, q_len, kv (+ window)
        grid=(B, n_sblk, MP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, Hk, q_block, G, D), q_index),
        scratch_shapes=[
            pltpu.VMEM((Hk, q_block * G, 1), jnp.float32),
            pltpu.VMEM((Hk, q_block * G, 1), jnp.float32),
            pltpu.VMEM((Hk, q_block * G, D), jnp.float32),
        ],
    )

    prefetch = (page_table, q_start, q_len, kv_lens)
    if windowed:
        prefetch = prefetch + (
            jnp.asarray(window, jnp.int32).reshape(1),
        )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hk, S, G, D), q.dtype),
        interpret=interpret,
    )(*prefetch, *operands)
    return out.transpose(0, 2, 1, 3, 4)  # [B, S, Hk, G, D]
