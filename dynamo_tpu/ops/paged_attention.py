"""Pallas TPU decode paged attention.

The decode hot op: one query token per sequence attends over that
sequence's paged KV (pages scattered in a global HBM pool, owned via a page
table). The jnp reference path (models/llama.py paged_attention_jnp)
gathers all pages into a dense [B, ctx] tensor per layer — an extra HBM
round trip of the whole KV working set. This kernel streams each page
HBM→VMEM once via BlockSpec index_maps driven by the scalar-prefetched page
table and accumulates flash-attention-style online softmax in VMEM scratch.

Grid: (B, MP) — page index innermost so the per-sequence running softmax
state lives across the page loop; all kv heads are processed per step. A
token-major page [PS, Hk, D] is one CONTIGUOUS slab in the pool, so each
grid step issues a single large DMA (the head-major layout needed Hk
strided chunks per page). Ragged contexts cost
only what they use: the index_map clamps pages past kv_len to the last
valid page, so consecutive grid steps see an unchanged block index and
Pallas elides the HBM→VMEM copy (and pl.when skips the compute).

The reference framework ships CUDA kernels for its block engine
(lib/llm/src/kernels/block_copy.cu, lib/kvbm-kernels/cuda/
tensor_kernels.cu); attention itself lives in vLLM. This is the TPU-native
equivalent of that hot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.parallel.mesh import AXIS_MODEL, attention_specs

NEG_INF = -1e30


def _decode_kernel_body(
    page_table_ref,  # [B, MP] int32 (SMEM)
    kv_lens_ref,  # [B] int32 (SMEM)
    win_ref,  # [1] int32 sliding window (0 = global) or None (no-window
    #   compile: Gemma-2 alternates sliding/global per layer with a
    #   TRACED scalar, so the window rides as a prefetch operand)
    q_ref,  # [Hk, G, D] all query heads for seq b
    k_ref,  # [PS, Hk, D] one token-major page of keys (one contiguous DMA)
    v_ref,  # [PS, Hk, D]
    ks_ref,  # [PS, Hk] f32 per-vector K scales (int8 KV) or None
    vs_ref,  # [PS, Hk] f32 per-vector V scales or None
    o_ref,  # [Hk, G, D]
    # scratch (persist across the page loop)
    m_ref,  # [Hk, G, 1] f32 running max
    l_ref,  # [Hk, G, 1] f32 running denom
    acc_ref,  # [Hk, G, D] f32 running numerator
    *,
    page_size: int,
    scale: float,
    softcap: float = 0.0,  # Gemma-2 attention-score soft capping (0 = off)
):
    b = pl.program_id(0)
    i = pl.program_id(1)
    n_pages = pl.num_programs(1)

    @pl.when(i == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    kv_len = kv_lens_ref[b]
    n_valid = jnp.clip(kv_len - i * page_size, 0, page_size)
    # sliding window: the decode query sits at position kv_len-1, so only
    # positions >= lo = kv_len - window are visible. Pages wholly below lo
    # contribute nothing (their DMA is already elided by the index_map's
    # low clamp); partially-covered pages mask their leading slots.
    lo = jnp.int32(0)
    if win_ref is not None:
        w = win_ref[0]
        lo = jnp.where(w > 0, jnp.maximum(kv_len - w, 0), 0)
    lo_in_page = jnp.clip(lo - i * page_size, 0, page_size)

    @pl.when((n_valid > 0) & (lo_in_page < n_valid))
    def _compute():
        q = q_ref[...].astype(jnp.float32)  # [Hk, G, D]
        k = k_ref[...].astype(jnp.float32)  # [PS, Hk, D]
        # s[h, g, p] = q[h, g, :] · k[p, h, :] (batch dim Hk sits at k
        # axis 1 — dot_general takes batch dims at any position)
        s = lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (1,))), preferred_element_type=jnp.float32
        ) * scale  # [Hk, G, PS]
        if ks_ref is not None:
            # int8 KV: fold the per-(token, head) K scale into the scores
            # instead of dequantizing K over D (one [Hk, 1, PS] multiply
            # replaces a [PS, Hk, D] one); the (PS, Hk) block transposes
            # in-register — 2 KiB, negligible next to the page DMA
            s = s * ks_ref[...].T[:, None, :]
        if softcap:
            # applied to the TRUE score (after any int8 scale fold),
            # matching paged_attention_jnp's order
            s = softcap * jnp.tanh(s / softcap)
        pos = lax.broadcasted_iota(jnp.int32, s.shape, 2)
        valid = (pos < n_valid) & (pos >= lo_in_page)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[...]  # [Hk, G, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)  # [Hk, G, PS]
        alpha = jnp.exp(m_prev - m_new)

        l_add = jnp.sum(p, axis=2, keepdims=True)  # BEFORE any V scaling:
        # the softmax denominator sums raw probabilities
        if vs_ref is not None:
            # fold the V scale into p before the PV matmul (same trick)
            p = p * vs_ref[...].T[:, None, :]
        v = v_ref[...].astype(jnp.float32)  # [PS, Hk, D]
        pv = lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))), preferred_element_type=jnp.float32
        )  # [Hk, G, D]
        acc_ref[...] = acc_ref[...] * alpha + pv
        l_ref[...] = l_ref[...] * alpha + l_add
        m_ref[...] = m_new

    @pl.when(i == n_pages - 1)
    def _finalize():
        denom = jnp.maximum(l_ref[...], 1e-30)
        o_ref[...] = (acc_ref[...] / denom).astype(o_ref.dtype)


def _decode_kernel(pt, kl, q, k, v, o, m, l, acc, *, page_size, scale,
                   softcap=0.0):
    _decode_kernel_body(
        pt, kl, None, q, k, v, None, None, o, m, l, acc,
        page_size=page_size, scale=scale, softcap=softcap,
    )


def _decode_kernel_win(pt, kl, win, q, k, v, o, m, l, acc, *, page_size,
                       scale, softcap=0.0):
    _decode_kernel_body(
        pt, kl, win, q, k, v, None, None, o, m, l, acc,
        page_size=page_size, scale=scale, softcap=softcap,
    )


def _decode_kernel_int8(pt, kl, q, k, ks, v, vs, o, m, l, acc, *, page_size,
                        scale, softcap=0.0):
    _decode_kernel_body(
        pt, kl, None, q, k, v, ks, vs, o, m, l, acc,
        page_size=page_size, scale=scale, softcap=softcap,
    )


def _decode_kernel_int8_win(pt, kl, win, q, k, ks, v, vs, o, m, l, acc, *,
                            page_size, scale, softcap=0.0):
    _decode_kernel_body(
        pt, kl, win, q, k, v, ks, vs, o, m, l, acc,
        page_size=page_size, scale=scale, softcap=softcap,
    )


def decode_paged_attention_sharded(
    q: jax.Array,  # [B, Hk, G, D] heads sharded over `axis_name`
    k_pool_l: jax.Array,  # [NP, PS, Hk, D] heads sharded over `axis_name`
    v_pool_l: jax.Array,
    page_table: jax.Array,  # [B, MP] replicated
    kv_lens: jax.Array,  # [B] replicated
    mesh,
    axis_name: str = AXIS_MODEL,
    window=None,  # traced int32 scalar (see decode_paged_attention)
    *,
    scale=None,
    softcap: float = 0.0,
    interpret: bool = False,
) -> jax.Array:
    """Tensor-parallel wrapper: attention is independent per kv-head, and
    the KV pool shards kv-heads over the model axis (ShardingPolicy), so
    each shard runs the kernel on its local heads — zero collectives (the
    block all-reduce happens later in the out-projection as usual)."""
    from jax.sharding import PartitionSpec as P

    heads, pool, scales = attention_specs(axis_name)
    if isinstance(k_pool_l, dict):  # int8 KV: scales [NP, PS, Hk] shard
        # the same head axis
        pool = {"q": pool, "s": scales}
    rep2 = P(None, None)
    rep1 = P(None)
    part = functools.partial(
        decode_paged_attention, scale=scale, softcap=softcap,
        interpret=interpret,
    )
    if window is None:
        fn = jax.shard_map(
            part,
            mesh=mesh,
            in_specs=(heads, pool, pool, rep2, rep1),
            out_specs=heads,
            check_vma=False,
        )
        return fn(q, k_pool_l, v_pool_l, page_table, kv_lens)
    fn = jax.shard_map(
        part,
        mesh=mesh,
        in_specs=(heads, pool, pool, rep2, rep1, P()),
        out_specs=heads,
        check_vma=False,
    )
    return fn(q, k_pool_l, v_pool_l, page_table, kv_lens,
              jnp.asarray(window, jnp.int32).reshape(1))


@functools.partial(
    jax.jit, static_argnames=("interpret", "scale", "softcap")
)
def decode_paged_attention(
    q: jax.Array,  # [B, Hk, G, D]
    k_pool_l: jax.Array,  # [NP, PS, Hk, D] one layer's token-major key pool
    v_pool_l: jax.Array,
    page_table: jax.Array,  # [B, MP] int32
    kv_lens: jax.Array,  # [B] int32 (context length incl. current token)
    window=None,  # None = no-window compile; else a traced int32 scalar
    #   (0 = global at runtime) — Gemma-2 alternates per layer in the scan
    *,
    scale=None,  # static score-scale override (query_pre_attn_scalar)
    softcap: float = 0.0,  # Gemma-2 logit soft capping (static; 0 = off)
    interpret: bool = False,
) -> jax.Array:
    """Returns [B, Hk, G, D]. KV for the current token must already be
    written to the pool (same contract as paged_attention_jnp)."""
    B, Hk, G, D = q.shape
    quantized = isinstance(k_pool_l, dict)
    kq = k_pool_l["q"] if quantized else k_pool_l
    NP, PS, _, _ = kq.shape
    MP = page_table.shape[1]
    if scale is None:
        scale = D**-0.5
    windowed = window is not None
    n_prefetch = 3 if windowed else 2

    def _clamp(b, i, pt, kl, *rest):
        # clamp past-the-end pages to the last valid page: the block index
        # then repeats across those grid steps and Pallas skips the DMA,
        # so a 128-token context in an 8192-token table costs 2 page
        # copies, not 128. With a sliding window, pages wholly below the
        # window likewise clamp UP to the first live page.
        last = jnp.maximum(kl[b] - 1, 0) // PS
        i_eff = jnp.minimum(i, last)
        if rest:
            (win,) = rest
            w = win[0]
            lo = jnp.where(w > 0, jnp.maximum(kl[b] - w, 0), 0)
            i_eff = jnp.maximum(i_eff, jnp.minimum(lo // PS, last))
        return i_eff

    def kv_index(b, i, pt, kl, *rest):
        return (pt[b, _clamp(b, i, pt, kl, *rest)], 0, 0, 0)

    def scale_index(b, i, pt, kl, *rest):
        return kv_index(b, i, pt, kl, *rest)[:3]

    def fixed_index(b, i, pt, kl, *rest):
        return (b, 0, 0, 0)

    q_spec = pl.BlockSpec((None, Hk, G, D), fixed_index)
    # one token-major page = one contiguous PS*Hk*D slab: a single DMA,
    # with a legal (PS, Hk, D) tile (minor dims (Hk, D))
    kv_spec = pl.BlockSpec((None, PS, Hk, D), kv_index)
    kw = dict(page_size=PS, scale=scale, softcap=softcap)
    if quantized:
        kernel = functools.partial(
            _decode_kernel_int8_win if windowed else _decode_kernel_int8, **kw
        )
        # (None, PS, Hk): minor dims are full array dims — legal tile
        s_spec = pl.BlockSpec((None, PS, Hk), scale_index)
        in_specs = [q_spec, kv_spec, s_spec, kv_spec, s_spec]
        operands = (q, kq, k_pool_l["s"], v_pool_l["q"], v_pool_l["s"])
    else:
        kernel = functools.partial(
            _decode_kernel_win if windowed else _decode_kernel, **kw
        )
        in_specs = [q_spec, kv_spec, kv_spec]
        operands = (q, kq, v_pool_l)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,  # page_table, kv_lens (+ window)
        grid=(B, MP),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, Hk, G, D), fixed_index),
        scratch_shapes=[
            pltpu.VMEM((Hk, G, 1), jnp.float32),
            pltpu.VMEM((Hk, G, 1), jnp.float32),
            pltpu.VMEM((Hk, G, D), jnp.float32),
        ],
    )

    prefetch = (page_table, kv_lens)
    if windowed:
        prefetch = prefetch + (
            jnp.asarray(window, jnp.int32).reshape(1),
        )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hk, G, D), q.dtype),
        interpret=interpret,
    )(*prefetch, *operands)
    return out
