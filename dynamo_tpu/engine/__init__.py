"""Native JAX serving engine: paged KV cache, continuous batching scheduler,
bucketed jit step functions, on-device sampling.

The reference orchestrates external engines (vLLM/SGLang/TRT-LLM); this
package is the TPU-native engine those adapters would wrap — it speaks the
same worker protocol (PreprocessedRequest in, engine-output items out) as
the rest of the stack.
"""
