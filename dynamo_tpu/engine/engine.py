"""InferenceEngine: the native TPU serving engine as an AsyncEngine.

Bridges the asyncio worker process and the blocking JAX step loop: requests
enter via `generate()` (the standard worker protocol — PreprocessedRequest
in, engine-output items out), a dedicated step thread runs the
scheduler/runner loop, and sampled tokens flow back through per-request
asyncio queues (one cross-thread hop per engine step, not per token).

Fills the role the reference delegates to vLLM/SGLang/TRT-LLM AsyncLLM
(components/src/dynamo/vllm/handlers.py), natively on TPU.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import queue as thread_queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Dict, List, Optional

import numpy as np

from typing import TYPE_CHECKING

from dynamo_tpu.engine.kv_pool import KvEvent, NoSpace, PagePool

if TYPE_CHECKING:  # jax stays un-imported in mocker processes
    from dynamo_tpu.engine.model_runner import ModelRunner
from dynamo_tpu.engine.scheduler import (
    DecodePlan,
    MixedPlan,
    PrefillPlan,
    Scheduler,
    SchedulerStats,
    Sequence,
    SeqState,
)
from dynamo_tpu.engine.ngram_draft import (
    accept_deterministic,
    accept_tree,
    propose as ngram_propose,
    propose_tree as ngram_propose_tree,
)
from dynamo_tpu.frontend.protocols import engine_output
from dynamo_tpu.runtime.annotations import annotate
from dynamo_tpu.runtime.context import Context
from dynamo_tpu.runtime.flight_recorder import FlightRecorder, IterationRecord
from dynamo_tpu.runtime import tracing

log = logging.getLogger("dynamo_tpu.engine")

# per-request ITL sample cap: bounds the spine's memory on long generations
_ITL_CAP = 512

# cached on a matcher whose schema exceeded the device DFA table budget,
# so the build (and its warning) happens once per matcher, not per dispatch
_OVER_BUDGET = object()


@dataclass
class ForwardPassMetrics:
    """Per-iteration engine metrics published for the planner (analog of
    reference FPM, docs/design-docs/planner-design.md:237-246)."""

    ts: float
    kind: str  # "prefill" | "decode"
    wall_time_s: float
    scheduled_tokens: int
    n_running: int
    n_waiting: int
    kv_usage: float


class GuidedMaskContext:
    """Per-dispatch host state that advances guided DFAs BETWEEN the steps
    of a fused decode loop (docs/agentic_serving.md). The runner's ordered
    io_callback calls `ctx(t, prev_tokens)` once per fused step; the
    context advances a COPY of each guided row's DFA state by the token
    that row sampled at step t-1 and returns the [B, V] sampling mask for
    step t. The engine's per-emitted-token `_guided_advance` stays
    authoritative — these copies exist only so constrained rows can ride
    full `decode_steps` loops instead of collapsing the whole plan to
    n_steps=1.

    `pending_advance=True` marks a context whose fed tokens have not been
    folded into the states yet (the ragged tail loop: tok0 was sampled on
    device by the ragged step), so the t=0 call advances too. A row whose
    copy hits EOS or desyncs goes all-True for the remaining steps — the
    engine discards tokens past a finish anyway."""

    def __init__(self, B: int, vocab: int, rows, pending_advance: bool = False):
        self.B = int(B)
        self.vocab = int(vocab)
        # row: [batch index, matcher, state copy, alive]
        self.rows = [[int(i), m, int(s), True] for i, m, s in rows]
        self.pending_advance = bool(pending_advance)
        self.calls = 0

    def _row_mask(self, m, state) -> np.ndarray:
        row = m.allowed(state)
        if not row.any():
            # degrade exactly like Engine._guided_mask: force EOS rather
            # than sampling garbage from an unextendable constraint
            row = row.copy()
            eos = m.lifter.eos_id
            if 0 <= eos < row.shape[0]:
                row[eos] = True
        return row

    def __call__(self, t, prev_tokens) -> np.ndarray:
        self.calls += 1
        t = int(t)
        mask = np.ones((self.B, self.vocab), bool)
        for row in self.rows:
            idx, m, state, alive = row
            if not alive:
                continue
            if t > 0 or self.pending_advance:
                tok = int(prev_tokens[idx])
                if tok == m.lifter.eos_id:
                    row[3] = False
                    continue
                try:
                    row[2] = state = m.advance(state, tok)
                except ValueError:
                    # desync (padding row fed a masked-out token, or the
                    # authoritative engine already finished the request)
                    row[3] = False
                    continue
            mask[idx] = self._row_mask(m, state)[: self.vocab]
        return mask


class InferenceEngine:
    def __init__(
        self,
        runner: "ModelRunner",
        *,
        max_batch: int = 64,
        chunk_size: int = 512,
        decode_steps: int = 4,
        mixed_prefill_tokens: int = 256,  # per-iteration prefill token POOL
        #   when co-scheduled with decode, fair-shared across packed chunks
        #   (0 = strict prefill-first alternation)
        mixed_prefill_seqs: int = 8,  # max distinct prefills packed per
        #   iteration (1 = legacy single-chunk MixedPlan)
        mixed_min_chunk: int = 16,  # fair-share floor per packed sequence
        idle_sleep_s: float = 0.002,
        host_kv_blocks: int = 0,  # G2 host-tier capacity (0 = disabled)
        disk_kv_blocks: int = 0,  # G3 disk-tier capacity (needs G2 enabled)
        disk_kv_root: Optional[str] = None,
        disk_kv_bytes: Optional[int] = None,  # G3 byte budget: exceeding
        #   it spills LRU blocks down to G4 even with block slots free
        obj_kv_root: Optional[str] = None,  # G4 object store (fs backend /
        #   shared mount; S3 via kvbm.object_store.S3Backend)
        slice_id: Optional[str] = None,  # topology label (ICI island) for
        #   link-class routing; advertised as kv_slice metadata
        kv_tier_quantize: bool = False,  # store demoted G2/G3/G4 blocks as
        #   int8 + per-(token, head) scales (kvbm/quant.py) — ~2x effective
        #   cold-tier capacity; promotion dequantizes, or passes through
        #   natively when the device pools are int8 (kv_quantize)
        onboard_layer_groups: int = 1,  # stream tier onboarding in this
        #   many contiguous layer groups (FlowKV-style overlap of transfer
        #   with the first layers' compute; 1 = whole-sequence import)
        prefetch: bool = False,  # router-hinted tier promotion ahead of
        #   dispatch (kvbm/prefetch.py; needs host_kv_blocks > 0)
        prefetch_max_inflight: int = 4,  # concurrent G3→G2 reads
        prefetch_bandwidth_mbps: float = 0.0,  # promoted bytes/s (0 = off)
        prefetch_hint_ttl_s: float = 10.0,  # unserved hint cancellation
        prefetch_pin_ttl_s: float = 5.0,  # promoted-block pin lifetime
        tokenizer_spec: str = "byte",  # guided decoding lifts byte DFAs to
        #   token masks against THIS tokenizer (must match the frontend's)
        recorder_size: int = 4096,  # flight-recorder ring capacity (0 = off)
        anomaly_k: float = 4.0,  # iteration wall > EWMA*k fires the trigger
        anomaly_dump_dir: Optional[str] = None,  # None = count, don't dump
        anomaly_dump_last_n: int = 256,  # ring records per anomaly dump
        anomaly_profile_ms: int = 0,  # >0: jax.profiler window per dump
        spec_ngram: bool = False,  # n-gram/prompt-lookup speculative
        #   decoding: draft from each sequence's own token history, verify
        #   as K+1-token ragged rows in the mixed dispatch
        spec_k: int = 4,  # draft tokens proposed per sequence per step
        spec_max_tokens: int = 0,  # per-iteration cap on drafted tokens
        #   (0 = bounded only by the mixed pool leftover)
        spec_branches: int = 1,  # tree speculation: candidate draft
        #   branches per sequence per verify iteration. 1 = linear-K
        #   (exact PR 8 behavior). >1 adds alternate-continuation verify
        #   rows sharing the sequence's trunk KV via PagePool.fork_table
        #   ref-sharing; acceptance walks the branch trie emitting target
        #   samples (distribution-preserving at any temperature), then
        #   the winning branch's forked table is adopted and the losers
        #   released — see docs/spec_decode.md
        spec_device_draft: Optional[bool] = None,  # device-resident
        #   n-gram proposal (runner draft_step): history lives in a
        #   device ring, the suffix match runs as one jitted gather over
        #   all slots, and the proposal readback is the only host touch
        #   (sanitizer label draft_readback). None = auto (on when the
        #   runner has draft_step); False forces the host-side scan
        enable_prefix_cache: bool = True,  # content-addressed KV reuse
        #   (session-tree warm turns; off = every prompt prefills cold —
        #   the A/B knob bench_agentic flips)
        sanitize: Optional[bool] = None,  # runtime sanitizer (transfer
        #   guard, recompile tripwire, lock-order recorder, pool audit);
        #   None = follow DYN_SAN env
        sanitizer: Optional[Any] = None,  # pre-built Sanitizer to share
        #   across engines (fleet-sim); overrides `sanitize`
    ):
        self.runner = runner
        # fused mixed dispatch (one program per iteration instead of two):
        # the win is the per-dispatch RTT, which matters on accelerators
        # (relay-attached chips pay ~3.7 ms each) — but the fused program
        # adds one compile unit per (decode bucket x prefill bucket)
        # combination, which on cold CPU test rigs inflates first-request
        # TTFT for no latency benefit. Default: fuse on accelerators,
        # not on cpu; DYN_FUSED_MIXED=0/1 overrides for A/Bs.
        import os as _os

        _fuse_env = _os.environ.get("DYN_FUSED_MIXED", "").lower()
        if _fuse_env in ("1", "true", "on", "yes"):
            self.fused_mixed = True
        elif _fuse_env in ("0", "false", "off", "no"):
            self.fused_mixed = False
        else:
            try:
                platform = runner.mesh.devices.flat[0].platform
            except AttributeError:  # SimRunner (no mesh, no fused method)
                platform = "cpu"
            self.fused_mixed = platform != "cpu"
        # cross-worker KVBM onboarding: worker_common injects an async
        # callable(hint) -> payload that pulls blocks from a peer's
        # kv_host_fetch endpoint (None = feature off)
        self.remote_kv_fetch = None
        self.pool = PagePool(runner.num_pages, runner.page_size)
        # fork-on-branch CoW: the pool copies a forked tail page's device
        # KV through the runner (None = runner can't copy; forks then
        # share garbage tails, which only matters once a runner that
        # writes real KV omits copy_pages — both real+sim define it)
        self.pool.copy_hook = getattr(runner, "copy_pages", None)
        self.host_pool = None
        self._host_events: List[KvEvent] = []
        self.kv_tier_quantize = bool(kv_tier_quantize)
        self.onboard_layer_groups = max(1, int(onboard_layer_groups))
        # per-tier EWMA of measured per-block onboard seconds (the phase
        # spine's kv_onboard_s attributed to the deepest tier each chain
        # touched, plus the remote-pull leg). Published in fleet digests;
        # the router's topology-aware placement consumes it as the live
        # transfer-cost model.
        self.kv_onboard_ewma: Dict[str, Dict[str, float]] = {}
        self.slice_id = str(slice_id) if slice_id is not None else None
        if (disk_kv_blocks > 0 or obj_kv_root) and host_kv_blocks <= 0:
            log.warning(
                "disk/object KV tiers ignored: they spill from the G2 host "
                "tier — also set host_kv_blocks > 0",
            )
        if host_kv_blocks > 0:
            from dynamo_tpu.kvbm.disk_pool import DiskKvPool, TieredKv
            from dynamo_tpu.kvbm.host_pool import HostKvPool

            host = HostKvPool(capacity_blocks=host_kv_blocks,
                              quantize=kv_tier_quantize)
            disk = None
            if disk_kv_blocks > 0:
                import tempfile

                disk = DiskKvPool(
                    disk_kv_root or tempfile.mkdtemp(prefix="dyn_kv_g3_"),
                    capacity_blocks=disk_kv_blocks,
                    quantize=kv_tier_quantize,
                    capacity_bytes=disk_kv_bytes,
                )
            obj = None
            if obj_kv_root:
                from dynamo_tpu.kvbm.object_store import FsBackend, ObjectKvPool

                obj = ObjectKvPool(FsBackend(obj_kv_root),
                                   quantize=kv_tier_quantize)
                # shared-tier residency events for the router's G4 index
                # (fires from the writer/spill thread → step thread)
                obj.store_listener = self._on_obj_stored
            self.host_pool = TieredKv(host, disk, obj)
            self.pool.evict_hook = self._offload_page
            self.host_pool.on_evict(self._on_host_evicted)
        self.prefetch = None
        if prefetch and self.host_pool is not None:
            from dynamo_tpu.kvbm.prefetch import PrefetchManager

            self.prefetch = PrefetchManager(
                self,
                max_inflight=prefetch_max_inflight,
                bandwidth_mbps=prefetch_bandwidth_mbps,
                hint_ttl_s=prefetch_hint_ttl_s,
                pin_ttl_s=prefetch_pin_ttl_s,
            )
        elif prefetch:
            log.warning(
                "prefetch requested without a host KV tier "
                "(host_kv_blocks=0); disabled")
        self.scheduler = Scheduler(
            self.pool,
            max_batch=max_batch,
            chunk_size=chunk_size,
            max_seq_pages=runner.max_pages_per_seq,
            max_seq_tokens=getattr(
                getattr(runner, "config", None), "max_seq_len", 0
            ) or 0,
            decode_steps=decode_steps,
            enable_prefix_cache=enable_prefix_cache,
            mixed_prefill_tokens=mixed_prefill_tokens,
            mixed_prefill_seqs=mixed_prefill_seqs,
            mixed_min_chunk=mixed_min_chunk,
            host_tier=self.host_pool,
            host_onboard=self._onboard_from_host if self.host_pool is not None else None,
            spec_max_tokens=spec_max_tokens,
            # ragged runners sample at most seg_cap rows per dispatch;
            # budgeting verify tokens to RAGGED_MAX_SEGS (= 96, minus one
            # slot per decode row / chunk) keeps every verify dispatch
            # inside the gather the compiled program already has — the
            # no-new-compile-families invariant (docs/ragged_attention.md)
            spec_seg_budget=(
                96 if hasattr(runner, "ensure_ragged_bucket") else 0
            ),
        )
        # n-gram speculative decoding (docs/spec_decode.md): drafts ride
        # the mixed dispatch as ragged verify rows, so both the runner
        # verify hook and a non-zero mixed pool are required
        self.spec_ngram = bool(spec_ngram)
        self.spec_k = max(1, int(spec_k))
        self._spec_on = (
            self.spec_ngram
            and mixed_prefill_tokens > 0
            and hasattr(runner, "verify_spec")
        )
        if self.spec_ngram and not self._spec_on:
            log.warning(
                "spec_ngram requested but unavailable "
                "(runner verify_spec=%s, mixed_prefill_tokens=%d); disabled",
                hasattr(runner, "verify_spec"), mixed_prefill_tokens,
            )
        # tree speculation: extra candidate branches per sequence ride the
        # same verify dispatch as independent segments on forked page
        # tables (trunk KV ref-shared); 1 = linear-K, the PR 8 contract
        self.spec_branches = max(1, int(spec_branches))
        # device-resident n-gram proposal: auto-on when the runner carries
        # the draft_step ring (ModelRunner jitted gather / SimRunner numpy
        # twin); the host scan remains as fallback and for A/Bs
        if spec_device_draft is None:
            spec_device_draft = hasattr(runner, "draft_step")
        self._spec_device_draft = (
            bool(spec_device_draft) and hasattr(runner, "draft_step")
        )
        self._draft_slots: Dict[str, int] = {}  # rid -> history-ring slot
        self._draft_free: List[int] = []
        self._draft_synced: Dict[str, int] = {}  # rid -> tokens mirrored
        self._draft_D = 0  # per-iteration append capacity (ring bucket)
        if self._spec_on and self._spec_device_draft:
            # allocate + WARM the ring at construction: the draft jit's
            # compile must land before the sanitizer's recompile tripwire
            # freezes the per-family variant counts (warmup_steps)
            self._draft_D = runner.ensure_draft_ring(max_batch, self.spec_k)
            self._draft_free = list(range(max_batch))
        # cumulative counters for goodput extras["spec"] / fleet digests
        self.spec_stats = {
            "drafted": 0, "accepted": 0, "rejected": 0,
            "verify_rows": 0, "verify_iters": 0, "spec_emitted": 0,
            "tree_rows": 0, "tree_switches": 0,
        }
        # The scheduler caps a mixed plan at max_batch decode rows +
        # mixed_prefill_tokens chunk tokens, so registering that exact sum
        # as a ragged T bucket makes the token budget BE the compile
        # bucket: a full mixed iteration compiles (and reuses) one ragged
        # variant instead of rounding up to the next power of two.
        if hasattr(runner, "ensure_ragged_bucket"):
            runner.ensure_ragged_bucket(mixed_prefill_tokens + max_batch)
        # planner retune ceilings: the ragged bucket registered above and
        # the draft ring sized below are compile-time commitments — a
        # live retune (engine.retune) may move knobs DOWN and back up to
        # these init values, never past them (a new compile family on the
        # warm path is exactly what the recompile tripwire forbids)
        self._mixed_tokens_init = int(mixed_prefill_tokens)
        self._spec_k_init = self.spec_k
        self.retunes = 0
        self.idle_sleep_s = idle_sleep_s
        self._inbox: thread_queue.Queue = thread_queue.Queue()
        self._streams: Dict[str, tuple[asyncio.Queue, asyncio.AbstractEventLoop]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._step_counter = 0
        self.fpm_history: List[ForwardPassMetrics] = []
        self._fpm_listeners: List[Any] = []
        self._kv_listeners: List[Any] = []
        self._phase_listeners: List[Any] = []
        # always-on iteration flight recorder (runtime/flight_recorder.py);
        # recorder_size=0 builds the disabled no-op variant for A/Bs
        self.recorder = FlightRecorder(
            recorder_size,
            anomaly_k=anomaly_k,
            anomaly_dump_dir=anomaly_dump_dir,
            anomaly_dump_last_n=anomaly_dump_last_n,
            anomaly_profile_ms=anomaly_profile_ms,
        )
        self._rec_prev_charged = 0  # runner packed_tokens_charged watermark
        # sick peers for cross-worker pulls: instance -> retry-after time
        self._remote_fetch_backoff: Dict[int, float] = {}
        # disaggregation state
        self._parked: Dict[str, tuple] = {}  # rid -> (Sequence, deadline)
        self._spec_sampling_warned: set = set()
        self._kv_pending: List[Sequence] = []  # disagg-decode awaiting space
        self.parked_ttl_s = 60.0
        self._embed_pending: List[tuple] = []  # (tokens, future, loop)
        # guided decoding: tokenizer-lifted constraint compile cache
        self.tokenizer_spec = tokenizer_spec
        self._guided_lifter = None
        self._guided_cache: Dict[str, Any] = {}
        self._guided_lock = threading.Lock()
        self._lifter_lock = threading.Lock()  # one-time TokenLifter build
        # runtime sanitizer: off unless asked (arg or DYN_SAN env). The
        # import is local so mocker processes that never arm it pay one
        # cheap module load at most.
        from dynamo_tpu.runtime.sanitizer import Sanitizer, env_enabled

        if sanitizer is not None:
            self.sanitizer = sanitizer
        elif sanitize or (sanitize is None and env_enabled()):
            self.sanitizer = Sanitizer()
        else:
            self.sanitizer = None
        if self.sanitizer is not None:
            san = self.sanitizer
            self._guided_lock = san.wrap_lock(
                self._guided_lock, "engine.guided_cache"
            )
            self._lifter_lock = san.wrap_lock(
                self._lifter_lock, "engine.lifter"
            )
            if hasattr(runner, "attach_sanitizer"):
                runner.attach_sanitizer(san)
        # called (from the step thread) on unrecoverable engine failure
        # (multi-host GroupBroken): the worker wires it to process exit
        self._fatal_cb = None
        # RL admin surface (reference lib/rl role): pause gates NEW
        # admissions during weight refreshes; weights_version counts
        # successful reloads
        self.paused = False
        self.weights_version = 0

    async def update_weights(self, orbax_path: str) -> int:
        """Swap serving weights from an orbax snapshot on the STEP thread
        (never racing an in-flight jit dispatch). Returns the new
        weights_version. Pause first for a clean cut between rollouts —
        running sequences otherwise continue on the new weights."""
        self.start()
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("reload_weights", (orbax_path, fut, loop)))
        return await fut

    def on_fatal(self, cb) -> None:
        self._fatal_cb = cb

    def retune(self, *, mixed_prefill_tokens: Optional[int] = None,
               mixed_prefill_seqs: Optional[int] = None,
               spec_k: Optional[int] = None) -> Dict[str, int]:
        """Planner actuation surface: adjust the co-scheduling knobs of a
        LIVE engine. Each knob is an int the step thread reads fresh
        every iteration (plain attribute stores are atomic under the
        GIL), so no pause is needed. Up-retunes are clamped to the
        compile-time commitments made at construction: the ragged bucket
        registered for `mixed_prefill_tokens + max_batch` and the draft
        ring sized for the initial K — exceeding either would mint a new
        compile family on the warm path. A DOWNWARD K retune on a
        device-draft runner re-keys the draft jit (bounded: at most
        init-K variants ever exist); strict-sanitizer deployments that
        retune K should pre-warm the alternate Ks. Returns the values
        actually in effect (callers journal these, not what they asked
        for)."""
        sched = self.scheduler
        if mixed_prefill_tokens is not None:
            cap = (self._mixed_tokens_init
                   if hasattr(self.runner, "ensure_ragged_bucket")
                   else max(self._mixed_tokens_init, mixed_prefill_tokens))
            sched.mixed_prefill_tokens = max(0, min(int(mixed_prefill_tokens),
                                                    cap))
        if mixed_prefill_seqs is not None:
            sched.mixed_prefill_seqs = max(1, int(mixed_prefill_seqs))
        if spec_k is not None:
            cap = (self._spec_k_init if self._spec_device_draft
                   else max(self._spec_k_init, int(spec_k)))
            self.spec_k = max(1, min(int(spec_k), cap))
        self.retunes += 1
        return {
            "mixed_prefill_tokens": sched.mixed_prefill_tokens,
            "mixed_prefill_seqs": sched.mixed_prefill_seqs,
            "spec_k": self.spec_k,
        }

    def _fail_everything(self, message: str) -> None:
        """Terminate every active/waiting/pending sequence with an error
        item (clients see a proper stream end and can migrate)."""
        seqs = list(self.scheduler.active) + list(self.scheduler.waiting)
        seqs += [s for s in self._kv_pending]
        for seq in seqs:
            try:
                self.scheduler.abort(seq.request_id)
            except Exception:
                # fail-everything must visit EVERY sequence even when one
                # abort races its normal finish; note it, keep going
                log.debug("abort of %s during fail-everything raced",
                          seq.request_id, exc_info=True)
            try:
                self._emit_item(seq, {
                    "finish_reason": "error", "error": message,
                    "token_ids": [],
                })
            except Exception:
                log.debug("error emit to %s failed during fail-everything "
                          "(stream already gone)", seq.request_id,
                          exc_info=True)

    # -- guided decoding ---------------------------------------------------
    def _compile_guided(self, spec: Dict[str, Any]):
        """Wire spec → GuidedMatcher (cached per spec+engine). Runs in an
        executor (DFA compilation for a big schema can take ~100ms).
        Double-checked locking: the lock only guards cache lookups and
        the insert — DFA compilation and the per-vocab lift happen
        OUTSIDE it, so one slow schema never serializes every concurrent
        guided request. A racing build of the same spec keeps the first
        inserted matcher (both are equivalent; ours is dropped). Only the
        TokenLifter (one per engine, the truly expensive vocab scan) is
        built under its own lock exactly once."""
        import json as _json

        key = _json.dumps(spec, sort_keys=True)
        with self._guided_lock:
            hit = self._guided_cache.get(key)
            if hit is not None:
                return hit
        from dynamo_tpu.guided import compile_regex, compile_structural

        kind = spec.get("kind")
        if kind == "regex":
            dfa = compile_regex(spec["pattern"])
        elif kind == "structural":
            dfa = compile_structural(spec)
        else:
            raise ValueError(f"unknown guided kind {kind!r}")
        matcher = self._get_lifter().lift(dfa)
        with self._guided_lock:
            hit = self._guided_cache.get(key)
            if hit is not None:
                return hit  # racer inserted first; equivalent matcher
            # small cap: each matcher holds up to _ROW_CACHE_MAX full-vocab
            # rows, so this bounds worker memory at tens of MB, not GB
            while len(self._guided_cache) >= 32:
                self._guided_cache.pop(next(iter(self._guided_cache)))
            self._guided_cache[key] = matcher
            return matcher

    def _get_lifter(self):
        lifter = self._guided_lifter
        if lifter is not None:
            return lifter
        with self._lifter_lock:
            if self._guided_lifter is None:
                from dynamo_tpu.frontend.tokenizer import load_tokenizer
                from dynamo_tpu.guided.token_mask import TokenLifter

                cfg = getattr(self.runner, "config", None)
                vocab = (
                    cfg.vocab_size if cfg is not None else self.runner.vocab_size
                )
                self._guided_lifter = TokenLifter.for_tokenizer(
                    load_tokenizer(self.tokenizer_spec), vocab,
                )
            return self._guided_lifter

    def _guided_mask(self, seq: Sequence) -> Optional[np.ndarray]:
        """Sampling mask for a constrained sequence. An all-False row (no
        token in this vocab can extend the constraint — possible when the
        tokenizer lacks a needed byte) degrades to force-EOS so the
        sequence stops instead of emitting garbage."""
        m = seq.guided_m
        if m is None:
            return None
        mask = m.allowed(seq.guided_s)
        if not mask.any():
            log.warning(
                "request %s: no token can extend the constraint from state "
                "%d — forcing EOS", seq.request_id, seq.guided_s,
            )
            if 0 <= m.lifter.eos_id < len(mask):
                mask = mask.copy()
                mask[m.lifter.eos_id] = True
        return mask

    def _guided_device_plan(self, seqs: List[Sequence]):
        """Device-resident guided plan for a fused multi-step dispatch:
        (tables, row_entries, pending) for the runner's _guided_op, or
        None when ANY constrained row's schema exceeds the device-table
        cell budget — the whole batch then keeps the host io_callback
        mask_fn (guided/device_table.py; a mixed device/host batch would
        need a second masking path in the loop for no warm-loop win).
        Tables compile once per matcher and ride the matcher's cache, so
        admission churn never rebuilds them; the runner keeps the staged
        combination device-resident across dispatches."""
        from dynamo_tpu.guided.device_table import build_device_table

        tables: List[Any] = []
        index: Dict[int, int] = {}
        rows: List[Any] = [None] * len(seqs)
        for i, s in enumerate(seqs):
            m = s.guided_m
            if m is None:
                continue
            tab = getattr(m, "_device_table", None)
            if tab is None:
                tab = build_device_table(m)
                if tab is None:
                    tab = _OVER_BUDGET
                    log.warning(
                        "guided schema exceeds the device DFA table "
                        "budget (DYN_GUIDED_DEVICE_MAX_ELEMS) — batches "
                        "containing it keep the host mask callback",
                    )
                m._device_table = tab  # matcher-lifetime cache
            if tab is _OVER_BUDGET:
                return None
            ti = index.get(tab.uid)
            if ti is None:
                ti = len(tables)
                index[tab.uid] = ti
                tables.append(tab)
            rows[i] = (ti, int(s.guided_s))
        if not tables:
            return None
        return (tables, rows, False)

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, name="engine-step", daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        if self.prefetch is not None:
            self.prefetch.stop()
        if self.sanitizer is not None:
            live = (len(self.scheduler.active) + len(self.scheduler.waiting)
                    + len(self._kv_pending))
            self.sanitizer.audit_pool(self.pool, live_seqs=live)

    def _san_scope(self, where: str):
        """Transfer-guard scope for a steady-state dispatch (no-op
        nullcontext when the sanitizer is off)."""
        san = self.sanitizer
        if san is None:
            return contextlib.nullcontext()
        return san.transfer_scope(where)

    def on_fpm(self, cb) -> None:
        """cb(ForwardPassMetrics) from the step thread."""
        self._fpm_listeners.append(cb)

    def on_kv_event(self, cb) -> None:
        """cb(List[KvEvent]) from the step thread."""
        self._kv_listeners.append(cb)

    def on_phases(self, cb) -> None:
        """cb(phases: Dict[str, float]) from the step thread, once per
        finished request (worker_common feeds /metrics histograms)."""
        self._phase_listeners.append(cb)

    # -- AsyncEngine protocol ----------------------------------------------
    async def generate(self, request: Dict[str, Any], context: Context) -> AsyncIterator[Any]:
        self.start()
        loop = asyncio.get_running_loop()
        out: asyncio.Queue = asyncio.Queue()
        rid = context.id
        self._streams[rid] = (out, loop)

        if self.paused:
            yield {
                "finish_reason": "error",
                "error": "worker paused (weight update in progress)",
                "token_ids": [],
            }
            self._streams.pop(rid, None)
            return
        annotations = request.get("annotations") or {}
        if annotations.get("kind") == "embedding":
            fut: asyncio.Future = loop.create_future()
            self._inbox.put(
                ("embed", ([int(t) for t in request.get("token_ids") or [0]], fut, loop))
            )
            try:
                vec = await fut
                yield {"embedding": vec, "finish_reason": "stop", "token_ids": []}
            finally:
                self._streams.pop(rid, None)
            return

        seq = Sequence(
            request_id=rid,
            prompt=[int(t) for t in request.get("token_ids") or [0]],
            sampling=request.get("sampling") or {},
            stop=request.get("stop") or {},
            arrival=time.monotonic(),
            disagg=annotations.get("disagg"),
            kv_import=request.get("kv_import"),
            adapter=request.get("adapter"),
            guided=request.get("guided"),
            logit_bias=request.get("logit_bias"),
        )
        # latency spine: upstream hops (frontend, router) stamped their
        # locally-measured durations into ctx.metadata["phases"]; seed the
        # sequence's phase dict so the final item carries the whole spine.
        # Durations only — monotonic clocks don't compare across processes.
        upstream = context.metadata.get("phases")
        if isinstance(upstream, dict):
            seq.phases.update({
                k: float(v) for k, v in upstream.items()
                if isinstance(v, (int, float))
            })
        # causal trace: remember the route span this request arrived
        # under; the step thread reconstructs the worker's phase spans
        # from the spine at finish (see _emit_worker_spans)
        tp = context.metadata.get("traceparent")
        if isinstance(tp, str):
            seq.tp = tp
        if context.metadata.get("migration_attempt"):
            seq.phases["migration_attempts"] = float(
                context.metadata["migration_attempt"])
        # n>1 sampling: fork-on-branch after prefill (the trunk KV is
        # shared copy-on-write, so n choices cost one prefill). Disagg
        # roles stream exactly one completion per worker — no fan-out.
        if seq.disagg is None:
            seq.n_branches = max(1, min(16, int(seq.sampling.get("n") or 1)))
        if seq.logit_bias and (
            getattr(self.runner, "has_draft", False)
            or getattr(self.runner, "pp", False)
            or not getattr(self.runner, "supports_logit_bias", False)
        ):
            # spec-decode verify can't honor a biased target distribution,
            # the PP loop has no bias operand, and sim runners have no
            # bias plumbing — reject up front rather than silently sample
            # the unbiased distribution (a dropped ban is a safety bug)
            yield {
                "finish_reason": "error",
                "error": "logit_bias is unsupported on this worker",
                "token_ids": [],
            }
            self._streams.pop(rid, None)
            return
        if seq.guided and getattr(self.runner, "has_draft", False):
            # speculative verify can't honor per-token masks; silently
            # dropping the constraint would hand back schema-invalid output
            # with finish_reason "stop" — reject up front instead
            yield {
                "finish_reason": "error",
                "error": "guided decoding is unsupported on a "
                         "speculative-decoding worker",
                "token_ids": [],
            }
            self._streams.pop(rid, None)
            return
        if seq.guided:
            try:
                seq.guided_m = await loop.run_in_executor(
                    None, self._compile_guided, seq.guided
                )
                seq.guided_s = seq.guided_m.start
                # disagg decode continuation: the prefill worker already
                # generated the trailing N prompt tokens under this
                # constraint — replay them so the DFA state matches
                n_adv = int(request.get("guided_advanced") or 0)
                for t in seq.prompt[len(seq.prompt) - n_adv:] if n_adv else []:
                    seq.guided_s = seq.guided_m.advance(seq.guided_s, int(t))
            except Exception as e:
                yield {
                    "finish_reason": "error",
                    "error": f"guided decoding spec rejected: {e}",
                    "token_ids": [],
                }
                self._streams.pop(rid, None)
                return
        # reject prompts that can NEVER be admitted (more pages than the
        # pool/per-seq cap) — without this the sequence waits forever and
        # head-of-line-blocks every request behind it
        PS = self.pool.page_size
        cap_tokens = min(self.scheduler.max_seq_pages, self.pool.num_pages) * PS
        if self.scheduler.max_seq_tokens:
            # the model context also bounds the PROMPT: prefilling past
            # the rope-valid range yields garbage logits, not an error
            cap_tokens = min(cap_tokens, self.scheduler.max_seq_tokens)
        if len(seq.prompt) + 1 > cap_tokens:
            yield {
                "finish_reason": "error",
                "error": (
                    f"prompt of {len(seq.prompt)} tokens exceeds this "
                    f"worker's KV capacity ({cap_tokens - 1} tokens)"
                ),
                "token_ids": [],
            }
            self._streams.pop(rid, None)
            return
        mm = request.get("mm")
        if mm:
            import numpy as np

            from dynamo_tpu.tokens.hashing import mm_content_seed

            arr = np.frombuffer(mm["data"], dtype=np.dtype(mm["dtype"])).reshape(mm["shape"])
            seq.mm_embeds = arr  # [n_img_tokens, E]
            seq.mm_positions = [int(p) for p in mm["positions"]]
            seq.mm_seed = mm_content_seed(mm["data"])
        if seq.adapter:
            try:
                seq.adapter_idx = self.runner.adapter_slot(seq.adapter)
            except (KeyError, AttributeError):
                yield {
                    "finish_reason": "error",
                    "error": f"unknown LoRA adapter {seq.adapter!r}",
                    "token_ids": [],
                }
                self._streams.pop(rid, None)
                return
        remote = request.get("kv_remote_host")
        if (remote and self.host_pool is not None
                and self.remote_kv_fetch is not None):
            # pull the peer's lower-tier blocks into the LOCAL host tier
            # before admission; the inbox is FIFO, so the import lands
            # before the scheduler sees the request
            await self._pull_remote_host(remote)
        if seq.disagg == "decode" and seq.kv_import is not None:
            self._inbox.put(("add_kv", seq))
        else:
            self._inbox.put(("add", seq))
        finished = False
        n_done = 0
        try:
            while True:
                if context.is_stopped:
                    return
                get = asyncio.create_task(out.get())
                stop_wait = asyncio.create_task(context.wait_stopped())
                done, pending = await asyncio.wait(
                    {get, stop_wait}, return_when=asyncio.FIRST_COMPLETED
                )
                for t in pending:
                    t.cancel()
                if get not in done:
                    return
                item = get.result()
                yield item
                if item.get("finish_reason"):
                    # a branched request streams one finish per choice;
                    # the stream ends when every branch has finished
                    n_done += 1
                    if n_done >= seq.n_branches:
                        finished = True
                        return
        finally:
            # runs on normal end, cancel, AND consumer break/close
            self._streams.pop(rid, None)
            # GIL-atomic discard; without it the warned-id set grows
            # unbounded on a long-lived spec-decode worker (ADVICE r3)
            self._spec_sampling_warned.discard(rid)
            if not finished:
                self._inbox.put(("abort", rid))

    async def _pull_remote_host(self, hint: Dict[str, Any]) -> None:
        """Best-effort remote-G2 pull (reference onboarding session
        search→pull, lib/kvbm-engine/docs/architecture.md). Failures fall
        back to recompute — never block admission on a sick peer."""
        hashes = [int(h) for h in hint.get("hashes") or []]
        parents = list(hint.get("parents") or [])
        if not hashes or len(parents) != len(hashes):
            return
        if (self.host_pool is not None
                and self.host_pool.match(hashes) >= len(hashes)):
            return  # already local (e.g. the prefetch hint pulled them)
        peer = int(hint.get("instance") or 0)
        now = time.monotonic()
        if now < self._remote_fetch_backoff.get(peer, 0.0):
            return  # peer recently failed: recompute instead of stalling
        t0 = time.perf_counter()
        try:
            # bounded timeout: a wedged peer must cost little — the
            # fallback (recompute) is always available (covers the
            # fetcher's up-to-2s discovery wait plus the transfer)
            payload = await asyncio.wait_for(
                self.remote_kv_fetch(hint), timeout=5.0
            )
        except Exception as e:
            self._remote_fetch_backoff[peer] = now + 30.0
            log.info("remote host-tier pull failed (%s); recomputing", e)
            return
        n = int((payload or {}).get("n") or 0)
        if n <= 0:
            return
        # the peer-pull leg of the transfer-cost model: remote blocks then
        # onboard from local G2, so the total remote cost the router sees
        # is ewma[remote] + ewma[host]. When the router tagged the hint
        # with the link class, the same sample also feeds the per-class
        # EWMA (remote_ici / remote_dcn) the link-aware selector prefers.
        elapsed = time.perf_counter() - t0
        self._note_onboard([], n, elapsed, tier="remote")
        link = hint.get("link")
        if link in ("ici", "dcn"):
            self._note_onboard([], n, elapsed, tier=f"remote_{link}")
        self._inbox.put(("host_import", (hashes[:n], parents[:n], payload)))

    async def prefetch_hint_async(self, hint: Dict[str, Any]) -> bool:
        """Router `kv_prefetch` hint ingress (worker_common endpoint):
        promote the hinted blocks up the KVBM ladder before the request
        itself arrives. A hint with a `remote` leg first pulls the peer's
        G2 blocks into the local host tier (the cross-worker machinery the
        admission path uses) — the inbox is FIFO, so the import lands
        before the promotion looks for it."""
        if self.prefetch is None:
            return False
        remote = hint.get("remote")
        if (remote and self.host_pool is not None
                and self.remote_kv_fetch is not None):
            await self._pull_remote_host(remote)
        self._inbox.put(("prefetch", hint))
        return True

    # -- step loop (dedicated thread) --------------------------------------
    def _loop(self) -> None:
        from dynamo_tpu.parallel.multihost import GroupBroken

        log.info("engine step loop started")
        while not self._stop.is_set():
            try:
                self._loop_once()
            except GroupBroken as e:
                # a multi-host group member died: limping along would hang
                # the next program's collectives — fail EVERY request
                # loudly and tell the process to exit so the supervisor
                # restarts the whole group (requests migrate to other
                # workers meanwhile). This catch sits OUTSIDE _loop_once
                # so inbox paths (exports, imports, embeds, evict hooks)
                # get the same fail-fast as the step itself.
                log.critical("worker group broken: %s — failing all "
                             "requests and shutting down", e)
                self._fail_everything(f"worker group broken: {e}")
                self._stop.set()
                cb = self._fatal_cb
                if cb is not None:
                    try:
                        cb()
                    except Exception:
                        # the callback is the worker's process-exit hook;
                        # its failure must not mask the fatal path itself
                        log.exception("fatal callback failed")
                break
        log.info("engine step loop stopped")

    def _loop_once(self) -> None:
        from dynamo_tpu.parallel.multihost import GroupBroken

        self._drain_inbox()  # dynlint: disable=DYN-J006 — embed readback (.tolist in _run_embeds) is a request-boundary transfer; sanitizer allowlists it as "embed_readback"
        self._propose_drafts()
        plan = self.scheduler.step_plan()
        if plan is None:
            if not self.scheduler.has_work():
                time.sleep(self.idle_sleep_s)
            return
        t0 = time.monotonic()
        t_start, ts_wall = t0, time.time()
        # plan-composition fields for this iteration's flight record;
        # branches fill in what they actually served
        rinfo = {"decode_seqs": 0, "decode_steps": 0, "n_chunks": 0,
                 "chunk_tokens": 0, "fused": False, "ragged": False,
                 "spec_rows": 0, "spec_drafted": 0, "spec_emitted": 0}
        if isinstance(plan, MixedPlan):
            _dseqs = plan.decode.seqs
        elif isinstance(plan, DecodePlan):
            _dseqs = plan.seqs
        else:
            _dseqs = []
        rinfo["guided_rows"] = sum(
            1 for s in _dseqs if s.guided_m is not None
        )
        decode_done = False
        try:
            if isinstance(plan, PrefillPlan):
                self._run_prefill(plan)
                kind, n_tok = "prefill", len(plan.chunk)
                rinfo.update(n_chunks=1, chunk_tokens=len(plan.chunk))
            elif isinstance(plan, MixedPlan):
                spec = any(s.spec_draft for s in plan.decode.seqs)
                if spec and self._mixed_fusible(plan):
                    # verify rows + packed prefill chunks share ONE ragged
                    # flat-token dispatch (the tentpole path)
                    res = self._run_spec_verify(plan.decode, plan.prefills)
                    if res is None:
                        spec = False  # drafts shed; plain paths below
                    else:
                        chunk_logits, sinfo = res
                        served = plan.prefills[:len(chunk_logits)]
                        rinfo.update(
                            decode_seqs=len(plan.decode.seqs),
                            decode_steps=1,
                            n_chunks=len(served),
                            chunk_tokens=sum(len(p.chunk) for p in served),
                            fused=True, ragged=True, **sinfo,
                        )
                        decode_done = True
                        self._finish_packed_prefills(served, chunk_logits)
                        kind = "mixed"
                        n_tok = (len(plan.decode.seqs) + sinfo["spec_drafted"]
                                 + sum(len(p.chunk) for p in served))
                elif spec:
                    # two-dispatch split (cpu / non-fused runners): the
                    # verify dispatch serves the decode half, the packed
                    # prefill path serves the chunks
                    res = self._run_spec_verify(plan.decode, [])
                    if res is None:
                        spec = False
                    else:
                        _, sinfo = res
                        decode_done = True
                        t1 = time.monotonic()
                        self._publish_fpm(
                            "decode", t1 - t0, len(plan.decode.seqs)
                        )
                        self._run_prefills(plan.prefills)
                        kind = "prefill"
                        n_tok = sum(len(p.chunk) for p in plan.prefills)
                        t0 = t1
                        rinfo.update(
                            decode_seqs=len(plan.decode.seqs),
                            decode_steps=1,
                            n_chunks=len(plan.prefills),
                            chunk_tokens=n_tok, **sinfo,
                        )
                if spec:
                    pass  # served above
                elif self._mixed_fusible(plan):
                    chunk_logits = self._run_mixed_dispatch(plan)
                    served = plan.prefills[:len(chunk_logits)]
                    rinfo.update(
                        decode_seqs=len(plan.decode.seqs),
                        decode_steps=plan.decode.n_steps,
                        n_chunks=len(served),
                        chunk_tokens=sum(len(p.chunk) for p in served),
                        fused=True,
                        # the packed multi-chunk program is the ragged
                        # flat-token path; single-chunk fused rides the
                        # padded decode_multi_with_prefill fallback
                        ragged=len(served) > 1,
                    )
                    # decode tokens are emitted: from here on a failure
                    # (e.g. in a chunk's sampling extras) must only
                    # fail the prefill sequences
                    decode_done = True
                    self._finish_packed_prefills(plan.prefills, chunk_logits)
                    # one dispatch ran both halves — a per-kind wall split
                    # doesn't exist; observers ignore the mixed kind
                    kind = "mixed"
                    n_tok = (len(plan.decode.seqs) * plan.decode.n_steps
                             + sum(len(p.chunk) for p in plan.prefills))
                else:
                    # decode first: ITL never waits behind prompt
                    # processing. Publish the halves as separate FPM
                    # events so observers fitting per-kind step-time
                    # models keep clean samples.
                    self._run_decode(plan.decode)
                    decode_done = True
                    t1 = time.monotonic()
                    self._publish_fpm(
                        "decode", t1 - t0, len(plan.decode.seqs)
                    )
                    self._run_prefills(plan.prefills)
                    kind = "prefill"
                    n_tok = sum(len(p.chunk) for p in plan.prefills)
                    t0 = t1
                    rinfo.update(
                        decode_seqs=len(plan.decode.seqs),
                        decode_steps=plan.decode.n_steps,
                        n_chunks=len(plan.prefills),
                        chunk_tokens=n_tok,
                    )
            else:
                res = None
                if any(s.spec_draft for s in plan.seqs):
                    res = self._run_spec_verify(plan, [])
                if res is not None:
                    _, sinfo = res
                    kind = "decode"
                    n_tok = len(plan.seqs) + sinfo["spec_drafted"]
                    rinfo.update(decode_seqs=len(plan.seqs),
                                 decode_steps=1, **sinfo)
                else:
                    self._run_decode(plan)
                    kind, n_tok = "decode", len(plan.seqs)
                    rinfo.update(decode_seqs=len(plan.seqs),
                                 decode_steps=plan.n_steps)
        except GroupBroken:
            raise  # unrecoverable: handled by _loop's fail-fast
        except Exception:
            # one bad step (malformed import, shape bug, OOM) must fail
            # ITS sequences, never kill the step thread: a dead loop
            # strands every queued request with no error and no stream
            # end (the failure surfaces only as a distributed hang).
            # For a mixed step whose decode half already completed, only
            # the prefill sequence is at risk — its decode batch has
            # emitted this iteration's tokens and stays healthy.
            if isinstance(plan, PrefillPlan):
                seqs = [plan.seq]
            elif isinstance(plan, MixedPlan):
                pseqs = [p.seq for p in plan.prefills]
                seqs = pseqs if decode_done else (
                    list(plan.decode.seqs) + pseqs
                )
            else:
                seqs = plan.seqs
            log.exception(
                "engine step failed; erroring %d sequence(s)", len(seqs)
            )
            for seq in seqs:
                try:
                    self._emit(seq, [], "error")
                    self.scheduler.abort(seq.request_id)
                except Exception:
                    log.exception("failed to fail sequence %s", seq.request_id)
            self._recover_poisoned_pools()
            return
        if self.sanitizer is not None:
            # arms the transfer guard + freezes the compiled-family
            # baseline after warmup; a new variant past that is a leak
            self.sanitizer.note_step(self.runner)
        self._publish_fpm(kind, time.monotonic() - t0, n_tok)
        self._publish_kv_events()
        self._record_iteration(
            ts_wall, time.monotonic() - t_start,
            "mixed" if isinstance(plan, MixedPlan) else kind, rinfo,
        )

    def _record_iteration(self, ts: float, wall: float, kind: str,
                          rinfo: Dict[str, Any]) -> None:
        """Assemble and append this iteration's flight record (step
        thread; cheap field reads only — see DYN-R004)."""
        rec = self.recorder
        if not rec.enabled:
            return
        st = self.scheduler.stats
        g2 = g3 = 0
        if self.host_pool is not None:
            g2 = len(self.host_pool.host)
            if self.host_pool.disk is not None:
                g3 = len(self.host_pool.disk)
        hits = self.prefetch.stats["hits"] if self.prefetch is not None else 0
        variants = calls = 0
        fams = getattr(self.runner, "_families", None)
        if fams:
            for fam in fams.values():
                variants += fam.variants
                calls += fam.calls
        charged = rinfo["chunk_tokens"]
        rstats = getattr(self.runner, "stats", None)
        if isinstance(rstats, dict) and "packed_tokens_charged" in rstats:
            # SimRunner keeps an honest cumulative padded-charge counter;
            # its per-iteration delta is the real charged-token figure
            cum = int(rstats.get("packed_tokens_charged") or 0)
            delta = cum - self._rec_prev_charged
            self._rec_prev_charged = cum
            if delta > 0:
                charged = delta
        trace_ids: List[str] = []
        if tracing.enabled():
            # bounded join key: the traces this iteration served (string
            # parses over <=8 cached traceparents — step-thread cheap)
            for s in self.scheduler.active[:8]:
                pctx = tracing.parse_traceparent(s.tp)
                if pctx is not None and pctx.trace_id not in trace_ids:
                    trace_ids.append(pctx.trace_id)
        rec.append(IterationRecord(
            seq=self._step_counter,
            ts=ts,
            wall_s=wall,
            kind=kind,
            decode_seqs=rinfo["decode_seqs"],
            decode_steps=rinfo["decode_steps"],
            n_chunks=rinfo["n_chunks"],
            chunk_tokens=rinfo["chunk_tokens"],
            charged_tokens=charged,
            ragged=rinfo["ragged"],
            fused=rinfo["fused"],
            n_waiting=st.n_waiting,
            n_running=st.n_running,
            kv_usage=st.kv_usage,
            g2_blocks=g2,
            g3_blocks=g3,
            prefetch_hits=hits,
            compile_variants=variants,
            compile_calls=calls,
            accepted_per_step=(
                rinfo.get("spec_emitted", 0) / rinfo["spec_rows"]
                if rinfo.get("spec_rows") else 0.0
            ),
            guided_rows=rinfo.get("guided_rows", 0),
            tree_hit_blocks=self.pool.match_hit_blocks,
            forks=self.pool.forks,
            trace_ids=trace_ids,
        ))

    def _recover_poisoned_pools(self) -> None:
        """A step that fails AFTER its jit dispatch consumed the donated
        KV pools leaves them deleted — every later step would raise
        'Array has been deleted' and the worker degrades into an error
        loop while still registered healthy. Detect that, rebuild zeroed
        pools, and fail everything whose device KV was lost (waiting
        sequences keep: they own no pages yet and prefill from scratch).
        Host/disk tiers keep their copies — those bytes are real."""
        if not getattr(self.runner, "pools_deleted", lambda: False)():
            return
        log.error("KV pools were consumed by a failed step; rebuilding "
                  "(all device-cached blocks lost)")
        # host/disk tiers keep their copies (those bytes are real) and
        # pending disagg imports stay admittable into the fresh pools
        self._flush_kv_state("error", drop_pending=False, clear_tiers=False)

    def _flush_kv_state(self, error_message: str, *, drop_pending: bool,
                        clear_tiers: bool) -> None:
        """Fail active sequences, release parked entries, zero the device
        pools + prefix cache; optionally drop queued disagg imports and
        flush the lower KV tiers (weight-update policy invalidation)."""
        for seq in list(self.scheduler.active):
            try:
                if error_message == "error":
                    self._emit(seq, [], "error")
                else:
                    self._emit_item(seq, {
                        "finish_reason": "error", "error": error_message,
                        "token_ids": [],
                    })
                self.scheduler.abort(seq.request_id)
            except Exception:
                log.exception("failed to fail sequence %s", seq.request_id)
        for rid, (seq, _) in list(self._parked.items()):
            try:
                self._parked.pop(rid, None)
                self.scheduler.release_parked(seq)
            except Exception:
                log.exception("failed to release parked %s", rid)
        if drop_pending:
            pending, self._kv_pending = self._kv_pending, []
            for seq in pending:
                try:
                    self._emit_item(seq, {
                        "finish_reason": "error", "error": error_message,
                        "token_ids": [],
                    })
                except Exception:
                    log.debug("error emit to pending %s failed (stream "
                              "already gone)", seq.request_id, exc_info=True)
        self.runner.reset_kv_pools()
        self.pool.reset()
        if clear_tiers and self.host_pool is not None:
            self.host_pool.clear()
        self._publish_kv_events()

    def _drain_inbox(self) -> None:
        while True:
            try:
                op, arg = self._inbox.get_nowait()
            except thread_queue.Empty:
                break
            if op == "add":
                self.scheduler.add(arg)
            elif op == "abort":
                self.scheduler.abort(arg)
                # forked branches live under derived ids; an abort of the
                # parent stream must tear them down too or their pages
                # leak until the (never-coming) finish
                for bid in [
                    s.request_id
                    for s in list(self.scheduler.active)
                    + list(self.scheduler.waiting)
                    if s.branch_of == arg
                ]:
                    self.scheduler.abort(bid)
                parked = self._parked.pop(arg, None)
                if parked is not None:
                    self.scheduler.release_parked(parked[0])
                self._kv_pending = [s for s in self._kv_pending if s.request_id != arg]
                # step-thread discard: the asyncio-side discard can race a
                # warn for a still-batched sequence (the abort lands after
                # the step that warned); this one runs on the warning
                # thread itself, after the sequence left the scheduler
                self._spec_sampling_warned.discard(arg)
            elif op == "add_kv":
                self._kv_pending.append(arg)
            elif op == "export":
                rid, fut, loop, discard = arg
                self._export_parked(rid, fut, loop, discard)
            elif op == "export_meta":
                rid, fut, loop = arg
                self._export_meta(rid, fut, loop)
            elif op == "export_chunk":
                rid, start, n, last, fut, loop = arg
                self._export_chunk(rid, start, n, last, fut, loop)
            elif op == "export_device":
                rid, fut, loop = arg
                self._export_parked_device(rid, fut, loop)
            elif op == "embed":
                self._embed_pending.append(arg)
            elif op == "host_export":
                hashes, fut, loop = arg
                self._host_export(hashes, fut, loop)
            elif op == "host_import":
                self._host_import(*arg)
            elif op == "prefetch":
                if self.prefetch is not None:
                    self.prefetch.on_hint(arg)
            elif op == "prefetch_disk":
                if self.prefetch is not None:
                    self.prefetch.on_disk_read(*arg)
            elif op == "prefetch_obj":
                if self.prefetch is not None:
                    self.prefetch.on_obj_read(*arg)
            elif op == "obj_event":
                h, parent = arg
                self._host_events.append(
                    KvEvent("store", [h], parent, tier="obj"))
            elif op == "reload_weights":
                path, fut, loop = arg
                try:
                    self.runner.reload_params(path)
                    # ALL cached KV was computed under the old policy:
                    # serving it against the new weights silently mixes
                    # policies (caught by the RL parity test)
                    self._flush_kv_state(
                        "weights updated mid-flight; retry",
                        drop_pending=True,  # queued disagg imports carry
                        # old-policy KV bytes — admitting them would mix
                        clear_tiers=True,
                    )
                    self.weights_version += 1
                    loop.call_soon_threadsafe(
                        _set_future, fut, self.weights_version
                    )
                except Exception as e:
                    log.exception("weight reload failed")
                    loop.call_soon_threadsafe(_set_future_exc, fut, e)
        self._admit_kv_pending()
        self._expire_parked()
        self._run_embeds()
        if self.prefetch is not None:
            self.prefetch.tick()

    def _kv_layout_mismatch(self, payload: Dict[str, Any]) -> Optional[str]:
        """Non-None when a host-staged payload can't be imported into the
        local pool: produced under a different pool layout version
        (mixed-version cluster) or a different page geometry (L, PS, Hk, D)
        — a peer serving a different model or page size. A differing TP
        degree is NOT a mismatch (dense full-head wire, see
        model_runner.kv_arrays_to_payload). Device payloads are
        same-process buffers and never re-sliced."""
        from dynamo_tpu.engine.model_runner import kv_payload_incompatible

        if payload.get("device"):
            return None
        page_shape = getattr(self.runner, "kv_page_shape", None)
        wire_dtype = getattr(self.runner, "kv_wire_dtype", None)
        parts = payload.get("chunks") or ([payload] if payload.get("data") else [])
        for p in parts:
            if not p.get("k"):
                continue
            if page_shape is not None:
                bad = kv_payload_incompatible(p, page_shape, wire_dtype)
            else:  # sim runners without pools: version check only
                from dynamo_tpu.engine.model_runner import KV_WIRE_LAYOUT_VERSION

                bad = (
                    None if p.get("layout") == KV_WIRE_LAYOUT_VERSION
                    else f"layout {p.get('layout')} != {KV_WIRE_LAYOUT_VERSION}"
                )
            if bad:
                return bad
        return None

    def _admit_kv_pending(self) -> None:
        """Disagg-decode sequences: admit + import transferred KV pages."""
        still: List[Sequence] = []
        for seq in self._kv_pending:
            bad = self._kv_layout_mismatch(seq.kv_import or {})
            if bad:
                # checked BEFORE admit_with_kv marks the prompt computed:
                # fall back to local prefill (recompute) — never error the
                # request for a peer's stale wire format, and never adopt
                # transposed bytes
                log.warning(
                    "P->D KV payload rejected (%s); recomputing %s locally",
                    bad, seq.request_id,
                )
                seq.kv_import = None
                self.scheduler.add(seq)
                continue
            try:
                self._admit_one_kv(seq, still)
            except Exception as admit_err:
                from dynamo_tpu.parallel.multihost import GroupBroken as _GB

                if isinstance(admit_err, _GB):
                    raise  # unrecoverable: _loop's fail-fast handles it
                # a malformed/corrupt transfer payload (bad shape metadata,
                # truncated bytes) must fail THIS request, not kill the
                # step thread — this runs from _drain_inbox, outside the
                # step-loop guard
                log.exception("KV import failed; erroring %s", seq.request_id)
                try:
                    self._emit(seq, [], "error")
                    self.scheduler.abort(seq.request_id)
                except Exception:
                    log.exception("failed to fail sequence %s", seq.request_id)
        self._kv_pending = still

    def _admit_one_kv(self, seq: Sequence, still: List[Sequence]) -> None:
        seq.tokens = list(seq.prompt)
        seq.n_prompt0 = len(seq.prompt)
        if not self.scheduler.admit_with_kv(seq):
            still.append(seq)
            return
        payload = seq.kv_import or {}
        seq.kv_import = None
        n_kv_pages = (len(seq.prompt) - 1 + self.pool.page_size - 1) // self.pool.page_size
        target = seq.pages[seq.n_shared_pages:n_kv_pages]
        if target and payload.get("device"):
            # colocated transfer: staged buffers are already on device
            self.runner.import_pages_device(
                target, seq.n_shared_pages, payload["k"], payload["v"]
            )
        elif target and payload.get("chunks"):
            # chunked host-staged transfer: each chunk covers global
            # pages [offset, offset+n); skip the prefix-cache-shared
            # span and scatter the rest
            ns = seq.n_shared_pages
            for ch in payload["chunks"]:
                off, n = int(ch.get("offset", 0)), int(ch["n_pages"])
                lo, hi = max(off, ns), min(off + n, n_kv_pages)
                if lo >= hi or not ch.get("data"):
                    continue
                self.runner.import_pages(seq.pages[lo:hi], lo - off, ch)
        elif target and payload.get("data"):
            self.runner.import_pages(target, seq.n_shared_pages, payload)
        if getattr(self.runner, "has_draft", False):
            # transferred KV covers the target model only; rebuild the
            # draft pools by (cheap) draft prefill — starting after the
            # prefix-cache-shared pages, whose draft KV the sequence
            # that populated them already wrote
            toks = seq.prompt[:-1]
            chunk = self.scheduler.chunk_size
            shared = seq.n_shared_pages * self.pool.page_size
            for start in range(shared, len(toks), chunk):
                self.runner.draft_prefill(
                    toks[start : start + chunk], start, seq.pages,
                    prior_len=start,
                )

    def _run_embeds(self) -> None:
        """Batch all pending embedding requests into one encoder pass."""
        if not self._embed_pending:
            return
        batch, self._embed_pending = self._embed_pending, []
        try:
            vecs = self.runner.embed([t for t, _, _ in batch])
            for i, (_, fut, loop) in enumerate(batch):
                loop.call_soon_threadsafe(_set_future, fut, vecs[i].tolist())
        except Exception as e:  # pragma: no cover
            log.exception("embed batch failed")
            for _, fut, loop in batch:
                loop.call_soon_threadsafe(_set_future_exc, fut, e)
            from dynamo_tpu.parallel.multihost import GroupBroken as _GB

            if isinstance(e, _GB):
                raise  # unrecoverable: _loop's fail-fast handles it

    def _expire_parked(self) -> None:
        if not self._parked:
            return
        now = time.monotonic()
        for rid in [r for r, (s, dl) in self._parked.items() if dl < now]:
            seq, _ = self._parked.pop(rid)
            self.scheduler.release_parked(seq)

    def _export_parked_device(self, rid: str, fut, loop) -> None:
        """Colocated P→D: gather the parked pages into device staging
        buffers on THIS engine's step thread (the only thread allowed to
        touch this runner's pools — they are donated every step)."""
        entry = self._parked.pop(rid, None)
        if entry is None:
            loop.call_soon_threadsafe(_set_future, fut, None)
            return
        seq, _ = entry
        n_kv_pages = self._n_prompt_pages(seq)
        k, v = self.runner.export_pages_device(seq.pages[:n_kv_pages])
        self.scheduler.release_parked(seq)
        loop.call_soon_threadsafe(
            _set_future, fut,
            {"device": True, "k": k, "v": v, "n_pages": n_kv_pages},
        )

    async def export_parked_kv_device(self, request_id: str):
        """Device-resident parked-KV export (same-process decode engine
        imports the staged buffers without a host round trip)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("export_device", (request_id, fut, loop)))
        return await fut

    def _n_prompt_pages(self, seq) -> int:
        """Pages a parked prompt's KV occupies (export side). The import
        side deliberately uses one page less when the prompt's final token
        starts a fresh page (_admit_kv_pending: ceil((len-1)/ps)) — the
        decode step recomputes that token's KV as it generates."""
        return (len(seq.prompt) + self.pool.page_size - 1) // self.pool.page_size

    def _export_meta(self, rid: str, fut, loop) -> None:
        """Page count of a parked request (no pop — the stream export
        reads chunk by chunk while the request stays parked)."""
        entry = self._parked.get(rid)
        if entry is None:
            loop.call_soon_threadsafe(_set_future, fut, None)
            return
        seq, _ = entry
        loop.call_soon_threadsafe(_set_future, fut, self._n_prompt_pages(seq))

    def _export_chunk(self, rid: str, start: int, n: int, last: bool, fut, loop) -> None:
        """Export pages [start, start+n) of a parked request; `last` pops
        and releases. Runs on the step thread between steps, so each chunk
        read interleaves with decode work instead of one long pool read."""
        entry = self._parked.get(rid)
        if entry is None:
            loop.call_soon_threadsafe(_set_future, fut, None)
            return
        seq, _ = entry
        # an actively-consumed transfer must not expire between chunks: a
        # multi-GB pull interleaved with decode steps can legitimately
        # outlive the parked TTL, so each chunk read renews the lease
        self._parked[rid] = (seq, time.monotonic() + self.parked_ttl_s)
        payload = self.runner.export_pages(seq.pages[start : start + n])
        payload["offset"] = start
        # importers validate coverage against this before trusting the
        # stream (a truncated transfer must recompute, never half-import)
        payload["total_pages"] = self._n_prompt_pages(seq)
        if last:
            self._parked.pop(rid, None)
            self.scheduler.release_parked(seq)
        loop.call_soon_threadsafe(_set_future, fut, payload)

    def _export_parked(self, rid: str, fut, loop, discard: bool = False) -> None:
        entry = self._parked.pop(rid, None)
        if entry is None:
            loop.call_soon_threadsafe(fut.set_result, None)
            return
        seq, _ = entry
        payload = None
        if not discard:
            n_kv_pages = self._n_prompt_pages(seq)
            payload = self.runner.export_pages(seq.pages[:n_kv_pages])
        self.scheduler.release_parked(seq)
        loop.call_soon_threadsafe(fut.set_result, payload)

    def _mm_chunk(self, seq: Sequence, start: int, n: int):
        """Multimodal embeddings falling inside [start, start+n) of the
        prompt, re-based to chunk-local offsets (None if none do)."""
        if seq.mm_embeds is None:
            return None
        idx = [
            (i, p - start)
            for i, p in enumerate(seq.mm_positions)
            if start <= p < start + n
        ]
        if not idx:
            return None
        import numpy as np

        rows, offs = zip(*idx)
        return {"embeds": np.ascontiguousarray(seq.mm_embeds[list(rows)]),
                "offsets": list(offs)}

    def _run_prefill(self, plan: PrefillPlan) -> None:
        with annotate("engine.prefill", tokens=len(plan.chunk)):
            self._run_prefill_inner(plan)

    def _run_prefills(self, plans: List[PrefillPlan]) -> None:
        """Non-fused execution of a packed chunk set. Runners exposing
        `prefill_packed` (the mocker, whose step-time model charges one
        dispatch for the whole set) get all chunks in one call; others
        (PP, interpreter fallback) run the chunks sequentially —
        scheduling still packs, only the dispatch is serial."""
        packed = getattr(self.runner, "prefill_packed", None)
        if (packed is None or len(plans) <= 1
                or getattr(self.runner, "has_draft", False)
                or any(
                    self._mm_chunk(p.seq, p.start_pos, len(p.chunk))
                    is not None
                    for p in plans
                )):
            for plan in plans:
                self._run_prefill(plan)
            return
        with annotate("engine.prefill_packed", chunks=len(plans),
                      tokens=sum(len(p.chunk) for p in plans)):
            logits_rows = packed([
                {
                    "tokens": p.chunk,
                    "start": p.start_pos,
                    "table": p.seq.pages,
                    "prior": p.start_pos,
                    "adapter": p.seq.adapter_idx,
                }
                for p in plans
            ])
            for plan, lg in zip(plans, logits_rows):
                self.scheduler.complete_prefill(plan)
                self._finish_prefill(plan, lg)

    def _run_prefill_inner(self, plan: PrefillPlan) -> None:
        seq = plan.seq
        mm_chunk = self._mm_chunk(seq, plan.start_pos, len(plan.chunk))
        logits = self.runner.prefill(
            plan.chunk,
            plan.start_pos,
            seq.pages,
            prior_len=plan.start_pos,
            adapter=seq.adapter_idx,
            mm=mm_chunk,
        )
        if getattr(self.runner, "has_draft", False) and seq.disagg != "prefill":
            # keep the draft model's KV pools in lockstep so spec decode
            # can propose over the full context (skipped on disagg-prefill
            # workers: draft KV isn't exported — the decode worker rebuilds
            # it on admission)
            self.runner.draft_prefill(
                plan.chunk, plan.start_pos, seq.pages, prior_len=plan.start_pos,
                mm=mm_chunk,
            )
        self.scheduler.complete_prefill(plan)
        self._finish_prefill(plan, logits)

    def _finish_prefill(self, plan: PrefillPlan, logits) -> None:
        """Post-chunk bookkeeping shared by the standalone and fused mixed
        dispatch paths: sample the first token on the LAST chunk (guided
        mask / logprobs / penalties variants), then park (disagg) or start
        the sequence RUNNING."""
        seq = plan.seq
        if not plan.is_last_chunk:
            return
        bias1 = None
        if seq.logit_bias:
            rows = _batch_biases([seq], self.runner)
            if rows is not None:
                bias1 = rows[0]
        first_lp = None
        mask1 = self._guided_mask(seq)
        n_lp1 = _batch_logprobs([seq])
        if (n_lp1 >= 0 or _batch_penalties([seq])) and hasattr(
            self.runner, "sample_one_ex"
        ):
            kw1 = {"mask": mask1} if mask1 is not None else {}
            if bias1 is not None:
                kw1["bias"] = bias1
            token, first_lp = self.runner.sample_one_ex(
                logits, _sampling_params([seq]), self._next_step(),
                history=list(seq.tokens) if _batch_penalties([seq]) else None,
                n_logprobs=n_lp1, **kw1,
            )
        else:
            kw1 = {"mask": mask1} if mask1 is not None else {}
            if bias1 is not None:
                kw1["bias"] = bias1
            token = self.runner.sample_one(
                logits, _sampling_params([seq]), self._next_step(), **kw1,
            )
        # fork BEFORE the parent's DFA advance: each branch samples its
        # own first token from these logits under the same pre-advance
        # constraint state the parent's token was sampled under
        if (seq.n_branches > 1 and seq.branch_of is None
                and seq.disagg is None and not seq.branches_spawned):
            self._fork_branches(seq, logits, mask1, bias1)
        self._guided_advance(seq, token)
        if seq.disagg == "prefill":
            # disagg: first token + transfer handle; pages stay pinned for
            # the decode worker's pull (disagg-serving.md bootstrap model)
            self.scheduler.park(seq)
            self._parked[seq.request_id] = (
                seq, time.monotonic() + self.parked_ttl_s
            )
            extra = {}
            if first_lp is not None:
                extra["logprobs"] = [_first_lp_entry(first_lp, seq)]
            self._emit_item(
                seq,
                engine_output(
                    [token],
                    "prefill_complete",
                    kv_transfer={
                        "request_id": seq.request_id,
                        "prompt_len": len(seq.prompt),
                        "first_token": token,
                    },
                    **extra,
                ),
            )
            return
        reason = self.scheduler.complete_decode(seq, token, advance_computed=False)
        emitted = token if reason != "stop" else None
        lp_entries = None
        if first_lp is not None and emitted is not None:
            lp_entries = [_first_lp_entry(first_lp, seq)]
        self._emit(
            seq, [token] if emitted is not None else [], reason,
            logprobs=lp_entries,
        )

    def _fork_branches(self, seq: Sequence, logits, mask1, bias1) -> None:
        """Fan a just-prefilled sequence out into n_branches siblings.

        Each branch shares the parent's complete trunk pages by reference
        (copy-on-write: only the partial tail page is duplicated via the
        pool's copy_hook), inherits the pre-advance guided DFA state, and
        samples its own first token from the parent's prefill logits —
        one prefill pass serves n choices. A branch that can't get pages
        or a batch slot emits an indexed error item; the parent and the
        other branches are unaffected."""
        seq.branches_spawned = True  # a preempted parent must not re-fork
        PS = self.pool.page_size
        n_shared = seq.computed_len // PS
        for k in range(1, seq.n_branches):
            branch = Sequence(
                request_id=f"{seq.request_id}#b{k}",
                prompt=list(seq.prompt),
                sampling=dict(seq.sampling),
                stop=seq.stop,
                arrival=seq.arrival,
                adapter=seq.adapter,
                adapter_idx=seq.adapter_idx,
                logit_bias=seq.logit_bias,
                mm_embeds=seq.mm_embeds,
                mm_positions=seq.mm_positions,
                mm_seed=seq.mm_seed,
                guided=seq.guided,
                guided_m=seq.guided_m,
                guided_s=seq.guided_s,
                branch_of=seq.request_id,
                branch_index=k,
            )
            if branch.sampling.get("seed") is not None:
                # mirror the frontend fan-out's choice-seed derivation so
                # seeded non-greedy branches diverge deterministically
                branch.sampling["seed"] = int(branch.sampling["seed"]) + k
            try:
                pages = self.pool.fork_table(seq.pages, n_shared)
            except NoSpace:
                self._emit_item(branch, engine_output(
                    [], "error",
                    error="no KV pages free to fork this choice",
                ))
                continue
            if not self.scheduler.adopt_branch(branch, seq, pages):
                self._emit_item(branch, engine_output(
                    [], "error",
                    error="no batch slot free to fork this choice",
                ))
                continue
            kwb = {"mask": mask1} if mask1 is not None else {}
            if bias1 is not None:
                kwb["bias"] = bias1
            tok = self.runner.sample_one(
                logits, _sampling_params([branch]), self._next_step(), **kwb,
            )
            self._guided_advance(branch, tok)
            reason = self.scheduler.complete_decode(
                branch, tok, advance_computed=False
            )
            self._emit(branch, [tok] if reason != "stop" else [], reason)

    def _finish_packed_prefills(self, prefills, chunk_logits) -> None:
        """Bookkeeping for prefill chunks whose KV landed in a shared
        dispatch, with per-chunk isolation: one chunk's sampling extras
        failing must not error sibling prefills (or the already-emitted
        decode half)."""
        from dynamo_tpu.parallel.multihost import GroupBroken

        for pplan, lg in zip(prefills, chunk_logits):
            try:
                self.scheduler.complete_prefill(pplan)
                self._finish_prefill(pplan, lg)
            except GroupBroken:
                raise
            except Exception:
                log.exception(
                    "packed chunk bookkeeping failed; erroring %s",
                    pplan.seq.request_id,
                )
                try:
                    self._emit(pplan.seq, [], "error")
                    self.scheduler.abort(pplan.seq.request_id)
                except Exception:
                    log.exception("failed to fail sequence %s",
                                  pplan.seq.request_id)
                self._recover_poisoned_pools()

    # -- speculative decoding (n-gram drafting + ragged verify) -------------
    def _warn_spec_once(self, rid: str, what: str) -> None:
        """One-shot (per request) warning that speculation was degraded;
        the set is pruned when the request finishes or aborts, so a
        long-lived worker's memory stays bounded."""
        if rid in self._spec_sampling_warned:
            return
        self._spec_sampling_warned.add(rid)
        log.warning("request %s: %s", rid, what)

    def _propose_drafts(self) -> None:
        """Propose this iteration's draft tokens (step thread, before
        step_plan so the scheduler can charge them against the mixed
        pool). Speculation is opportunistic per iteration and per
        SEQUENCE: guided and logit-bias rows simply never draft — they
        ride the verify dispatch as single plain rows whose mask/bias
        plumb through verify_spec's always-present sampling operands —
        while free rows in the same batch keep drafting. Only
        logprobs/penalties still pause the whole batch: the verify
        program has no logprob report or penalty count table, so
        partial speculation would silently drop those extras for every
        row in the shared dispatch."""
        running = [
            s for s in self.scheduler.active if s.state == SeqState.RUNNING
        ]
        for s in running:
            s.spec_draft = []
            s.spec_tree = []
        if not self._spec_on or not running:
            return
        blocked = [
            s for s in running
            if _batch_logprobs([s]) >= 0 or _batch_penalties([s])
        ]
        if blocked:
            for s in blocked:
                self._warn_spec_once(
                    s.request_id,
                    "logprobs/penalties sampling is incompatible with "
                    "speculative verification — speculation paused while "
                    "this request is in the batch",
                )
            return
        oracle = getattr(self.runner, "spec_draft", None)
        tree_oracle = getattr(self.runner, "spec_draft_tree", None)
        free: List[Sequence] = []
        for s in running:
            if s.guided_m is not None or s.logit_bias:
                # per-sequence pause: this row stays a plain 1-token
                # verify row (masked/biased); siblings keep speculating
                self._warn_spec_once(
                    s.request_id,
                    "guided/bias row rides the verify dispatch without "
                    "drafting (per-sequence speculation pause)",
                )
                continue
            free.append(s)
        if self.spec_branches > 1:
            # tree mode keeps the host scan (branch enumeration needs
            # every suffix-match site, which the device ring's
            # single-winner gather doesn't surface)
            for s in free:
                tree = None
                if tree_oracle is not None:
                    tree = tree_oracle(
                        s.tokens[-1], s.computed_len,
                        self.spec_k, self.spec_branches,
                    )
                if tree is None:
                    tree = ngram_propose_tree(
                        s.tokens, self.spec_k, self.spec_branches
                    )
                if tree and tree[0]:
                    s.spec_draft = [int(t) for t in tree[0]]
                    # siblings clipped to the primary's length: the
                    # scheduler charged pages/segments for that shape
                    s.spec_tree = [
                        [int(t) for t in b[: len(tree[0])]]
                        for b in tree[1:] if b
                    ]
            return
        # linear K: an oracle (SimRunner A/B knob) answers first, per row
        # (it returns None when unset); rows it declines go through ONE
        # fused device-ring proposal when the runner carries the ring,
        # with the host suffix scan as the last fallback
        pending: List[Sequence] = []
        for s in free:
            draft = None
            if oracle is not None:
                draft = oracle(s.tokens[-1], s.computed_len, self.spec_k)
            if draft is None:
                pending.append(s)
            else:
                s.spec_draft = [int(t) for t in draft]
        device: Dict[str, List[int]] = {}
        if self._spec_device_draft and pending:
            device = self._device_draft(pending)
        for s in pending:
            draft = device.get(s.request_id)
            if draft is None:
                draft = ngram_propose(s.tokens, self.spec_k)
            s.spec_draft = [int(t) for t in draft] if draft else []

    def _device_draft(self, seqs: List[Sequence]) -> Dict[str, List[int]]:
        """One fused device proposal for every free speculating row:
        per-row token deltas append into the runner's history ring and
        the jitted suffix-match gather proposes k tokens per slot — the
        draft side of the warm loop touches the host exactly once (the
        [slots, k] proposal readback). Returns rid -> draft; rows that
        couldn't get a ring slot are absent (the host scan serves them).
        Bit-identical to ngram_draft.propose while the history fits the
        ring window (model_runner.DRAFT_RING_WINDOW)."""
        if not self._draft_free and not self._draft_slots:
            return {}  # ring was never allocated (disabled after init)
        live = {s.request_id for s in seqs}
        for rid in [r for r in self._draft_slots if r not in live]:
            # finished/preempted/now-guided rows hand their slot back;
            # a row that resumes simply resets into a fresh slot
            self._draft_free.append(self._draft_slots.pop(rid))
            self._draft_synced.pop(rid, None)
        updates: List[tuple] = []
        for s in seqs:
            rid = s.request_id
            slot = self._draft_slots.get(rid)
            delta = len(s.tokens) - self._draft_synced.get(rid, 0)
            if slot is None:
                if not self._draft_free:
                    continue  # more rows than slots: host scan fallback
                slot = self._draft_free.pop()
                self._draft_slots[rid] = slot
                delta = -1  # fresh slot: force the cold reset below
            if delta < 0 or delta > self._draft_D:
                self.runner.draft_ring_reset(slot, s.tokens)
            elif delta:
                updates.append((slot, s.tokens[-delta:]))
            self._draft_synced[rid] = len(s.tokens)
        drafts, n_prop = self.runner.draft_step(updates, self.spec_k)
        out: Dict[str, List[int]] = {}
        for s in seqs:
            slot = self._draft_slots.get(s.request_id)
            if slot is not None:
                n = int(n_prop[slot])
                out[s.request_id] = [int(t) for t in drafts[slot][:n]]
        return out

    def _run_spec_verify(self, dplan: DecodePlan, prefills):
        """ONE ragged flat-token dispatch verifying every speculating
        row's draft (a K+1-token segment: the last real token + K draft
        tokens) alongside the plain decode rows and, on fused runners,
        the packed prefill chunks. Acceptance is the deterministic
        (one-hot q) specialization of spec_decode.accept_and_finalize:
        emit target samples through the first mismatch (+ bonus token on
        a full match), so temperature-0 output is byte-identical to
        plain decode. Rejected drafts cost nothing durable — their KV
        sits past computed_len on unshared pages and the next step
        overwrites it, so pages never leak and the prefix-hash lineage
        (tokens/hashing.py) only ever advances over committed tokens.

        Returns (chunk_logits, rinfo_spec) or None when the runner
        can't shape the dispatch (drafts are dropped; the caller reruns
        the plain path)."""
        if hasattr(self.runner, "ensure_ragged_bucket"):
            from dynamo_tpu.engine.model_runner import BucketOverflowError
        else:
            # SimRunner buckets saturate instead of overflowing, and the
            # mocker process must stay jax-free — catch nothing there
            BucketOverflowError = ()

        seqs = dplan.seqs
        drafts = [list(s.spec_draft) for s in seqs]
        trees = [list(s.spec_tree) for s in seqs]
        for s in seqs:
            s.spec_draft = []  # consumed (or shed) either way
            s.spec_tree = []
        tokens = [s.tokens[-1] for s in seqs]
        positions = [s.computed_len for s in seqs]
        tables = [s.pages for s in seqs]
        step0 = self._next_step()
        chunks = [
            {
                "tokens": p.chunk, "start": p.start_pos,
                "table": p.seq.pages, "prior": p.start_pos,
                "adapter": p.seq.adapter_idx,
            }
            for p in prefills
        ]
        n_drafted = sum(len(d) for d in drafts)
        # tree speculation: each extra branch is an INDEPENDENT verify
        # segment on a forked page table — trunk (committed) pages are
        # ref-shared, only the speculative tail is fresh, so branch KV
        # writes never collide with the primary row's. Branch rows are
        # appended AFTER every primary row, which keeps the row-indexed
        # mask/bias dicts below valid, and they reuse the owning
        # sequence's sampling params + seed: identical branch prefixes
        # then yield identical target samples, the trie invariant
        # accept_tree's walk relies on.
        sp = _sampling_params(seqs)
        branch_rows: List[List[int]] = [[] for _ in seqs]
        forks: List[List[List[int]]] = [[] for _ in seqs]
        n_branch_tok = 0
        if any(trees):
            PS = self.pool.page_size
            for i, s in enumerate(seqs):
                if not drafts[i]:
                    trees[i] = []  # branches never ride without a primary
                for b in trees[i]:
                    try:
                        fork = self.pool.fork_table(
                            s.pages, n_shared=s.computed_len // PS
                        )
                    except NoSpace:
                        break  # pool pressure: shed remaining branches
                    branch_rows[i].append(len(tokens))
                    forks[i].append(fork)
                    tokens.append(s.tokens[-1])
                    positions.append(s.computed_len)
                    tables.append(fork)
                    drafts.append([int(t) for t in b])
                    n_branch_tok += len(b) + 1
                    for kf in sp:
                        sp[kf].append(sp[kf][i])
                trees[i] = trees[i][: len(forks[i])]

        def _release_forks(i: int) -> None:
            for f in forks[i]:
                if f is not None:
                    self.pool.release(f)
            forks[i] = []
        # guided/bias rows never draft (_propose_drafts), so each owns
        # exactly ONE verify position; its mask/bias rides the dispatch's
        # always-present sampling operands (row-aligned dicts)
        vkw: Dict[str, Any] = {}
        masks = {
            i: self._guided_mask(s)
            for i, s in enumerate(seqs) if s.guided_m is not None
        }
        if masks:
            vkw["masks"] = masks
        brows = _batch_biases(seqs, self.runner)
        if brows is not None:
            vkw["biases"] = {
                i: brows[i] for i, s in enumerate(seqs) if s.logit_bias
            }
        n_branch_rows = sum(len(r) for r in branch_rows)
        with annotate("engine.spec_verify", batch=len(seqs),
                      drafted=n_drafted, chunks=len(chunks),
                      branches=n_branch_rows):
            try:
                with self._san_scope("spec_verify"):
                    rows, chunk_logits = self.runner.verify_spec(
                        tokens, positions, tables, drafts,
                        sp, step0, chunks=chunks, **vkw,
                    )
            except BucketOverflowError as e:
                for i in range(len(seqs)):
                    _release_forks(i)  # no KV was committed to them
                log.warning(
                    "spec verify overflows runner buckets (%s); dropping "
                    "this iteration's drafts", e,
                )
                return None
            n_rows = sum(1 for d in drafts[: len(seqs)] if d)
            accepted = emitted_spec = tree_sw = 0
            for i, seq in enumerate(seqs):
                if forks[i]:
                    emitted, winner = accept_tree(
                        [drafts[i]] + trees[i],
                        [rows[i]] + [rows[r] for r in branch_rows[i]],
                    )
                    if winner > 0:
                        # adopt the winning branch's forked table BEFORE
                        # committing: its fresh tail pages hold the KV of
                        # the accepted suffix (the primary's tail is stale
                        # past the first divergence). Trunk pages are
                        # shared, so the swap moves one reference; the old
                        # table's speculative tail goes back to the pool.
                        old = seq.pages
                        seq.pages = forks[i][winner - 1]
                        forks[i][winner - 1] = None
                        self.pool.release(old)
                        tree_sw += 1
                    _release_forks(i)  # losers (and fork-side trunk refs)
                else:
                    emitted = accept_deterministic(drafts[i], rows[i])
                if drafts[i]:
                    accepted += len(emitted) - 1
                    emitted_spec += len(emitted)
                emit: List[int] = []
                reason = None
                for token in emitted:
                    reason = self.scheduler.complete_decode(seq, token)
                    if not reason:
                        self._guided_advance(seq, token)
                    if reason != "stop":
                        emit.append(token)
                    if reason:
                        break
                self._emit(seq, emit, reason)
        st = self.spec_stats
        st["verify_iters"] += 1
        st["verify_rows"] += n_rows
        st["drafted"] += n_drafted
        st["accepted"] += accepted
        st["rejected"] += n_drafted - accepted
        st["spec_emitted"] += emitted_spec
        st["tree_rows"] += n_branch_rows
        st["tree_switches"] += tree_sw
        return chunk_logits, {
            "spec_rows": n_rows,
            # billing-honest: branch rows cost len+1 flat tokens each on
            # the dispatch, exactly what the scheduler charged (_spec_cost)
            "spec_drafted": n_drafted + n_branch_tok,
            "spec_emitted": emitted_spec,
        }

    def _mixed_fusible(self, plan: MixedPlan) -> bool:
        """Whether this MixedPlan can run as ONE dispatch (runner
        decode_multi_with_prefill). Feature planes the fused program
        doesn't carry fall back to the two-dispatch path."""
        runner = self.runner
        if (not self.fused_mixed
                or not hasattr(runner, "decode_multi_with_prefill")
                or getattr(runner, "has_draft", False)
                or getattr(runner, "pp", False)
                or getattr(runner, "sp_enabled", False)):
            # SP runners prefill with ring attention on the full mesh —
            # the fused program's plain attn_impl would miscompute the
            # chunk's KV there
            return False
        if len(plan.prefills) > 1 and not hasattr(
            runner, "decode_multi_with_prefills"
        ):
            return False  # packed ragged program unavailable on this runner
        seqs = plan.decode.seqs
        if any(s.guided_m is not None or s.logit_bias for s in seqs):
            # masks and bias exist only as ragged-step / decode-loop
            # operands: guided or biased decode rows fuse iff this plan
            # rides the ragged flat-token program (never the padded
            # [N, S] fallback, which would silently drop the constraint)
            use_ragged = getattr(runner, "_use_ragged", None)
            if (use_ragged is None
                    or not getattr(runner, "guided_fused", False)
                    or not use_ragged(len(seqs), len(plan.prefills))):
                return False
        if _batch_logprobs(seqs) >= 0 or _batch_penalties(seqs):
            return False
        if any(p.seq.logit_bias for p in plan.prefills):
            return False  # chunk-side bias keeps the two-dispatch path
        for pplan in plan.prefills:
            if self._mm_chunk(
                pplan.seq, pplan.start_pos, len(pplan.chunk)
            ) is not None:
                return False  # multimodal chunks ride the standalone prefill
        return True

    def _run_mixed_dispatch(self, plan: MixedPlan):
        """The fused dispatch + decode-half bookkeeping: the decode
        batch's fused steps and the packed prefill chunk set share a
        single jitted program — one host sync per iteration instead of
        1 + n_chunks (each dispatch is a full RTT through a
        relay-attached chip). Returns the per-chunk last-token logits
        (one row per packed chunk); the caller finishes the prefill half
        separately so a failure THERE only fails prefill sequences (the
        decode tokens are already emitted)."""
        from dynamo_tpu.engine.model_runner import BucketOverflowError

        seqs = plan.decode.seqs
        T = plan.decode.n_steps
        n_chunk_tok = sum(len(p.chunk) for p in plan.prefills)
        prefills = list(plan.prefills)
        with annotate("engine.mixed", batch=len(seqs), steps=T,
                      chunks=len(plan.prefills), chunk=n_chunk_tok):
            tokens = [s.tokens[-1] for s in seqs]
            positions = [s.computed_len for s in seqs]
            tables = [s.pages for s in seqs]
            step0 = self._step_counter + 1
            self._step_counter += T
            # guided rows ride the fused program: step 0 samples under the
            # ragged step's mask operand; steps 1..T-1 fetch per-step masks
            # through the decode loop's host callback, which advances a
            # COPY of each row's DFA state by the device-sampled feedback
            # token (pending_advance: step 0's token was sampled on device
            # and not yet folded into the authoritative engine state)
            mixkw: Dict[str, Any] = {}
            guided_rows = [
                i for i, s in enumerate(seqs) if s.guided_m is not None
            ]
            if guided_rows:
                vocab = seqs[guided_rows[0]].guided_m.lifter.vocab_size
                masks = np.ones((len(seqs), vocab), bool)
                for i in guided_rows:
                    masks[i] = self._guided_mask(seqs[i])
                mixkw["masks"] = masks
                if T > 1:
                    # tail steps after the ragged step 0: device DFA plan
                    # when every schema fits the table budget (the runner
                    # forces pending_advance — step 0's token was sampled
                    # on device and not yet folded into the states), host
                    # callback otherwise
                    gdev = self._guided_device_plan(seqs)
                    if gdev is not None:
                        mixkw["guided_dev"] = gdev
                    else:
                        mixkw["mask_fn"] = GuidedMaskContext(
                            len(seqs), vocab,
                            [(i, seqs[i].guided_m, seqs[i].guided_s)
                             for i in guided_rows],
                            pending_advance=True,
                        )
            biases = _batch_biases(seqs, self.runner)
            if biases is not None:
                mixkw["biases"] = biases
            while True:
                # Bucket-overflow degradation: a pack the runner can't
                # shape (pack/chunk/T bucket exceeded) sheds its newest
                # chunk and retries. Shed chunks were never
                # complete_prefill'd, so the scheduler re-plans them
                # verbatim next iteration (planning is side-effect-free;
                # their pages are already held). The caller's
                # zip(plan.prefills, chunk_logits) pairs only the served
                # prefix — chunks are shed strictly from the tail.
                try:
                    if len(prefills) == 1:
                        pplan = prefills[0]
                        sampled, lg = self.runner.decode_multi_with_prefill(
                            T, tokens, positions, tables,
                            _sampling_params(seqs),
                            step0, pplan.chunk, pplan.start_pos,
                            pplan.seq.pages, pplan.start_pos,
                            adapters=[s.adapter_idx for s in seqs],
                            chunk_adapter=pplan.seq.adapter_idx,
                            **mixkw,
                        )
                        chunk_logits = [lg]
                    else:
                        sampled, chunk_logits = (
                            self.runner.decode_multi_with_prefills(
                                T, tokens, positions, tables,
                                _sampling_params(seqs),
                                step0,
                                [
                                    {
                                        "tokens": p.chunk,
                                        "start": p.start_pos,
                                        "table": p.seq.pages,
                                        "prior": p.start_pos,
                                        "adapter": p.seq.adapter_idx,
                                    }
                                    for p in prefills
                                ],
                                adapters=[s.adapter_idx for s in seqs],
                                **mixkw,
                            )
                        )
                    break
                except BucketOverflowError as e:
                    if len(prefills) <= 1:
                        raise  # even one chunk won't fit any shape
                    shed = prefills.pop()
                    log.warning(
                        "mixed pack overflows runner buckets (%s); "
                        "deferring chunk of %s to the next iteration",
                        e, shed.seq.request_id,
                    )
            for i, seq in enumerate(seqs):
                emit: List[int] = []
                reason = None
                for j in range(T):
                    token = int(sampled[i, j])
                    reason = self.scheduler.complete_decode(seq, token)
                    if not reason:
                        self._guided_advance(seq, token)
                    if reason != "stop":
                        emit.append(token)
                    if reason:
                        break
                self._emit(seq, emit, reason)
        return chunk_logits

    def _run_decode(self, plan: DecodePlan) -> None:
        with annotate("engine.decode", batch=len(plan.seqs),
                      steps=plan.n_steps):
            with self._san_scope("decode"):
                self._run_decode_inner(plan)

    def _run_decode_inner(self, plan: DecodePlan) -> None:
        """Fused multi-step decode: plan.n_steps iterations in one jit with
        on-device token feedback (one host sync per plan, not per token).
        Tokens sampled past a stop are discarded host-side."""
        seqs = plan.seqs
        T = plan.n_steps
        tokens = [s.tokens[-1] for s in seqs]
        positions = [s.computed_len for s in seqs]
        page_tables = [s.pages for s in seqs]
        step0 = self._step_counter + 1
        gamma = getattr(self.runner, "spec_gamma", 0)
        use_draft_spec = getattr(self.runner, "has_draft", False)
        if use_draft_spec and (
            _batch_logprobs(seqs) >= 0 or _batch_penalties(seqs)
        ):
            # the speculative verify distribution can't honor
            # logprobs/penalties: warn once per offending request and
            # fall back to the PLAIN decode path below, which does. The
            # draft model's KV pools skip these positions — that costs
            # draft acceptance on later iterations (verify still
            # corrects every token), never correctness.
            for s in seqs:
                if _batch_logprobs([s]) >= 0 or _batch_penalties([s]):
                    self._warn_spec_once(
                        s.request_id,
                        "logprobs/penalties are incompatible with "
                        "speculative verification — falling back to "
                        "non-speculative decode",
                    )
            use_draft_spec = False
        if use_draft_spec:
            # (guided requests were rejected at admission on draft workers,
            # so no mask handling is needed on this path)
            # speculative path: R fused draft-propose + target-verify
            # rounds; each round yields 1..gamma+1 tokens per sequence.
            # Near a token budget (T < gamma+1) shrink gamma instead of
            # falling back to plain decode — the plain path writes no draft
            # KV, which would leave batch-wide draft-pool holes (gamma=0 is
            # plain decoding plus the draft bookkeeping)
            if T < gamma + 1:
                gamma, R = T - 1, 1
            else:
                R = T // (gamma + 1)
            self._step_counter += R
            toks, counts = self.runner.spec_decode_multi(
                R, tokens, positions, page_tables, _sampling_params(seqs), step0,
                gamma=gamma, adapters=[s.adapter_idx for s in seqs],
            )
            for i, seq in enumerate(seqs):
                emit: List[int] = []
                reason = None
                for r in range(R):
                    for j in range(int(counts[i, r])):
                        token = int(toks[i, r, j])
                        reason = self.scheduler.complete_decode(seq, token)
                        if reason != "stop":
                            emit.append(token)
                        if reason:
                            break
                    if reason:
                        break
                self._emit(seq, emit, reason)
            return
        masks = None
        mask_fn = None
        guided_dev = None
        guided_rows = [i for i, s in enumerate(seqs) if s.guided_m is not None]
        if guided_rows:
            vocab = seqs[guided_rows[0]].guided_m.lifter.vocab_size
            if T > 1 and getattr(self.runner, "guided_fused", False):
                # constrained rows need a fresh mask per sampled token.
                # Preferred: the device-resident DFA plan — state advance
                # and mask gather happen in-XLA inside the fused loop,
                # ZERO host syncs per step. Fallback (schema over the
                # device-table budget): a host callback that advances a
                # COPY of each row's DFA state by the device-sampled
                # feedback token between fused steps — guided rows still
                # ride the full decode_steps loop either way, and both
                # paths produce byte-identical masks on bounded schemas
                # (pinned by tests/test_guided.py)
                guided_dev = self._guided_device_plan(seqs)
                if guided_dev is None:
                    mask_fn = GuidedMaskContext(
                        len(seqs), vocab,
                        [(i, seqs[i].guided_m, seqs[i].guided_s)
                         for i in guided_rows],
                    )
            else:
                # runners without callback plumbing (PP loop) keep the
                # legacy one-step masked dispatch
                T = 1
                masks = np.ones((len(seqs), vocab), bool)
                for i in guided_rows:
                    masks[i] = self._guided_mask(seqs[i])
        biases = _batch_biases(seqs, self.runner)
        self._step_counter += T
        n_lp = _batch_logprobs(seqs)
        histories = (
            [list(s.tokens) for s in seqs] if _batch_penalties(seqs) else None
        )
        if (n_lp >= 0 or histories is not None) and getattr(
            self.runner, "pp", False
        ):
            # the PP decode loop has no logprob/penalty wiring yet — drop
            # the extras with a warning (same contract as spec decode
            # above) instead of letting a raise inside the shared dispatch
            # error EVERY sequence in the plan
            for s in seqs:
                if _batch_logprobs([s]) >= 0 or _batch_penalties([s]):
                    self._warn_spec_once(
                        s.request_id,
                        "logprobs/penalties are unsupported on "
                        "pipeline-parallel workers and were ignored",
                    )
            n_lp, histories = -1, None
        lp = None
        if (n_lp >= 0 or histories is not None) and hasattr(
            self.runner, "decode_multi_ex"
        ):
            mkw = {"masks": masks} if masks is not None else {}
            if mask_fn is not None:
                mkw["mask_fn"] = mask_fn
            if guided_dev is not None:
                mkw["guided_dev"] = guided_dev
            if biases is not None:
                mkw["biases"] = biases
            sampled, lp = self.runner.decode_multi_ex(
                T, tokens, positions, page_tables, _sampling_params(seqs), step0,
                adapters=[s.adapter_idx for s in seqs],
                n_logprobs=n_lp, histories=histories,
                prompt_lens=[s.n_prompt0 for s in seqs],
                **mkw,
            )
        else:
            mkw = {"masks": masks} if masks is not None else {}
            if mask_fn is not None:
                mkw["mask_fn"] = mask_fn
            if guided_dev is not None:
                mkw["guided_dev"] = guided_dev
            if biases is not None:
                mkw["biases"] = biases
            sampled = self.runner.decode_multi(
                T, tokens, positions, page_tables, _sampling_params(seqs), step0,
                adapters=[s.adapter_idx for s in seqs],
                **mkw,
            )
        for i, seq in enumerate(seqs):
            emit: List[int] = []
            lp_entries: List[Dict[str, Any]] = []
            reason = None
            for j in range(T):
                token = int(sampled[i, j])
                reason = self.scheduler.complete_decode(seq, token)
                if not reason:
                    self._guided_advance(seq, token)
                if reason != "stop":
                    emit.append(token)
                    if lp is not None and seq.sampling.get("logprobs") is not None:
                        lp_entries.append(_lp_entry(lp, i, j, seq))
                if reason:
                    break
            self._emit(seq, emit, reason, logprobs=lp_entries or None)

    def _guided_advance(self, seq: Sequence, token: int) -> None:
        """Advance a sequence's constraint DFA past an accepted token. A
        desync (should be impossible while masks are honored) drops the
        constraint and logs rather than killing the whole batch."""
        m = seq.guided_m
        if m is None or token == m.lifter.eos_id:
            return
        try:
            seq.guided_s = m.advance(seq.guided_s, int(token))
        except ValueError as e:
            log.error("request %s: %s — constraint dropped", seq.request_id, e)
            seq.guided_m = None

    def _next_step(self) -> int:
        self._step_counter += 1
        return self._step_counter

    # -- emission ----------------------------------------------------------
    def _emit(
        self,
        seq: Sequence,
        token_ids: List[int],
        finish: Optional[str],
        logprobs: Optional[List[Dict[str, Any]]] = None,
    ) -> None:
        extra = {"logprobs": logprobs} if logprobs else {}
        if token_ids:
            # latency spine: first emitted token fixes TTFT; later emit
            # groups contribute per-token ITL samples (bounded list — a
            # long generation keeps its first _ITL_CAP samples)
            now = time.monotonic()
            if "ttft_s" not in seq.phases:
                if seq.arrival:
                    seq.phases["ttft_s"] = max(0.0, now - seq.arrival)
            elif seq.t_last_emit and len(seq.itl) < _ITL_CAP:
                # a multi-token group (fused steps, accepted speculative
                # drafts) contributes ONE ITL sample PER TOKEN — the step
                # wall divided across the group — so itl percentiles, SLO
                # burn rates, and goodput weight a 4-token step as 4 fast
                # inter-token gaps, not one slow one
                per = max(0.0, now - seq.t_last_emit) / len(token_ids)
                n = min(len(token_ids), _ITL_CAP - len(seq.itl))
                seq.itl.extend([per] * n)
            seq.t_last_emit = now
        self._emit_item(seq, engine_output(token_ids, finish, **extra))

    def _emit_item(self, seq: Sequence, item: Dict[str, Any]) -> None:
        if item.get("finish_reason"):
            # final item carries the request's phase spine downstream
            # (loadgen/goodput aggregate it; the frontend adds span events)
            phases = dict(seq.phases)
            if seq.arrival:
                phases["e2e_s"] = max(0.0, time.monotonic() - seq.arrival)
            if seq.itl:
                phases["itl_s"] = list(seq.itl)
            pctx = tracing.parse_traceparent(seq.tp)
            if pctx is not None:
                # trace id rides the spine so digests / incident bundles
                # can join aggregates back to individual traces
                phases["trace_id"] = pctx.trace_id
            try:
                self._emit_worker_spans(seq, phases,
                                        item.get("finish_reason"))
            except Exception:  # pragma: no cover
                log.exception("worker span synthesis failed")
            item.setdefault("phases", phases)
            for cb in self._phase_listeners:
                try:
                    cb(phases)
                except Exception:  # pragma: no cover
                    log.exception("phase listener failed")
        if seq.branch_of is not None or seq.n_branches > 1:
            # branched choices multiplex the parent's stream; the index
            # tells the consumer which choice each item belongs to
            item.setdefault("index", seq.branch_index)
        entry = self._streams.get(seq.branch_of or seq.request_id)
        if entry is None:
            return
        out, loop = entry
        loop.call_soon_threadsafe(out.put_nowait, item)

    def _emit_worker_spans(self, seq: Sequence, phases: Dict[str, Any],
                           finish: str) -> None:
        """Synthesize the worker's phase spans retroactively at finish.

        The phase spine measures durations on the step thread; only at
        the final item is the whole story known, so the spans are
        reconstructed from (now - e2e) backwards instead of holding live
        spans open across engine iterations: queue -> kv_onboard
        (tier-labeled) -> prefill -> stream, all children of one
        worker.request span parented on the route span's traceparent."""
        if seq.tp is None or not tracing.enabled():
            return
        e2e = float(phases.get("e2e_s") or 0.0)
        if e2e <= 0.0:
            return
        end_ns = time.time_ns()
        t0 = end_ns - int(e2e * 1e9)
        root = tracing.record_span(
            "worker.request", t0, end_ns, parent=seq.tp,
            attributes={
                "request.id": seq.request_id,
                "finish_reason": finish,
                "n_tokens": len(seq.tokens),
                "preemptions": seq.n_preemptions,
            })
        if root is None:
            return
        wtp = root.traceparent
        qw = max(0.0, float(phases.get("queue_wait_s") or 0.0))
        ob = max(0.0, float(phases.get("kv_onboard_s") or 0.0))
        ttft = max(qw + ob, float(phases.get("ttft_s") or 0.0))
        # clamp each cut into [t0, end_ns] — clock skew between the
        # spine's monotonic stamps and this wall-clock anchor must not
        # produce a child escaping its parent
        cut = [min(end_ns, t0 + int(s * 1e9))
               for s in (qw, qw + ob, ttft)]
        attrs = {"request.id": seq.request_id}
        tracing.record_span("worker.queue", t0, cut[0], parent=wtp,
                            attributes=attrs)
        if ob > 0.0:
            tracing.record_span(
                "worker.kv_onboard", cut[0], cut[1], parent=wtp,
                attributes=dict(attrs, **{
                    "kv.tier": seq.onboard_tier or "G2"}))
        tracing.record_span("worker.prefill", cut[1], cut[2], parent=wtp,
                            attributes=attrs)
        tracing.record_span(
            "worker.stream", cut[2], end_ns, parent=wtp,
            attributes=dict(attrs, n_itl_samples=len(seq.itl)))

    # -- disagg export (called from the asyncio side) -----------------------
    async def export_host_blocks(self, hashes: List[int]) -> Dict[str, Any]:
        """Serve a peer's cross-worker onboarding pull (runs the lower-tier
        read on the step thread — the pools are step-thread state)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("host_export", ([int(h) for h in hashes], fut, loop)))
        return await fut

    async def export_parked_kv(
        self, request_id: str, discard: bool = False
    ) -> Optional[Dict[str, Any]]:
        """Pull a parked request's KV pages (runs the device read on the
        step thread between steps); releases the parked pages. discard=True
        releases without reading (early-finished disagg requests)."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("export", (request_id, fut, loop, discard)))
        return await fut

    async def export_parked_kv_stream(self, request_id: str, chunk_pages: int = 16):
        """Chunked parked-KV export (reference disagg-serving.md bootstrap
        handoff: the decode worker pulls KV in bounded pieces instead of
        one monolithic message). Each chunk is read on the step thread
        between decode steps, so a 70B-scale transfer neither stalls
        decode for its full duration nor materializes the whole prompt's
        KV in one host buffer. Yields payload dicts carrying "offset"."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._inbox.put(("export_meta", (request_id, fut, loop)))
        total = await fut
        if total is None:
            return
        chunk_pages = max(1, int(chunk_pages))
        for start in range(0, total, chunk_pages):
            n = min(chunk_pages, total - start)
            last = start + n >= total
            fut = loop.create_future()
            self._inbox.put(
                ("export_chunk", (request_id, start, n, last, fut, loop))
            )
            payload = await fut
            if payload is None:  # parked entry expired mid-stream
                return
            yield payload

    def _publish_fpm(self, kind: str, wall: float, n_tok: int) -> None:
        st = self.scheduler.stats
        m = ForwardPassMetrics(
            ts=time.time(),
            kind=kind,
            wall_time_s=wall,
            scheduled_tokens=n_tok,
            n_running=st.n_running,
            n_waiting=st.n_waiting,
            kv_usage=st.kv_usage,
        )
        self.fpm_history.append(m)
        if len(self.fpm_history) > 2048:
            del self.fpm_history[:1024]
        for cb in self._fpm_listeners:
            try:
                cb(m)
            except Exception:  # pragma: no cover
                log.exception("fpm listener failed")

    def _publish_kv_events(self) -> None:
        events = self.pool.drain_events() + self._host_events
        self._host_events = []
        if not events:
            return
        for cb in self._kv_listeners:
            try:
                cb(events)
            except Exception:  # pragma: no cover
                log.exception("kv listener failed")

    # -- KVBM G2 tier (step-thread callbacks) -------------------------------
    def _offload_page(self, page: int, block_hash: int, parent: Optional[int]) -> None:
        """Device page being evicted → copy its KV to the host tier."""
        from dynamo_tpu.engine.model_runner import kv_payload_to_arrays

        arrays = kv_payload_to_arrays(self.runner.export_pages([page]))
        k, v = arrays if arrays is not None else (None, None)
        self.host_pool.put([block_hash], [parent], k, v)
        self._host_events.append(KvEvent("store", [block_hash], parent, tier="host"))

    def _on_host_evicted(self, hashes: List[int]) -> None:
        self._host_events.append(KvEvent("remove", hashes, tier="host"))
        if getattr(self.host_pool, "obj", None) is not None:
            # terminal tier is G4: the block left the shared store too,
            # so the router's obj_index residency must expire with it
            self._host_events.append(KvEvent("remove", hashes, tier="obj"))

    def _on_obj_stored(self, block_hash: int, parent: Optional[int]) -> None:
        """G4 store_listener — may fire from the writer/spill thread, so
        hand the event to the step thread via the inbox (the KvEvent list
        is step-thread-owned)."""
        self._inbox.put(("obj_event", (block_hash, parent)))

    def _host_export(self, hashes: List[int], fut, loop) -> None:
        """Serve a peer's cross-worker onboarding pull: the leading run of
        `hashes` resident in this worker's lower tiers, as a KV payload
        (reference kvbm-engine onboarding sessions: the remote-G2 read)."""
        from dynamo_tpu.engine.model_runner import kv_arrays_to_payload

        out: Dict[str, Any] = {"n": 0}
        if self.host_pool is not None and hashes:
            n = self.host_pool.match(hashes)
            if n:
                try:
                    k, v = self.host_pool.get(hashes[:n])
                except Exception:
                    # eviction races raise KeyError; G3/G4 reads can raise
                    # IO/network errors — a peer's pull must never kill the
                    # step thread, so fail the export, not the loop
                    log.warning("host export failed; replying empty",
                                exc_info=True)
                    n = 0
                    k = v = None
                if k is None and hasattr(self.runner, "export_pages_device"):
                    # real engine with hash-only entries (data lost, e.g. a
                    # shared G4 object deleted): advertising n>0 without
                    # data would spread phantom residency cluster-wide
                    n = 0
                out["n"] = n
                if n and k is not None:
                    out.update(kv_arrays_to_payload(k, v))
        loop.call_soon_threadsafe(_set_future, fut, out)

    def _host_import(self, hashes: List[int], parents: List[Optional[int]],
                     payload: Dict[str, Any]) -> None:
        """Blocks pulled from a peer's lower tier land in the local G2 (the
        admission path then onboards them like any host-tier hit). Emits
        host store events so the router's lower-tier credits follow."""
        from dynamo_tpu.engine.model_runner import kv_payload_to_arrays

        if self.host_pool is None or not hashes:
            return
        try:
            # geometry/dtype validated at INGEST: a mismatched peer block
            # stored into G2 would otherwise pass host_pool and explode as
            # an unhandled KvWireLayoutMismatch at onboard time
            arrays = kv_payload_to_arrays(
                payload,
                getattr(self.runner, "kv_page_shape", None),
                getattr(self.runner, "kv_wire_dtype", None),
            )
        except Exception:
            # mixed-version peer (KvWireLayoutMismatch) or corrupt bytes:
            # drop the pull — admission recomputes; never adopt the blocks
            log.warning("peer KV payload rejected; recomputing", exc_info=True)
            return
        k, v = arrays if arrays is not None else (None, None)
        self.host_pool.put(hashes, parents, k, v)
        self._host_events.append(
            KvEvent("store", list(hashes), parents[0] if parents else None,
                    tier="host")
        )

    def _onboard_from_host(self, pages: List[int], hashes: List[int],
                           seq: Optional[Sequence] = None) -> bool:
        """Host-tier blocks → device pages during admission. Returns False
        when a matched block was evicted between match and get (lower-tier
        LRU churn under memory pressure) — the scheduler then recomputes
        instead of trusting a partial import.

        Imports stream in `onboard_layer_groups` layer slabs (FlowKV);
        when both the tier AND the device pools are int8-quantized the
        blocks pass through natively (no dequantize/requantize). Measured
        transfer time feeds the per-tier kv_onboard_ewma that topology-
        aware routing consumes."""
        from dynamo_tpu.engine.model_runner import kv_arrays_to_payload

        if self.prefetch is not None:
            # any of these blocks still mid-promotion arrived LATE: this
            # synchronous import wins, the prefetch job is cancelled (a
            # duplicate in-flight import dedups via pool.register)
            self.prefetch.note_sync_onboard(hashes)
        tiers = (self.host_pool.residency(hashes)
                 if hasattr(self.host_pool, "residency")
                 else ["host"] * len(hashes))
        if seq is not None and tiers:
            # deepest rung dominates the transfer — it labels the
            # worker.kv_onboard span (same attribution as the EWMA)
            order = {"host": 0, "disk": 1, "obj": 2}
            label = {"host": "G2", "disk": "G3", "obj": "G4"}
            deepest = max(tiers, key=lambda t: order.get(t, -1))
            seq.onboard_tier = label.get(deepest, deepest)
        groups = self.onboard_layer_groups
        t0 = time.perf_counter()
        try:
            payload = self._native_quant_payload(hashes, tiers)
            k = v = None
            if payload is None:
                k, v = self.host_pool.get(hashes)
        except KeyError:
            log.info("lower-tier block evicted before onboard; recomputing")
            return False
        if payload is not None:
            self.runner.import_pages(pages, 0, payload, layer_groups=groups)
            self._note_onboard(tiers, len(hashes), time.perf_counter() - t0)
            return True
        if k is None:
            # real engines need bytes (a hash-indexed block whose data is
            # gone — e.g. a shared G4 object deleted externally — must be
            # recomputed, not trusted); sim runners track KV at hash level
            # only and None is their normal case — but the transfer still
            # takes wall time, so charge the import (SimRunner sleeps it;
            # without this, mocker prefetch A/Bs would credit the
            # synchronous path with a free onboard)
            if hasattr(self.runner, "export_pages_device"):
                log.info("lower-tier block has no data; recomputing")
                return False
            self.runner.import_pages(
                pages, 0, {"sim": True, "data": True, "n_pages": len(pages)},
                layer_groups=groups)
            self._note_onboard(tiers, len(hashes), time.perf_counter() - t0)
            return True
        self.runner.import_pages(pages, 0, kv_arrays_to_payload(k, v),
                                 layer_groups=groups)
        self._note_onboard(tiers, len(hashes), time.perf_counter() - t0)
        return True

    def _native_quant_payload(self, hashes: List[int], tiers: List[str]):
        """int8+scales pass-through payload when the whole chain is
        G2-resident, the tier quantizes, and the device pools are int8
        (kv_quantize) — else None (dense path). Raises KeyError on
        eviction races like host_pool.get."""
        if not getattr(self.runner, "kv_quantize", None):
            return None
        host = getattr(self.host_pool, "host", self.host_pool)
        if not getattr(host, "quantize", False):
            return None
        if any(t != "host" for t in tiers):
            return None
        from dynamo_tpu.kvbm.quant import is_quantized_block
        from dynamo_tpu.engine.model_runner import kv_quant_arrays_to_payload

        blocks = [host.get_block_raw(h) for h in hashes]
        if not blocks or not all(
            is_quantized_block(k) and is_quantized_block(v)
            for k, v in blocks
        ):
            return None
        kq = np.stack([b[0]["q"] for b in blocks], axis=1)
        ks = np.stack([b[0]["s"] for b in blocks], axis=1)
        vq = np.stack([b[1]["q"] for b in blocks], axis=1)
        vs = np.stack([b[1]["s"] for b in blocks], axis=1)
        return kv_quant_arrays_to_payload(kq, ks, vq, vs)

    def _note_onboard(self, tiers: List[str], n_blocks: int,
                      elapsed_s: float, tier: Optional[str] = None) -> None:
        """Fold one measured onboard into the per-tier per-block EWMA.
        A chain spanning tiers is attributed to its DEEPEST tier — the
        rung that dominated the transfer time (G3 file reads dwarf the
        G2 memcpy above them)."""
        if tier is None:
            order = {"host": 0, "disk": 1, "obj": 2}
            tier = "host"
            for t in tiers:
                if order.get(t, -1) > order[tier]:
                    tier = t
        per_block = elapsed_s / max(1, n_blocks)
        e = self.kv_onboard_ewma.get(tier)
        if e is None:
            self.kv_onboard_ewma[tier] = {"s_per_block": per_block,
                                          "n": n_blocks}
            return
        alpha = 0.25
        e["s_per_block"] = alpha * per_block + (1 - alpha) * e["s_per_block"]
        e["n"] += n_blocks


def _set_future(fut: asyncio.Future, value) -> None:
    if not fut.done():
        fut.set_result(value)


def _set_future_exc(fut: asyncio.Future, exc: Exception) -> None:
    if not fut.done():
        fut.set_exception(exc)


def _stable_seed(request_id: str) -> int:
    """Process-independent sampling seed so a migrated/retried request samples
    the same stream on whichever worker replays it (Python's hash() is salted
    per process)."""
    import hashlib

    d = hashlib.blake2b(request_id.encode(), digest_size=4).digest()
    return int.from_bytes(d, "big") & 0x7FFFFFFF


def _sampling_params(seqs: List[Sequence]) -> Dict[str, list]:
    """Plain host lists; the runner converts to device arrays (keeps the
    mocker's SimRunner — and thus mocker processes — entirely jax-free)."""
    return {
        "temperature": [float(s.sampling.get("temperature", 1.0)) for s in seqs],
        "top_k": [int(s.sampling.get("top_k", 0)) for s in seqs],
        "top_p": [float(s.sampling.get("top_p", 1.0)) for s in seqs],
        "seeds": [
            (s.sampling.get("seed") if s.sampling.get("seed") is not None
             else _stable_seed(s.request_id))
            for s in seqs
        ],
        "rep": [float(s.sampling.get("repetition_penalty", 1.0)) for s in seqs],
        "freq": [float(s.sampling.get("frequency_penalty", 0.0)) for s in seqs],
        "presence": [float(s.sampling.get("presence_penalty", 0.0)) for s in seqs],
    }


def _batch_biases(seqs: List[Sequence], runner):
    """[n, V] f32 additive logit-bias rows for the batch, or None when no
    sequence carries one (out-of-range token ids are ignored — the
    preprocessor validates, but the wire is untrusted). The vocab lookup
    happens only when a bias exists: sim runners expose vocab_size
    directly and have no .config."""
    if not any(s.logit_bias for s in seqs):
        return None
    vocab_size = getattr(
        getattr(runner, "config", None), "vocab_size", None
    ) or getattr(runner, "vocab_size")
    rows = np.zeros((len(seqs), vocab_size), np.float32)
    for i, s in enumerate(seqs):
        if not s.logit_bias:
            continue
        cached = getattr(s, "_bias_row", None)
        if cached is None or cached.shape[0] != vocab_size:
            cached = np.zeros(vocab_size, np.float32)
            for tok, b in s.logit_bias:
                t = int(tok)
                if 0 <= t < vocab_size:
                    cached[t] = float(b)
            s._bias_row = cached  # constant for the sequence's lifetime
        rows[i] = cached
    return rows


def _batch_penalties(seqs: List[Sequence]) -> bool:
    """True when any sequence in the batch asked for a repetition/
    frequency/presence penalty (switches on the token-history transfer +
    on-device count table; no-op rows keep default parameters)."""
    return any(
        float(s.sampling.get("repetition_penalty", 1.0)) != 1.0
        or float(s.sampling.get("frequency_penalty", 0.0)) != 0.0
        or float(s.sampling.get("presence_penalty", 0.0)) != 0.0
        for s in seqs
    )


def _batch_logprobs(seqs: List[Sequence]) -> int:
    """Top-N logprob report size for the batch (-1 = nobody asked). One
    compiled variant serves the whole batch; the report width is bucketed
    to a fixed menu because it is a jit-static argument — arbitrary widths
    would let clients induce a fresh decode-loop compile per request.
    Per-sequence responses are trimmed to each request's own N."""
    want = [int(s.sampling.get("logprobs") or 0)
            for s in seqs if s.sampling.get("logprobs") is not None]
    if not want:
        return -1
    mx = max(want)
    for b in (0, 5, 20):
        if mx <= b:
            return b
    return 20


def _first_lp_entry(first_lp, seq: Sequence) -> Dict[str, Any]:
    """Prefill-first-token logprob record, trimmed to the sequence's own
    requested top-N (the compiled report width is the bucketed batch max)."""
    n = int(seq.sampling.get("logprobs") or 0)
    return {
        "logprob": first_lp[0],
        "top_ids": first_lp[1][:n],
        "top_logprobs": first_lp[2][:n],
    }


def _lp_entry(lp, i: int, j: int, seq: Sequence) -> Dict[str, Any]:
    """One emitted token's logprob record from the decode loop's stacked
    report, trimmed to the sequence's own requested top-N."""
    tok_lp, ids, vals = lp
    n = int(seq.sampling.get("logprobs") or 0)
    return {
        "logprob": float(tok_lp[i, j]),
        "top_ids": [int(t) for t in ids[i, j, :n]],
        "top_logprobs": [float(v) for v in vals[i, j, :n]],
    }
