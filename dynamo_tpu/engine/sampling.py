"""On-device token sampling: greedy / temperature / top-k / top-p.

Runs fused at the end of the jitted decode step (logits never leave the
device except as one sampled token id per sequence).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    """Per-sequence device-resident sampling state (arrays of shape [B])."""

    temperature: jax.Array  # f32; 0 → greedy
    top_k: jax.Array  # i32; 0 → disabled
    top_p: jax.Array  # f32; 1.0 → disabled
    key: jax.Array  # [B, 2] u32 PRNG keys

    @classmethod
    def make(cls, temperature, top_k, top_p, seeds) -> "SamplingParams":
        return cls(
            temperature=jnp.asarray(temperature, jnp.float32),
            top_k=jnp.asarray(top_k, jnp.int32),
            top_p=jnp.asarray(top_p, jnp.float32),
            key=jax.vmap(lambda s: jax.random.key_data(jax.random.PRNGKey(s)))(
                jnp.asarray(seeds, jnp.uint32)
            ),
        )


def sample(logits: jax.Array, params: SamplingParams, step: jax.Array) -> jax.Array:
    """logits [B, V] f32 → token ids [B] i32. `step` folds the decode step
    index into each sequence's key so repeated calls draw fresh samples."""
    B, V = logits.shape

    def one(logit, temp, top_k, top_p, key_data):
        key = jax.random.wrap_key_data(key_data, impl="threefry2x32")
        key = jax.random.fold_in(key, step)

        # top-k filter
        def apply_top_k(l):
            kth = jnp.sort(l)[V - jnp.clip(top_k, 1, V)]
            return jnp.where(l < kth, -jnp.inf, l)

        logit = jax.lax.cond(top_k > 0, apply_top_k, lambda l: l, logit)

        # top-p (nucleus) filter
        def apply_top_p(l):
            sorted_l = jnp.sort(l)[::-1]
            probs = jax.nn.softmax(sorted_l)
            cum = jnp.cumsum(probs)
            # keep tokens until cumulative prob exceeds top_p (always >= 1 tok)
            cutoff_idx = jnp.sum(cum < top_p)
            cutoff = sorted_l[jnp.clip(cutoff_idx, 0, V - 1)]
            return jnp.where(l < cutoff, -jnp.inf, l)

        logit = jax.lax.cond(top_p < 1.0, apply_top_p, lambda l: l, logit)

        greedy = jnp.argmax(logit).astype(jnp.int32)
        scaled = logit / jnp.maximum(temp, 1e-6)
        sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
        return jnp.where(temp <= 0.0, greedy, sampled)

    return jax.vmap(one)(logits, params.temperature, params.top_k, params.top_p, params.key)
