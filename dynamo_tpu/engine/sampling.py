"""On-device token sampling: greedy / temperature / top-k / top-p.

Runs fused at the end of the jitted decode step (logits never leave the
device except as one sampled token id per sequence).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    """Per-sequence device-resident sampling state (arrays of shape [B])."""

    temperature: jax.Array  # f32; 0 → greedy
    top_k: jax.Array  # i32; 0 → disabled
    top_p: jax.Array  # f32; 1.0 → disabled
    key: jax.Array  # [B, 2] u32 PRNG keys
    rep_penalty: jax.Array  # f32; 1.0 → disabled (HF-style multiplicative)
    freq_penalty: jax.Array  # f32; 0.0 → disabled (count-scaled subtract)
    presence_penalty: jax.Array  # f32; 0.0 → disabled (flat subtract)

    @classmethod
    def make(
        cls, temperature, top_k, top_p, seeds,
        rep_penalty=None, freq_penalty=None, presence_penalty=None,
    ) -> "SamplingParams":
        n = len(temperature)
        return cls(
            temperature=jnp.asarray(temperature, jnp.float32),
            top_k=jnp.asarray(top_k, jnp.int32),
            top_p=jnp.asarray(top_p, jnp.float32),
            key=jax.vmap(lambda s: jax.random.key_data(jax.random.PRNGKey(s)))(
                jnp.asarray(seeds, jnp.uint32)
            ),
            rep_penalty=jnp.asarray(
                [1.0] * n if rep_penalty is None else rep_penalty, jnp.float32
            ),
            freq_penalty=jnp.asarray(
                [0.0] * n if freq_penalty is None else freq_penalty, jnp.float32
            ),
            presence_penalty=jnp.asarray(
                [0.0] * n if presence_penalty is None else presence_penalty,
                jnp.float32,
            ),
        )


def apply_penalties(
    logits: jax.Array,
    counts_all: jax.Array,
    counts_out: jax.Array,
    params: SamplingParams,
) -> jax.Array:
    """Repetition / frequency / presence penalties over raw logits
    (reference sampling mapping, lib/llm/src/protocols/openai/).

    Two count tables [B, V] f32, matching the de-facto split (HF vs
    OpenAI/vLLM semantics):
    - `counts_all` (prompt + generated) drives HF-style repetition: seen
      tokens' positive logits are divided by the penalty, negative
      multiplied — pushes uniformly away from any reuse;
    - `counts_out` (GENERATED ONLY) drives the OpenAI pair: frequency
      subtracts penalty * count, presence subtracts the penalty once for
      any generated token. Prompt content must not pre-penalize the first
      generated token.
    All-default params make this an exact no-op, so one compiled path
    serves penalized and unpenalized batches."""
    seen_all = counts_all > 0.0
    rp = params.rep_penalty[:, None]
    logits = jnp.where(
        seen_all, jnp.where(logits > 0, logits / rp, logits * rp), logits
    )
    logits = logits - params.freq_penalty[:, None] * counts_out
    logits = logits - params.presence_penalty[:, None] * (counts_out > 0.0)
    return logits


# Sampling truncates to the top MAX_CANDIDATES logits first (one lax.top_k,
# no full-vocab sorts — a full 128k sort per sequence costs ~ms on TPU and
# dominated the decode step). Probability mass beyond the top-64 of a
# trained LM is negligible; top_k requests above this cap are clamped.
MAX_CANDIDATES = 64


def _filtered_scaled(logits: jax.Array, params: SamplingParams):
    """Shared filter pipeline: top-K truncate, apply top-k/top-p masks,
    temperature-scale. Returns (idx [B,K] token ids desc, scaled [B,K])."""
    B, V = logits.shape
    K = min(MAX_CANDIDATES, V)
    vals, idx = jax.lax.top_k(logits, K)  # [B, K] descending

    j = jnp.arange(K)
    # top-k filter (0 → disabled, clamped to K candidates)
    k_eff = jnp.where(params.top_k > 0, jnp.minimum(params.top_k, K), K)
    vals = jnp.where(j[None, :] < k_eff[:, None], vals, -jnp.inf)
    # top-p (nucleus): keep token j while cumulative prob before j < top_p
    # (always keeps j=0)
    probs = jax.nn.softmax(vals, axis=-1)
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    vals = jnp.where(cum_before < params.top_p[:, None], vals, -jnp.inf)

    scaled = vals / jnp.maximum(params.temperature, 1e-6)[:, None]
    return idx, scaled


def filtered_probs(logits: jax.Array, params: SamplingParams):
    """The EXACT distribution `sample` draws from, as explicit
    probabilities: (idx [B,K] candidate token ids, probs [B,K]). Greedy
    rows (temperature <= 0) come back one-hot on idx[:, 0]. This is what
    speculative decoding's accept/resample math consumes for both the
    draft (q) and target (p) models."""
    idx, scaled = _filtered_scaled(logits, params)
    probs = jax.nn.softmax(scaled, axis=-1)
    greedy = jnp.zeros_like(probs).at[:, 0].set(1.0)
    probs = jnp.where((params.temperature <= 0.0)[:, None], greedy, probs)
    return idx, probs


def top_logprobs(logits: jax.Array, sampled: jax.Array, k: int):
    """Logprob report for the OpenAI `logprobs` surface, computed from the
    RAW model distribution (pre temperature/top-k/top-p — what clients use
    logprobs for: inspecting the model, not the sampler). Returns
    (tok_lp [B], top_ids [B, k], top_lps [B, k]); k=0 → empty top arrays."""
    lp = jax.nn.log_softmax(logits, axis=-1)
    tok_lp = jnp.take_along_axis(lp, sampled[:, None], axis=1)[:, 0]
    if k <= 0:
        B = logits.shape[0]
        return tok_lp, jnp.zeros((B, 0), jnp.int32), jnp.zeros((B, 0), jnp.float32)
    vals, ids = jax.lax.top_k(lp, k)
    return tok_lp, ids.astype(jnp.int32), vals


def sample(
    logits: jax.Array, params: SamplingParams, step: jax.Array, mask=None,
    bias=None,
) -> jax.Array:
    """logits [B, V] f32 → token ids [B] i32. `step` folds the decode step
    index into each sequence's key so repeated calls draw fresh samples.
    `mask` [B, V] bool (guided decoding) bans False tokens outright; the
    caller guarantees every live row keeps at least one allowed token.
    `bias` [B, V] f32 (OpenAI logit_bias) adds to the logits before
    filtering — ±100 effectively forces/bans per the OpenAI contract."""
    if bias is not None:
        logits = logits + bias
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    idx, scaled = _filtered_scaled(logits, params)

    def draw(key_data, row):
        key = jax.random.wrap_key_data(key_data, impl="threefry2x32")
        return jax.random.categorical(jax.random.fold_in(key, step), row)

    choice = jax.vmap(draw)(params.key, scaled).astype(jnp.int32)
    pick = jnp.where(params.temperature <= 0.0, 0, choice)  # idx 0 = argmax
    return jnp.take_along_axis(idx, pick[:, None], axis=1)[:, 0].astype(jnp.int32)
