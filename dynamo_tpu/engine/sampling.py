"""On-device token sampling: greedy / temperature / top-k / top-p.

Runs fused at the end of the jitted decode step (logits never leave the
device except as one sampled token id per sequence).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class SamplingParams(NamedTuple):
    """Per-sequence device-resident sampling state (arrays of shape [B])."""

    temperature: jax.Array  # f32; 0 → greedy
    top_k: jax.Array  # i32; 0 → disabled
    top_p: jax.Array  # f32; 1.0 → disabled
    key: jax.Array  # [B, 2] u32 PRNG keys

    @classmethod
    def make(cls, temperature, top_k, top_p, seeds) -> "SamplingParams":
        return cls(
            temperature=jnp.asarray(temperature, jnp.float32),
            top_k=jnp.asarray(top_k, jnp.int32),
            top_p=jnp.asarray(top_p, jnp.float32),
            key=jax.vmap(lambda s: jax.random.key_data(jax.random.PRNGKey(s)))(
                jnp.asarray(seeds, jnp.uint32)
            ),
        )


# Sampling truncates to the top MAX_CANDIDATES logits first (one lax.top_k,
# no full-vocab sorts — a full 128k sort per sequence costs ~ms on TPU and
# dominated the decode step). Probability mass beyond the top-64 of a
# trained LM is negligible; top_k requests above this cap are clamped.
MAX_CANDIDATES = 64


def _filtered_scaled(logits: jax.Array, params: SamplingParams):
    """Shared filter pipeline: top-K truncate, apply top-k/top-p masks,
    temperature-scale. Returns (idx [B,K] token ids desc, scaled [B,K])."""
    B, V = logits.shape
    K = min(MAX_CANDIDATES, V)
    vals, idx = jax.lax.top_k(logits, K)  # [B, K] descending

    j = jnp.arange(K)
    # top-k filter (0 → disabled, clamped to K candidates)
    k_eff = jnp.where(params.top_k > 0, jnp.minimum(params.top_k, K), K)
    vals = jnp.where(j[None, :] < k_eff[:, None], vals, -jnp.inf)
    # top-p (nucleus): keep token j while cumulative prob before j < top_p
    # (always keeps j=0)
    probs = jax.nn.softmax(vals, axis=-1)
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    vals = jnp.where(cum_before < params.top_p[:, None], vals, -jnp.inf)

    scaled = vals / jnp.maximum(params.temperature, 1e-6)[:, None]
    return idx, scaled


def filtered_probs(logits: jax.Array, params: SamplingParams):
    """The EXACT distribution `sample` draws from, as explicit
    probabilities: (idx [B,K] candidate token ids, probs [B,K]). Greedy
    rows (temperature <= 0) come back one-hot on idx[:, 0]. This is what
    speculative decoding's accept/resample math consumes for both the
    draft (q) and target (p) models."""
    idx, scaled = _filtered_scaled(logits, params)
    probs = jax.nn.softmax(scaled, axis=-1)
    greedy = jnp.zeros_like(probs).at[:, 0].set(1.0)
    probs = jnp.where((params.temperature <= 0.0)[:, None], greedy, probs)
    return idx, probs


def sample(logits: jax.Array, params: SamplingParams, step: jax.Array) -> jax.Array:
    """logits [B, V] f32 → token ids [B] i32. `step` folds the decode step
    index into each sequence's key so repeated calls draw fresh samples."""
    idx, scaled = _filtered_scaled(logits, params)

    def draw(key_data, row):
        key = jax.random.wrap_key_data(key_data, impl="threefry2x32")
        return jax.random.categorical(jax.random.fold_in(key, step), row)

    choice = jax.vmap(draw)(params.key, scaled).astype(jnp.int32)
    pick = jnp.where(params.temperature <= 0.0, 0, choice)  # idx 0 = argmax
    return jnp.take_along_axis(idx, pick[:, None], axis=1)[:, 0].astype(jnp.int32)
